"""paddle.nn.functional parity (python/paddle/nn/functional/ in the
reference). All math routes through ops/kernels.py jnp kernels under the
eager autograd tape; the same kernels serve the static-graph executor."""
from __future__ import annotations

import numpy as np

from ...core import random as _random
from ...core.dtypes import convert_dtype
from ...core.tensor import Tensor, apply_op
from ...ops import kernels as K
from ...tensor.ops import _op, _t


def _jnp():
    import jax.numpy as jnp

    return jnp


# ----------------------------- activations -----------------------------

def relu(x, name=None):
    return _op("relu", K.relu, x)


def relu6(x, name=None):
    return _op("relu6", K.relu6, x)


def relu_(x):
    out = relu(x)
    x._data = out._data
    return out


def sigmoid(x, name=None):
    return _op("sigmoid", K.sigmoid, x)


def tanh(x, name=None):
    return _op("tanh", K.tanh, x)


def gelu(x, approximate=False, name=None):
    return _op("gelu", lambda a: K.gelu(a, approximate), x)


def silu(x, name=None):
    return _op("silu", K.silu, x)


def swish(x, name=None):
    return _op("swish", K.swish, x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return _op("leaky_relu", lambda a: K.leaky_relu(a, negative_slope), x)


def elu(x, alpha=1.0, name=None):
    return _op("elu", lambda a: K.elu(a, alpha), x)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return _op("selu", lambda a: K.selu(a, scale, alpha), x)


def prelu(x, weight, data_format="NCHW", name=None):
    def fn(a, w):
        jnp = _jnp()
        if w.size == 1:
            return jnp.where(a >= 0, a, w.reshape(()) * a)
        shape = [1] * a.ndim
        axis = 1 if data_format == "NCHW" else a.ndim - 1
        shape[axis] = -1
        return jnp.where(a >= 0, a, w.reshape(shape) * a)

    return _op("prelu", fn, x, weight)


def hardswish(x, name=None):
    return _op("hardswish", K.hardswish, x)


def hardsigmoid(x, slope=1.0 / 6.0, offset=0.5, name=None):
    return _op("hardsigmoid", lambda a: K.hardsigmoid(a, slope, offset), x)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return _op("hardtanh", lambda a: K.hardtanh(a, min, max), x)


def hardshrink(x, threshold=0.5, name=None):
    return _op("hardshrink",
               lambda a: _jnp().where(_jnp().abs(a) > threshold, a, 0.0), x)


def softshrink(x, threshold=0.5, name=None):
    def fn(a):
        jnp = _jnp()
        return jnp.where(a > threshold, a - threshold,
                         jnp.where(a < -threshold, a + threshold, 0.0))
    return _op("softshrink", fn, x)


def tanhshrink(x, name=None):
    return _op("tanhshrink", lambda a: a - _jnp().tanh(a), x)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return _op("softplus", lambda a: K.softplus(a, beta, threshold), x)


def softsign(x, name=None):
    return _op("softsign", K.softsign, x)


def mish(x, name=None):
    return _op("mish", K.mish, x)


def maxout(x, groups, axis=1, name=None):
    def fn(a):
        jnp = _jnp()
        shape = list(a.shape)
        c = shape[axis]
        new = shape[:axis] + [c // groups, groups] + shape[axis + 1:]
        return a.reshape(new).max(axis=axis + 1)
    return _op("maxout", fn, x)


def softmax(x, axis=-1, dtype=None, name=None):
    dt = convert_dtype(dtype)

    def fn(a):
        if dt is not None:
            a = a.astype(dt)
        return K.softmax(a, axis)

    return _op("softmax", fn, x)


def log_softmax(x, axis=-1, dtype=None, name=None):
    return _op("log_softmax", lambda a: K.log_softmax(a, axis), x)


def log_sigmoid(x, name=None):
    import jax

    return _op("log_sigmoid", lambda a: jax.nn.log_sigmoid(a), x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    import jax

    key = _random.next_key()

    def fn(a):
        jnp = _jnp()
        g = -jnp.log(-jnp.log(
            jax.random.uniform(key, a.shape, dtype=jnp.float32,
                               minval=1e-20, maxval=1.0)))
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = y.argmax(axis=axis, keepdims=True)
            onehot = jnp.zeros_like(y).at[
                tuple(jnp.meshgrid(*[jnp.arange(s) for i, s in
                                     enumerate(y.shape) if i != axis % y.ndim],
                                   indexing="ij"))
            ].set(1.0) if False else jax.nn.one_hot(
                y.argmax(axis=axis), y.shape[axis], axis=axis, dtype=y.dtype)
            y = onehot + y - jax.lax.stop_gradient(y)
        return y

    return _op("gumbel_softmax", fn, x)


# ----------------------------- linear / conv -----------------------------

def linear(x, weight, bias=None, name=None):
    if bias is None:
        return _op("linear", lambda a, w: K.linear(a, w), x, weight)
    return _op("linear", K.linear, x, weight, bias)


def linear_int8(x, weight_q, scale, bias=None, name=None):
    """Scaled int8 matmul: `weight_q` int8 [in, out] + per-output-
    channel f32 `scale` (ops.quant.quantize_int8_weight's layout),
    fp32 accumulate, result in x's dtype — the serving engines'
    quantize="int8" weight path (nn.Linear.quantize_int8)."""
    from ...ops import quant as Qm

    if bias is None:
        return _op("linear_int8",
                   lambda a, w, s: Qm.int8_matmul(a, w, s),
                   x, weight_q, scale)
    return _op("linear_int8",
               lambda a, w, s, b: Qm.int8_matmul(a, w, s, b),
               x, weight_q, scale, bias)


def embedding_int8(x, weight_q, scale, dtype, name=None):
    """Embedding lookup over an int8 table with per-output-channel
    scales (nn.Embedding.quantize_int8's storage): gather + scale, no
    dense dequantized copy."""
    from ...ops import quant as Qm

    return _op("embedding_int8",
               lambda ids, w, s: Qm.int8_gather(ids, w, s, dtype),
               x, weight_q, scale)


def lora_delta(x, A, B, ids, name=None):
    """Per-row low-rank adapter delta `(x @ A[ids]) @ B[ids]` over
    stacked [n_adapters, ...] banks — the batched gathered matmul the
    multi-tenant serving pool fuses into its decode step (see
    ops.quant.lora_delta; ids row 0 = base model, zero delta)."""
    from ...ops import quant as Qm

    return _op("lora_delta",
               lambda a, wa, wb, i: Qm.lora_delta(a, wa, wb, i),
               x, A, B, ids)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(data_format)
    nhwc = data_format == "NHWC"

    def fn(a, w, *b):
        jnp = _jnp()
        if nhwc:
            a = jnp.transpose(a, (0, 3, 1, 2))
        out = K.conv2d(a, w, stride, padding, dilation, groups)
        if b:
            out = out + b[0].reshape(1, -1, 1, 1)
        if nhwc:
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    args = (x, weight) if bias is None else (x, weight, bias)
    return _op("conv2d", fn, *args)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCHW", output_size=None, name=None):
    def fn(a, w, *b):
        out = K.conv2d_transpose(a, w, stride, padding, output_padding,
                                 dilation, groups)
        if b:
            out = out + b[0].reshape(1, -1, 1, 1)
        return out

    args = (x, weight) if bias is None else (x, weight, bias)
    return _op("conv2d_transpose", fn, *args)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    def fn(a, w, *b):
        jnp = _jnp()
        a4 = a[:, :, None, :]
        w4 = w[:, :, None, :]
        s = stride if isinstance(stride, int) else stride[0]
        d = dilation if isinstance(dilation, int) else dilation[0]
        p = padding if isinstance(padding, (int, str)) else padding[0]
        if isinstance(p, int):
            pad = [(0, 0), (p, p)]
        else:
            pad = p
        out = K.conv2d(a4, w4, (1, s), pad if isinstance(pad, list) else pad,
                       (1, d), groups)
        out = out[:, :, 0, :]
        if b:
            out = out + b[0].reshape(1, -1, 1)
        return out

    args = (x, weight) if bias is None else (x, weight, bias)
    return _op("conv1d", fn, *args)


# ----------------------------- pooling -----------------------------

def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    return _op("max_pool2d",
               lambda a: K.max_pool2d(a, kernel_size, stride, padding,
                                      ceil_mode), x)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _op("avg_pool2d",
               lambda a: K.avg_pool2d(a, kernel_size, stride, padding,
                                      ceil_mode, exclusive), x)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _op("adaptive_avg_pool2d",
               lambda a: K.adaptive_avg_pool2d(a, output_size), x)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _op("adaptive_max_pool2d",
               lambda a: K.adaptive_max_pool2d(a, output_size), x)


def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               name=None):
    def fn(a):
        a4 = a[:, :, None, :]
        k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
        s = stride if stride is None or isinstance(stride, int) else stride[0]
        p = padding if isinstance(padding, int) else padding[0]
        out = K.max_pool2d(a4, (1, k), (1, s if s else k), (0, p), ceil_mode)
        return out[:, :, 0, :]
    return _op("max_pool1d", fn, x)


def avg_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, name=None):
    def fn(a):
        a4 = a[:, :, None, :]
        k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
        s = stride if stride is None or isinstance(stride, int) else stride[0]
        p = padding if isinstance(padding, int) else padding[0]
        out = K.avg_pool2d(a4, (1, k), (1, s if s else k), (0, p), ceil_mode,
                           exclusive)
        return out[:, :, 0, :]
    return _op("avg_pool1d", fn, x)


# ----------------------------- normalization -----------------------------

def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    """Functional BN. In training mode also updates running stats in-place
    (reference: operators/batch_norm_op.cc semantics)."""
    jnp = _jnp()
    c = x.shape[1] if data_format.startswith("NC") else x.shape[-1]
    w = weight if weight is not None else Tensor._wrap(jnp.ones((c,),
                                                               x._data.dtype))
    b = bias if bias is not None else Tensor._wrap(jnp.zeros((c,),
                                                             x._data.dtype))
    if training and not use_global_stats:
        def fn(a, g, bb, rm, rv):
            y, _, _, _, _ = K.batch_norm_train(a, g, bb, rm, rv, momentum,
                                               epsilon, data_format)
            return y

        out = _op("batch_norm", fn, x, w, b, running_mean.detach(),
                  running_var.detach())
        # update running stats outside the tape
        _, nm, nv, _, _ = K.batch_norm_train(
            x._data, w._data, b._data, running_mean._data, running_var._data,
            momentum, epsilon, data_format)
        running_mean._data = nm
        running_var._data = nv
        return out
    return _op("batch_norm_infer",
               lambda a, g, bb, rm, rv: K.batch_norm_infer(
                   a, g, bb, rm, rv, epsilon, data_format),
               x, w, b, running_mean.detach(), running_var.detach())


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    begin = -len(normalized_shape)

    if weight is None and bias is None:
        return _op("layer_norm",
                   lambda a: K.layer_norm(a, None, None, epsilon, begin), x)
    if bias is None:
        return _op("layer_norm",
                   lambda a, w: K.layer_norm(a, w, None, epsilon, begin),
                   x, weight)
    if weight is None:
        return _op("layer_norm",
                   lambda a, b: K.layer_norm(a, None, b, epsilon, begin),
                   x, bias)
    return _op("layer_norm",
               lambda a, w, b: K.layer_norm(a, w, b, epsilon, begin),
               x, weight, bias)


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
               data_format="NCHW", name=None):
    args = [x] + [a for a in (weight, bias) if a is not None]
    has_w, has_b = weight is not None, bias is not None

    def fn(a, *wb):
        w = wb[0] if has_w else None
        b = wb[1] if (has_w and has_b) else (wb[0] if has_b else None)
        return K.group_norm(a, num_groups, w, b, epsilon)

    return _op("group_norm", fn, *args)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    args = [x] + [a for a in (weight, bias) if a is not None]
    has_w, has_b = weight is not None, bias is not None

    def fn(a, *wb):
        w = wb[0] if has_w else None
        b = wb[1] if (has_w and has_b) else (wb[0] if has_b else None)
        return K.instance_norm(a, w, b, eps)

    return _op("instance_norm", fn, *args)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(a):
        jnp = _jnp()
        n = K.norm(a, p, axis, True)
        return a / jnp.maximum(n, epsilon)
    return _op("normalize", fn, x)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def fn(a):
        jnp = _jnp()
        sq = a * a
        half = size // 2
        pads = [(0, 0), (half, size - 1 - half), (0, 0), (0, 0)]
        sq = jnp.pad(sq, pads)
        acc = jnp.zeros_like(a)
        for i in range(size):
            acc = acc + sq[:, i:i + a.shape[1]]
        return a / (k + alpha * acc) ** beta
    return _op("lrn", fn, x)


# ----------------------------- dropout / embedding -----------------------

def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return _op("dropout", lambda a: a * (1.0 - p), x)
        return _t(x)
    key = _random.next_key()
    if axis is not None:
        import jax

        def fn(a):
            jnp = _jnp()
            keep = 1.0 - p
            shape = [a.shape[i] if i in (
                axis if isinstance(axis, (list, tuple)) else [axis])
                else 1 for i in range(a.ndim)]
            mask = jax.random.bernoulli(key, jnp.float32(keep), tuple(shape))
            scale_v = (1.0 / keep) if mode == "upscale_in_train" else 1.0
            return jnp.where(mask, a * scale_v, 0.0).astype(a.dtype)
        return _op("dropout", fn, x)
    return _op("dropout", lambda a: K.dropout(a, key, p, training, mode), x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    return dropout(x, p, axis=[0, 1], training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return _t(x)
    import jax

    key = _random.next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def fn(a):
        jnp = _jnp()
        keep = 1.0 - p
        mask = jax.random.bernoulli(key, jnp.float32(keep), a.shape)
        a_coef = (keep + alpha_p ** 2 * keep * (1 - keep)) ** -0.5
        b_coef = -a_coef * alpha_p * (1 - keep)
        return (a_coef * jnp.where(mask, a, alpha_p) + b_coef).astype(a.dtype)

    return _op("alpha_dropout", fn, x)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    # the SelectedRows cotangent can only terminate at a LEAF weight; a
    # derived weight (amp cast, scale, slice) needs a dense cotangent to
    # flow upstream, so fall back to the dense path (the reference raises
    # for non-parameter sparse lookups; densifying is strictly safer)
    if sparse and weight.is_leaf:
        return _sparse_embedding(x, weight, padding_idx)
    return _op("embedding",
               lambda ids, w: K.embedding(ids, w, padding_idx), x, weight)


def _sparse_embedding(x, weight, padding_idx):
    """is_sparse=True lookup (lookup_table_op.cc grad with SelectedRows):
    the tape records a custom vjp whose weight cotangent is
    SelectedRows(rows=ids, values=output grads) — no [vocab, dim] dense
    gradient is ever built."""
    import jax.numpy as jnp

    from ...core.tensor import apply_custom_vjp
    from ...sparse import SelectedRows

    ids_raw = x._data
    w_raw = weight._data
    out = K.embedding(ids_raw, w_raw, padding_idx)
    V = int(w_raw.shape[0])

    def vjp(ct):
        flat_ids = ids_raw.reshape(-1)
        vals = ct.reshape((-1,) + tuple(w_raw.shape[1:]))
        if padding_idx is not None and padding_idx >= 0:
            # padding rows receive no gradient: route them out of range so
            # merge()/to_dense() (mode='drop') discard them
            flat_ids = jnp.where(flat_ids == padding_idx, V, flat_ids)
        return (None, SelectedRows(flat_ids, vals, V))

    return apply_custom_vjp(
        "embedding_sparse_grad", out,
        [(x, False), (weight, not weight.stop_gradient)], vjp)


def one_hot(x, num_classes, name=None):
    return _op("one_hot", lambda a: K.one_hot(a, num_classes), x)


# ----------------------------- losses -----------------------------

def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, name=None):
    args = (input, label) if weight is None else (input, label, weight)

    def fn(logits, lbl, *w):
        return K.cross_entropy_loss(
            logits, lbl, soft_label, reduction, ignore_index,
            w[0] if w else None, axis, use_softmax)

    lt = _t(label)
    if not soft_label:
        lt = lt.detach()
    return apply_op("cross_entropy", fn,
                    [_t(input), lt] + ([_t(weight)] if weight is not None
                                       else []))


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    out = apply_op(
        "softmax_with_cross_entropy",
        lambda lg, lb: K.softmax_with_cross_entropy(lg, lb, soft_label, axis,
                                                    ignore_index),
        [_t(logits), _t(label).detach() if not soft_label else _t(label)])
    if return_softmax:
        return out, softmax(logits, axis)
    return out


def mse_loss(input, label, reduction="mean", name=None):
    return _reduce_loss(_op("mse_loss", K.mse_loss, input, label), reduction)


def l1_loss(input, label, reduction="mean", name=None):
    return _reduce_loss(_op("l1_loss", K.l1_loss, input, label), reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    return _reduce_loss(
        _op("smooth_l1", lambda a, b: K.smooth_l1(a, b, delta), input, label),
        reduction)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    args = (input, _t(label).detach()) if weight is None else (
        input, _t(label).detach(), weight)
    out = _op("nll_loss",
              lambda lp, lb, *w: K.nll_loss(lp, lb, w[0] if w else None,
                                            ignore_index), *args)
    return _reduce_loss(out, reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    out = _op("bce_loss", K.bce_loss, input, label)
    if weight is not None:
        out = out * weight
    return _reduce_loss(out, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    if pos_weight is not None:
        out = _op("bce_logits",
                  lambda a, b, pw: K.bce_with_logits(a, b, pw), logit, label,
                  pos_weight)
    else:
        out = _op("bce_logits", K.bce_with_logits, logit, label)
    if weight is not None:
        out = out * weight
    return _reduce_loss(out, reduction)


def kl_div(input, label, reduction="mean", name=None):
    out = _op("kl_div", K.kl_div, input, label)
    if reduction == "batchmean":
        return out.sum() / out.shape[0]
    return _reduce_loss(out, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    out = _op("margin_ranking",
              lambda a, b, lbl: _jnp().maximum(
                  0.0, -lbl * (a - b) + margin), input, other, label)
    return _reduce_loss(out, reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):
    out = _op("hinge_embedding",
              lambda a, lbl: _jnp().where(
                  lbl == 1.0, a, _jnp().maximum(0.0, margin - a)),
              input, label)
    return _reduce_loss(out, reduction)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def fn(a, b):
        jnp = _jnp()
        num = (a * b).sum(axis=axis)
        den = jnp.sqrt((a * a).sum(axis=axis)) * jnp.sqrt(
            (b * b).sum(axis=axis))
        return num / jnp.maximum(den, eps)
    return _op("cosine_similarity", fn, x1, x2)


def square_error_cost(input, label):
    return _op("square_error_cost", K.mse_loss, input, label)


def log_loss(input, label, epsilon=1e-4, name=None):
    def fn(a, lbl):
        jnp = _jnp()
        return -lbl * jnp.log(a + epsilon) - (1.0 - lbl) * jnp.log(
            1.0 - a + epsilon)
    return _op("log_loss", fn, input, label)


def ctc_loss(log_probs, labels, input_lengths, label_lengths,
             blank=0, reduction="mean", norm_by_times=False):
    """paddle.nn.functional.ctc_loss parity (warpctc capability):
    log_probs [T, B, C] raw logits (log_softmax applied internally, as
    warpctc does its own normalization), labels [B, L] padded."""
    import jax

    from ...ops import sequence_losses as SL

    def fn(lp_raw):
        lp = jax.nn.log_softmax(lp_raw.astype("float32"), axis=-1)
        loss = SL.ctc_loss(lp, _t(labels)._data, _t(input_lengths)._data,
                           _t(label_lengths)._data, blank=blank)
        if norm_by_times:
            import jax.numpy as jnp

            loss = loss / jnp.maximum(
                jnp.reshape(_t(input_lengths)._data, (-1,)).astype(
                    loss.dtype), 1.0)
        return loss

    out = _op("ctc_loss", fn, log_probs)
    if reduction == "mean":
        return out.mean()
    if reduction == "sum":
        return out.sum()
    return out


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum"):
    def fn(lg, lb):
        import jax

        jnp = _jnp()
        p = jax.nn.sigmoid(lg)
        ce = K.bce_with_logits(lg, lb)
        p_t = p * lb + (1 - p) * (1 - lb)
        a_t = alpha * lb + (1 - alpha) * (1 - lb)
        return a_t * ((1 - p_t) ** gamma) * ce
    out = _op("sigmoid_focal_loss", fn, logit, label)
    if normalizer is not None:
        out = out / normalizer
    return _reduce_loss(out, reduction)


def _reduce_loss(out, reduction):
    if reduction == "mean":
        return out.mean()
    if reduction == "sum":
        return out.sum()
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    if prior_dist is not None:
        return _op("label_smooth",
                   lambda lbl, p: K.label_smooth(lbl, epsilon, p), label,
                   prior_dist)
    return _op("label_smooth", lambda lbl: K.label_smooth(lbl, epsilon),
               label)


# ----------------------------- vision -----------------------------

def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    t_ = _t(x)
    spatial = t_.ndim - 2
    if size is not None:
        if isinstance(size, Tensor):
            size = [int(s) for s in size.numpy()]
        out = [int(s) for s in (size if isinstance(size, (list, tuple))
                                else [size] * spatial)]
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else \
            (scale_factor,) * spatial
        out = [int(d * s) for d, s in zip(t_.shape[2:], sf)]
    if mode == "nearest":
        if spatial != 2:
            raise NotImplementedError("nearest interpolate is 2-D")
        return _op("interp_nearest",
                   lambda a: K.interpolate_nearest(a, tuple(out)), t_)
    if mode == "bilinear" or (mode == "linear" and spatial == 2):
        return _op("interp_bilinear",
                   lambda a: K.interpolate_bilinear(
                       a, tuple(out), align_corners, align_mode), t_)
    if mode in ("linear", "trilinear"):
        from ...fluid.lowering_batch3 import _linear_nd

        return _op("interp_linear",
                   lambda a: _linear_nd(a, out, align_corners,
                                        align_mode), t_)
    if mode == "bicubic":
        from ...fluid.lowering_batch3 import _cubic_nd

        return _op("interp_bicubic",
                   lambda a: _cubic_nd(a, out, align_corners).astype(
                       a.dtype), t_)
    raise NotImplementedError(f"interpolate mode {mode}")


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, name=None):
    return interpolate(x, size, scale_factor, mode, align_corners)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    def fn(a):
        jnp = _jnp()
        n, c, h, w = a.shape
        r = upscale_factor
        a = a.reshape(n, c // (r * r), r, r, h, w)
        a = jnp.transpose(a, (0, 1, 4, 2, 5, 3))
        return a.reshape(n, c // (r * r), h * r, w * r)
    return _op("pixel_shuffle", fn, x)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    import jax

    k = K._pair(kernel_sizes)
    s = K._pair(strides)
    p = K._pair(paddings)
    d = K._pair(dilations)

    def fn(a):
        jnp = _jnp()
        n, c, h, w = a.shape
        a_p = jnp.pad(a, [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])])
        oh = (h + 2 * p[0] - d[0] * (k[0] - 1) - 1) // s[0] + 1
        ow = (w + 2 * p[1] - d[1] * (k[1] - 1) - 1) // s[1] + 1
        cols = []
        for i in range(k[0]):
            for j in range(k[1]):
                patch = a_p[:, :, i * d[0]:i * d[0] + oh * s[0]:s[0],
                            j * d[1]:j * d[1] + ow * s[1]:s[1]]
                cols.append(patch)
        out = jnp.stack(cols, axis=2)  # n, c, k*k, oh, ow
        return out.reshape(n, c * k[0] * k[1], oh * ow)
    return _op("unfold", fn, x)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    def fn(a, g):
        jnp = _jnp()
        n, c, h, w = a.shape
        gx = (g[..., 0] + 1.0) * (w - 1) / 2.0 if align_corners else \
            ((g[..., 0] + 1.0) * w - 1.0) / 2.0
        gy = (g[..., 1] + 1.0) * (h - 1) / 2.0 if align_corners else \
            ((g[..., 1] + 1.0) * h - 1.0) / 2.0
        x0 = jnp.floor(gx).astype(jnp.int32)
        y0 = jnp.floor(gy).astype(jnp.int32)
        x1, y1 = x0 + 1, y0 + 1
        wx = gx - x0
        wy = gy - y0

        def sample(yy, xx):
            valid = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
            yy = jnp.clip(yy, 0, h - 1)
            xx = jnp.clip(xx, 0, w - 1)
            # a: n c h w; index per-batch
            bidx = jnp.arange(n).reshape(n, 1, 1)
            out = a[bidx, :, yy, xx]  # n, gh, gw, c
            return jnp.where(valid[..., None], out, 0.0)

        v00 = sample(y0, x0)
        v01 = sample(y0, x1)
        v10 = sample(y1, x0)
        v11 = sample(y1, x1)
        out = (v00 * ((1 - wx) * (1 - wy))[..., None]
               + v01 * (wx * (1 - wy))[..., None]
               + v10 * ((1 - wx) * wy)[..., None]
               + v11 * (wx * wy)[..., None])
        return jnp.transpose(out, (0, 3, 1, 2))
    return _op("grid_sample", fn, x, grid)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    def fn(th):
        jnp = _jnp()
        n, c, h, w = [int(v) for v in out_shape]
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, h)
            xs = jnp.linspace(-1.0, 1.0, w)
        else:
            ys = (jnp.arange(h) * 2 + 1) / h - 1
            xs = (jnp.arange(w) * 2 + 1) / w - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)  # h w 3
        return jnp.einsum("nij,hwj->nhwi", th, base)
    return _op("affine_grid", fn, theta)


# ----------------------------- padding / misc -----------------------------

def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A002
    pads = [int(p._data) if isinstance(p, Tensor) else int(p) for p in pad] \
        if isinstance(pad, (list, tuple)) else pad
    return _op("pad", lambda a: K.pad(a, pads, mode, value), x)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    def fn(a):
        jnp = _jnp()
        nt, c, h, w = a.shape
        n = nt // seg_num
        a = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate(
            [a[:, 1:, :fold], jnp.zeros_like(a[:, :1, :fold])], axis=1)
        right = jnp.concatenate(
            [jnp.zeros_like(a[:, :1, fold:2 * fold]),
             a[:, :-1, fold:2 * fold]], axis=1)
        rest = a[:, :, 2 * fold:]
        out = jnp.concatenate([left, right, rest], axis=2)
        return out.reshape(nt, c, h, w)
    return _op("temporal_shift", fn, x)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def fn(a, p, lbl):
        jnp = _jnp()
        sim = jnp.matmul(a, p.T)
        lbl = lbl.reshape(-1, 1)
        tgt = (lbl == lbl.T).astype(a.dtype)
        tgt = tgt / tgt.sum(axis=1, keepdims=True)
        logp = jax_log_softmax(sim)
        ce = -(tgt * logp).sum(axis=1).mean()
        reg = (a * a).sum(axis=1).mean() + (p * p).sum(axis=1).mean()
        return ce + l2_reg * reg * 0.25
    import jax
    jax_log_softmax = jax.nn.log_softmax
    return _op("npair_loss", fn, anchor, positive, _t(labels).detach())


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None,
                                 layout="BHSD", segment_ids=None):
    """TPU-first attention entry. Uses the pallas flash kernel on TPU when
    shapes allow; falls back to the XLA softmax composition elsewhere.
    layout="BSHD" takes [batch, seq, heads, dim] operands and skips the
    head transposes entirely on the short-sequence XLA path. Attention
    dropout (the reference MultiHeadAttention's dropout on the softmax
    output) runs IN-KERNEL on the flash path and via jax.random on the
    XLA paths. segment_ids ([B, S] int, packed monotone rows from
    core/lod.pack_padded) restrict attention to same-segment tokens —
    the LoD-packed varlen path; the dispatcher routes it to the
    segment-masked flash kernel with block-level early-out on TPU and
    to a densely-masked reference composition elsewhere."""
    from ...ops import attention as A

    if layout not in ("BHSD", "BSHD"):
        raise ValueError(f"sdpa layout must be 'BHSD' or 'BSHD', got "
                         f"{layout!r}")
    args = [query, key, value]
    if attn_mask is not None:
        args.append(attn_mask)
    segs = None
    if segment_ids is not None:
        # ids are metadata, not a differentiable operand: keep them out
        # of the tape
        segs = _t(segment_ids).detach()._data
    sdpa_fn = A.sdpa_bshd if layout == "BSHD" else A.sdpa
    p = float(dropout_p or 0.0) if training else 0.0
    key_ = _random.next_key() if p else None

    def fn(q, k, v, *m):
        return sdpa_fn(q, k, v, m[0] if m else None, is_causal,
                       dropout_p=p, dropout_key=key_, segment_ids=segs)

    return _op("sdpa", fn, *args)
