"""paddle.nn parity surface (python/paddle/nn/__init__.py in the reference).
"""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer.layers import Layer, ParamAttr, Parameter  # noqa: F401
from .layer.common import (  # noqa: F401
    AdaptiveAvgPool2D, AdaptiveMaxPool2D, AlphaDropout, AvgPool1D, AvgPool2D,
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, BCELoss,
    BCEWithLogitsLoss, Bilinear, Conv1D, Conv2D, Conv2DTranspose,
    CosineSimilarity, CrossEntropyLoss, Dropout, Dropout2D, ELU, Embedding,
    Flatten, GELU, GroupNorm, Hardshrink, Hardsigmoid, Hardswish, Hardtanh,
    Identity, InstanceNorm2D, KLDivLoss, L1Loss, LayerDict, LayerList,
    LayerNorm, LeakyReLU, Linear, LocalResponseNorm, LogSigmoid, LogSoftmax,
    MarginRankingLoss, Maxout, MaxPool1D, MaxPool2D, Mish, MSELoss, NLLLoss,
    Pad2D, ParameterList, PixelShuffle, PReLU, ReLU, ReLU6, RMSNorm, SELU,
    Sequential, Sigmoid, Silu, SmoothL1Loss, Softmax, Softplus, Softshrink,
    Softsign, Swish, SyncBatchNorm, Tanh, Tanhshrink, Unfold, Upsample,
    UpsamplingBilinear2D, UpsamplingNearest2D)
from .layer.moe import MoELayer  # noqa: F401
from .layer.transformer import (  # noqa: F401
    MultiHeadAttention, Transformer, TransformerDecoder,
    TransformerDecoderLayer, TransformerEncoder, TransformerEncoderLayer)
from .layer.rnn import (  # noqa: F401
    GRU, GRUCell, LSTM, LSTMCell, RNN, RNNCellBase, SimpleRNN, SimpleRNNCell)


_CLIP_GLOBAL_JIT = None


def _clip_global_jit():
    """One jitted computation for the whole grad list: fp32-accumulated
    global norm + every rescale in a single dispatch (vs the historical
    N+1 eager reductions and N scale-multiplies). The clip norm rides in
    as a traced scalar so every ClipGradByGlobalNorm instance shares the
    same compile-cache entry per grad-list signature."""
    global _CLIP_GLOBAL_JIT
    if _CLIP_GLOBAL_JIT is None:
        import jax
        import jax.numpy as jnp

        def clip(grads, clip_norm):
            gnorm = jnp.sqrt(sum((g.astype(jnp.float32) ** 2).sum()
                                 for g in grads))
            scale = jnp.minimum(1.0, clip_norm / jnp.maximum(gnorm,
                                                             1e-12))
            return tuple((g * scale).astype(g.dtype) for g in grads)

        _CLIP_GLOBAL_JIT = jax.jit(clip)
    return _CLIP_GLOBAL_JIT


class ClipGradByGlobalNorm:
    """nn.ClipGradByGlobalNorm parity (fluid/clip.py GradientClipByGlobalNorm).

    This legacy per-param path remains behind the fused optimizer step
    (the sparse fallback, user code calling the clip directly); the fused
    step folds the same math into its single dispatch instead."""

    def __init__(self, clip_norm=1.0):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        from ..core.tensor import Tensor

        grads = [g for _, g in params_grads if g is not None]
        if not grads:
            return params_grads
        scaled = iter(_clip_global_jit()(
            tuple(g._data for g in grads), self.clip_norm))
        return [(p, g if g is None else Tensor._wrap(next(scaled)))
                for p, g in params_grads]


class ClipGradByNorm:
    def __init__(self, clip_norm=1.0):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        import jax.numpy as jnp

        from ..core.tensor import Tensor
        from ..ops import kernels as K

        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
            else:
                out.append((p, Tensor._wrap(K.clip_by_norm(g._data,
                                                           self.clip_norm))))
        return out


class ClipGradByValue:
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        import jax.numpy as jnp

        from ..core.tensor import Tensor

        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
            else:
                out.append((p, Tensor._wrap(jnp.clip(g._data, self.min,
                                                     self.max))))
        return out


def utils_spectral_norm(*a, **k):
    raise NotImplementedError
