"""paddle.nn layer classes.

Reference parity: python/paddle/nn/layer/{common,conv,norm,pooling,activation,
loss,transformer,rnn}.py and fluid/dygraph/nn.py (Conv2D/Linear/BatchNorm...).
All compute routes through nn.functional → ops/kernels.py jnp kernels.
"""
from __future__ import annotations

import collections
import math

import numpy as np

from ...core.dtypes import get_default_dtype
from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer, Parameter, ParamAttr


def _jnp():
    import jax.numpy as jnp

    return jnp


# ============================ containers ============================

class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                layers[0] and isinstance(layers[0][0], (list, tuple)):
            for name, layer in layers[0]:
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                if isinstance(layer, tuple):
                    self.add_sublayer(layer[0], layer[1])
                else:
                    self.add_sublayer(str(i), layer)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        return self._sub_layers[str(idx if idx >= 0 else
                                    len(self._sub_layers) + idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def append(self, p):
        self.add_parameter(str(len(self._parameters)), p)
        return self

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            for k, v in (sublayers.items() if isinstance(sublayers, dict)
                         else sublayers):
                self.add_sublayer(k, v)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def keys(self):
        return self._sub_layers.keys()

    def values(self):
        return self._sub_layers.values()

    def items(self):
        return self._sub_layers.items()

    def __len__(self):
        return len(self._sub_layers)


class Identity(Layer):
    def forward(self, x):
        return x


# ============================ linear/embedding ============================

class Linear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)

    @property
    def param_dtype(self):
        """The compute dtype this layer's outputs carry — the live
        weight's dtype, or the recorded pre-quantization dtype after
        quantize_int8() dropped the fp32 weight."""
        if self.weight is not None:
            return self.weight._data.dtype
        return self._weight_dtype

    def quantize_int8(self):
        """Serving-time weight quantization: replace the fp32 weight
        parameter with a symmetric per-output-channel int8 buffer +
        f32 scales (ops.quant.quantize_int8_weight) and route forward
        through the scaled-int8 matmul. One-way (serving engines own
        the model by contract); bias/compute dtype untouched."""
        if self.weight is None:
            return self
        from ...core.tensor import Tensor
        from ...ops import quant as Q

        w = self.weight._data
        q, s = Q.quantize_int8_weight(w)
        self._weight_dtype = w.dtype
        self.register_buffer("weight_q", Tensor._wrap(q))
        self.register_buffer("weight_scale", Tensor._wrap(s))
        self.weight = None          # drops the fp32 copy from params
        return self

    def forward(self, x):
        if self.weight is None and "weight_q" in self._buffers:
            y = F.linear_int8(x, self.weight_q, self.weight_scale,
                              self.bias)
        else:
            y = F.linear(x, self.weight, self.bias)
        # batched-LoRA hook: an AdapterPool installs `_lora_idx` on its
        # target layers; inside a serving step's `lora_scope` the
        # per-row adapter delta joins the output (one dict read + one
        # scope read when installed, nothing at all otherwise)
        idx = self.__dict__.get("_lora_idx")
        if idx is not None:
            from ...ops.quant import current_lora

            ctx = current_lora()
            if ctx is not None:
                ids, banks = ctx
                A, B = banks[idx]
                y = y + F.lora_delta(x, A, B, ids)
        return y


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._padding_idx = padding_idx
        self._sparse = sparse
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        if padding_idx is not None:
            self.weight._data = self.weight._data.at[padding_idx].set(0.0)

    def quantize_int8(self):
        """Serving-time vocab-table quantization: the [V, D] table
        becomes int8 + per-output-channel f32 scales; lookups gather
        int8 rows and scale (no dense dequantized copy). Mirrors
        Linear.quantize_int8 — the embedding is the one-hot matmul."""
        if self.weight is None:
            return self
        from ...core.tensor import Tensor
        from ...ops import quant as Q

        w = self.weight._data
        q, s = Q.quantize_int8_weight(w)
        self._weight_dtype = w.dtype
        self.register_buffer("weight_q", Tensor._wrap(q))
        self.register_buffer("weight_scale", Tensor._wrap(s))
        self.weight = None
        return self

    def forward(self, x):
        if self.weight is None and "weight_q" in self._buffers:
            return F.embedding_int8(x, self.weight_q,
                                    self.weight_scale,
                                    self._weight_dtype)
        return F.embedding(x, self.weight, self._padding_idx, self._sparse)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        return x.flatten(self.start_axis, self.stop_axis)


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p, self.axis, self.mode = p, axis, mode

    def forward(self, x):
        return F.dropout(x, self.p, self.axis, self.training, self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.dropout2d(x, self.p, self.training)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, self.training)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, name=None):
        super().__init__(size, scale_factor, "bilinear", True)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, name=None):
        super().__init__(size, scale_factor, "nearest")


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.r = upscale_factor

    def forward(self, x):
        return F.pixel_shuffle(x, self.r)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.padding = padding if isinstance(padding, (list, tuple)) else \
            [padding] * 4
        self.mode, self.value = mode, value

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = self.create_parameter([1, out_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        from ...tensor.ops import _op

        def fn(a, b, w, bias):
            out = _jnp().einsum("bi,oij,bj->bo", a, w, b)
            return out + bias

        return _op("bilinear", fn, x1, x2, self.weight, self.bias)


# ============================ conv ============================

def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


class Conv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        k = _pair(kernel_size)
        self._stride, self._padding = stride, padding
        self._dilation, self._groups = dilation, groups
        self._data_format = data_format
        fan_in = in_channels * k[0] * k[1] // groups
        std = math.sqrt(2.0 / fan_in)  # MSRA default like reference conv
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, k[0], k[1]],
            attr=weight_attr, default_initializer=I.Normal(0.0, std))
        self.bias = self.create_parameter([out_channels], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        k = _pair(kernel_size)
        self._args = (stride, padding, output_padding, dilation, groups)
        self.weight = self.create_parameter(
            [in_channels, out_channels // groups, k[0], k[1]],
            attr=weight_attr, default_initializer=I.XavierUniform())
        self.bias = self.create_parameter([out_channels], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        s, p, op, d, g = self._args
        return F.conv2d_transpose(x, self.weight, self.bias, s, p, op, d, g)


class Conv1D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__()
        k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
        self._args = (stride, padding, dilation, groups)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, k], attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = self.create_parameter([out_channels], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        s, p, d, g = self._args
        return F.conv1d(x, self.weight, self.bias, s, p, d, g)


# ============================ pooling ============================

class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCHW", name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, ceil_mode)

    def forward(self, x):
        return F.max_pool2d(x, *self._args)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, ceil_mode, exclusive)

    def forward(self, x):
        return F.avg_pool2d(x, *self._args)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self._output_size)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self._output_size)


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, ceil_mode)

    def forward(self, x):
        return F.max_pool1d(x, *self._args)


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, ceil_mode, exclusive)

    def forward(self, x):
        return F.avg_pool1d(x, *self._args)


# ============================ normalization ============================

class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._momentum, self._epsilon = momentum, epsilon
        self._data_format = "NCHW" if data_format in ("NCHW", "NCL", "NC") \
            else "NHWC"
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)
        jnp = _jnp()
        self.register_buffer("_mean", Tensor._wrap(
            jnp.zeros((num_features,), get_default_dtype())))
        self.register_buffer("_variance", Tensor._wrap(
            jnp.ones((num_features,), get_default_dtype())))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class BatchNorm(_BatchNormBase):
    """fluid.dygraph.BatchNorm compatibility (act fusion)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", in_place=False, use_global_stats=False,
                 **kw):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout,
                         use_global_stats or None)
        self._act = act

    def forward(self, x):
        y = super().forward(x)
        if self._act:
            y = getattr(F, self._act)(y)
        return y


class SyncBatchNorm(_BatchNormBase):
    """Under SPMD data parallel, XLA computes global batch stats when the
    train step is jitted over the mesh; eager single-process behaves like BN.
    (reference: operators/sync_batch_norm_op.cu → psum of partial moments)"""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            out = SyncBatchNorm(layer.weight.shape[0], layer._momentum,
                                layer._epsilon)
            out.weight = layer.weight
            out.bias = layer.bias
            out._mean = layer._mean
            out._variance = layer._variance
        else:
            for name, sub in list(layer._sub_layers.items()):
                layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(self._normalized_shape,
                                          attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups, self._epsilon = num_groups, epsilon
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_channels], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self.weight, self.bias,
                            self._epsilon)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self._args = (size, alpha, beta, k)

    def forward(self, x):
        return F.local_response_norm(x, *self._args)


class SpectralNorm(Layer):
    """Spectral weight normalization (spectral_norm_op.cc): divides the
    weight by its largest singular value, estimated by `power_iters`
    rounds of power iteration on persistent u/v vectors."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None, dtype="float32"):
        super().__init__()
        import numpy as np

        from ...core.tensor import to_tensor

        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        rs = np.random.RandomState(0)
        self.register_buffer("weight_u", to_tensor(
            _np_l2norm(rs.randn(h).astype(dtype))))
        self.register_buffer("weight_v", to_tensor(
            _np_l2norm(rs.randn(w).astype(dtype))))

    def forward(self, weight):
        from ...ops import kernels as K
        from ...tensor import ops as T

        w = weight._data if hasattr(weight, "_data") else weight
        out, u_new, v_new = K.spectral_normalize(
            w, self.weight_u._data, self.weight_v._data, self._dim,
            self._power_iters, self._eps)
        # persist the power-iteration state so sigma converges across
        # steps (reference CalcMatrixSigmaAndNormWeight mutates U/V)
        self.weight_u._data = u_new
        self.weight_v._data = v_new
        return T.Tensor._wrap(out)


def _np_l2norm(a):
    import numpy as np

    return a / (np.linalg.norm(a) + 1e-12)


class RMSNorm(Layer):
    """TPU-native extra (standard in modern LLM stacks)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        from ...tensor.ops import _op
        from ...ops import kernels as K

        return _op("rms_norm",
                   lambda a, w: K.rms_norm(a, w, self._epsilon), x,
                   self.weight)


# ============================ activations ============================

def _act_layer(name, fn_name=None):
    fn = getattr(F, fn_name or name.lower())

    cls = type(name, (Layer,), {
        "forward": lambda self, x: fn(x),
    })
    return cls


ReLU = _act_layer("ReLU", "relu")
ReLU6 = _act_layer("ReLU6", "relu6")
Sigmoid = _act_layer("Sigmoid", "sigmoid")
Tanh = _act_layer("Tanh", "tanh")
Softsign = _act_layer("Softsign", "softsign")
Mish = _act_layer("Mish", "mish")
Hardswish = _act_layer("Hardswish", "hardswish")
Hardsigmoid = _act_layer("Hardsigmoid", "hardsigmoid")
Silu = _act_layer("Silu", "silu")
Swish = _act_layer("Swish", "swish")
LogSigmoid = _act_layer("LogSigmoid", "log_sigmoid")
Tanhshrink = _act_layer("Tanhshrink", "tanhshrink")


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self._approximate = approximate

    def forward(self, x):
        return F.gelu(x, self._approximate)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._slope)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.elu(x, self._alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772,
                 name=None):
        super().__init__()
        self._scale, self._alpha = scale, alpha

    def forward(self, x):
        return F.selu(x, self._scale, self._alpha)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.log_softmax(x, self._axis)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self._args = (beta, threshold)

    def forward(self, x):
        return F.softplus(x, *self._args)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self._args = (min, max)

    def forward(self, x):
        return F.hardtanh(x, *self._args)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self._threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self._threshold)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self._args = (groups, axis)

    def forward(self, x):
        return F.maxout(x, *self._args)


# ============================ losses ============================

class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True, name=None):
        super().__init__()
        self._args = dict(weight=weight, ignore_index=ignore_index,
                          reduction=reduction, soft_label=soft_label,
                          axis=axis, use_softmax=use_softmax)

    def forward(self, input, label):
        return F.cross_entropy(input, label, **self._args)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self._reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self._reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 name=None):
        super().__init__()
        self._args = (weight, ignore_index, reduction)

    def forward(self, input, label):
        w, ig, red = self._args
        return F.nll_loss(input, label, w, ig, red)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self._args = (weight, reduction)

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, *self._args)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None,
                 name=None):
        super().__init__()
        self._args = (weight, reduction, pos_weight)

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, *self._args)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self._args = (reduction, delta)

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, *self._args)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, self._reduction)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self._args = (margin, reduction)

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, *self._args)
