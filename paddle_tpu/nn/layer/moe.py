"""Mixture-of-Experts FFN with top-1 (switch) routing and
capacity-bounded dispatch/combine over the `ep` mesh axis.

Reference role: the reference framework predates MoE support (its
distributed stack is PS/collective-only); this is a beyond-parity
capability required by the `ep` axis the SPMD engine advertises.
TPU-native design: dispatch/combine are dense one-hot einsums over a
STATIC [tokens, experts, capacity] tensor (Mesh-TensorFlow / Switch
Transformer formulation) — no dynamic shapes, no scatter; expert
weights are stacked [E, ...] so `parallel.sharding` rules
(`experts.weight_in/out` -> ("ep", ...)) shard the expert axis and XLA
inserts the all-to-alls implied by the einsum contractions.
"""
from __future__ import annotations

from .. import functional as F
from .layers import Layer


class _Experts(Layer):
    """Parameter container whose PATH gives the `experts.weight_in/out`
    names the sharding rules key on (parallel/sharding.py:59)."""

    def __init__(self, num_experts, d_model, d_ff):
        super().__init__()
        import paddle_tpu.nn.initializer as I

        self.weight_in = self.create_parameter(
            [num_experts, d_model, d_ff],
            default_initializer=I.XavierUniform())
        self.weight_out = self.create_parameter(
            [num_experts, d_ff, d_model],
            default_initializer=I.XavierUniform())


class MoELayer(Layer):
    """Top-1 routed FFN: y[t] = gate[t] * W_out[e(t)] @ act(W_in[e(t)] x[t]).

    Tokens beyond an expert's capacity (capacity_factor * tokens /
    num_experts) are dropped (contribute zero — the residual connection
    around the layer carries them), matching Switch Transformer
    semantics. The router's load-balancing auxiliary loss is stored on
    `self.aux_loss` each forward; trainers add `moe_aux_weight *
    sum(aux losses)` to the objective.
    """

    def __init__(self, d_model, d_ff, num_experts=2, capacity_factor=1.25,
                 activation="gelu", name=None):
        super().__init__()
        import paddle_tpu.nn.initializer as I

        self.num_experts = int(num_experts)
        self.capacity_factor = float(capacity_factor)
        self.act = activation
        self.router = self.create_parameter(
            [d_model, self.num_experts],
            default_initializer=I.XavierUniform())
        # stacked expert weights: leading E axis is the `ep` shard axis
        # (parallel/sharding.py rules match the experts.* path)
        self.experts = _Experts(self.num_experts, d_model, d_ff)
        # the load-balance aux loss rides a (non-persistable) BUFFER:
        # FunctionalModule threads buffer mutations through apply()'s
        # RETURN value, which survives jit and jax.checkpoint — a side
        # list would leak tracers out of the remat trace. SpmdTrainer
        # picks every `aux_loss_val` buffer out of new_buffers and adds
        # moe_aux_weight * sum to the objective.
        import numpy as np

        from ...core.tensor import Tensor

        self.register_buffer("aux_loss_val",
                             Tensor(np.zeros((), np.float32)),
                             persistable=False)
        self._last_aux = None

    @property
    def aux_loss(self):
        """Eager: the tape Tensor from the last forward (differentiable
        for `total = loss + w * moe.aux_loss` training loops). In a
        functional/jit context read the `aux_loss_val` entry of
        apply()'s new_buffers instead."""
        if self._last_aux is not None:
            return self._last_aux
        return self._buffers["aux_loss_val"]

    def forward(self, x):
        """x: [B, S, d_model] -> [B, S, d_model]."""
        from ...tensor import ops as T

        B, S, D = x.shape
        E = self.num_experts
        tokens = B * S
        cap = max(1, int(self.capacity_factor * tokens / E))
        xf = T.reshape(x, [tokens, D])

        logits = T.einsum("td,de->te", xf, self.router)
        probs = F.softmax(logits, axis=-1)                    # [T, E]
        expert_idx = T.argmax(probs, axis=-1)                 # [T]
        onehot = F.one_hot(expert_idx, E)                     # [T, E]
        gate = T.sum(probs * onehot, axis=-1)                 # [T]

        # position of each token within its expert's queue, in token
        # order; tokens past capacity get mask 0
        pos = T.cumsum(onehot, axis=0) * onehot               # [T, E]
        pos = T.sum(pos, axis=-1) - 1.0                       # [T]
        keep = (pos < float(cap)).astype("float32")
        pos_oh = F.one_hot(T.clip(pos, 0.0, float(cap - 1)).astype(
            "int64"), cap)                                    # [T, C]
        # dispatch[t, e, c] = 1 iff token t sits in slot c of expert e
        dispatch = T.einsum("te,tc->tec",
                            onehot * T.unsqueeze(keep, -1), pos_oh)
        combine = dispatch * T.unsqueeze(
            T.unsqueeze(gate, -1), -1)                        # [T, E, C]

        expert_in = T.einsum("tec,td->ecd", dispatch, xf)     # [E, C, D]
        h = T.einsum("ecd,edf->ecf", expert_in,
                     self.experts.weight_in)
        h = F.gelu(h) if self.act == "gelu" else F.relu(h)
        expert_out = T.einsum("ecf,efd->ecd", h,
                              self.experts.weight_out)        # [E, C, D]
        out = T.einsum("tec,ecd->td", combine, expert_out)

        # Switch load-balance aux loss: E * sum_e f_e * P_e, where f_e =
        # fraction of tokens routed to e, P_e = mean router prob of e
        f_e = T.mean(onehot, axis=0)
        p_e = T.mean(probs, axis=0)
        aux = T.sum(f_e * p_e) * float(E)
        self._buffers["aux_loss_val"]._data = aux._data  # jit channel
        try:
            from jax._src import core as _jc

            self._last_aux = aux if _jc.trace_state_clean() else None
        except Exception:
            self._last_aux = None

        return T.reshape(out, [B, S, D])
