from . import common, layers, rnn, transformer  # noqa: F401
