"""Recurrent layers.

Reference parity: python/paddle/nn/layer/rnn.py (LSTM/GRU/SimpleRNN + cells)
and the C++ recurrent machinery (operators/math/lstm_compute, gru_compute,
operators/controlflow/recurrent_op.cc). TPU-native design: the time loop is a
single `lax.scan` — one compiled XLA While with fused per-step matmuls —
instead of an interpreted static RNN (compiler-friendly control flow).
"""
from __future__ import annotations

import math

import numpy as np

from ...core.tensor import Tensor, apply_op
from .. import functional as F  # noqa: F401
from .. import initializer as I
from .layers import Layer


def _jnp():
    import jax.numpy as jnp

    return jnp


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        jnp = _jnp()
        b = batch_ref.shape[batch_dim_idx]
        return Tensor._wrap(jnp.full((b, self.hidden_size), init_value,
                                     batch_ref._data.dtype))


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter(
            [hidden_size], bias_ih_attr, is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter(
            [hidden_size], bias_hh_attr, is_bias=True, default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def fn(x, h, wi, wh, bi, bh):
            jnp = _jnp()
            z = x @ wi.T + bi + h @ wh.T + bh
            return jnp.tanh(z) if self.activation == "tanh" else \
                jnp.maximum(z, 0)

        out = apply_op("rnn_cell", fn,
                       [inputs, states, self.weight_ih, self.weight_hh,
                        self.bias_ih, self.bias_hh])
        return out, out


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
        else:
            h, c = states

        def fn(x, h_, c_, wi, wh, bi, bh):
            import jax

            jnp = _jnp()
            gates = x @ wi.T + bi + h_ @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c_ + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new

        h_new, c_new = apply_op(
            "lstm_cell", fn,
            [inputs, h, c, self.weight_ih, self.weight_hh, self.bias_ih,
             self.bias_hh], n_outputs=2)
        return h_new, (h_new, c_new)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def fn(x, h_, wi, wh, bi, bh):
            import jax

            jnp = _jnp()
            xg = x @ wi.T + bi
            hg = h_ @ wh.T + bh
            xr, xz, xn = jnp.split(xg, 3, axis=-1)
            hr, hz, hn = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            return (1.0 - z) * n + z * h_

        out = apply_op("gru_cell", fn,
                       [inputs, states, self.weight_ih, self.weight_hh,
                        self.bias_ih, self.bias_hh])
        return out, out


class RNN(Layer):
    """Wraps a cell into a scanned sequence layer (nn.RNN parity)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        # eager loop (autograd-taped); the static path uses lax.scan
        axis = 0 if self.time_major else 1
        steps = inputs.shape[axis]
        states = initial_states
        outs = []
        rng = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        for tstep in rng:
            xt = inputs[(slice(None),) * axis + (tstep,)]
            out, states = self.cell(xt, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        from ...tensor import ops as T

        return T.stack(outs, axis=axis), states


class _MultiLayerRNN(Layer):
    MODE = "RNN"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirectional = direction in ("bidirect", "bidirectional")
        num_dir = 2 if self.bidirectional else 1
        self.num_directions = num_dir
        cells = []
        for layer in range(num_layers):
            for d in range(num_dir):
                in_sz = input_size if layer == 0 else hidden_size * num_dir
                cells.append(self._make_cell(in_sz, hidden_size, activation,
                                             weight_ih_attr, weight_hh_attr,
                                             bias_ih_attr, bias_hh_attr))
        from .common import LayerList

        self.cells = LayerList(cells)

    def _make_cell(self, in_sz, hid, act, *attrs):
        if self.MODE == "LSTM":
            return LSTMCell(in_sz, hid, *attrs)
        if self.MODE == "GRU":
            return GRUCell(in_sz, hid, *attrs)
        return SimpleRNNCell(in_sz, hid, act, *attrs)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor import ops as T

        x = inputs
        final_states = []
        for layer in range(self.num_layers):
            outs_dir = []
            states_dir = []
            for d in range(self.num_directions):
                cell = self.cells[layer * self.num_directions + d]
                init = None
                if initial_states is not None:
                    init = self._slice_init(initial_states, layer, d)
                rnn = RNN(cell, is_reverse=(d == 1),
                          time_major=self.time_major)
                out, st = rnn(x, init)
                outs_dir.append(out)
                states_dir.append(st)
            x = outs_dir[0] if len(outs_dir) == 1 else T.concat(
                outs_dir, axis=-1)
            final_states.extend(states_dir)
            if self.dropout and layer < self.num_layers - 1 and self.training:
                x = F.dropout(x, self.dropout, training=True)
        if self.MODE == "LSTM":
            h = T.stack([s[0] for s in final_states], axis=0)
            c = T.stack([s[1] for s in final_states], axis=0)
            return x, (h, c)
        h = T.stack(final_states, axis=0)
        return x, h

    def _slice_init(self, initial_states, layer, d):
        idx = layer * self.num_directions + d
        if self.MODE == "LSTM":
            h, c = initial_states
            return h[idx], c[idx]
        return initial_states[idx]


class SimpleRNN(_MultiLayerRNN):
    MODE = "RNN"


class LSTM(_MultiLayerRNN):
    MODE = "LSTM"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, "tanh", weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)


class GRU(_MultiLayerRNN):
    MODE = "GRU"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, "tanh", weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)
