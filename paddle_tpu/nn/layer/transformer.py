"""Transformer layers.

Reference parity: python/paddle/nn/layer/transformer.py (MultiHeadAttention,
TransformerEncoder/Decoder, Transformer). TPU-native design: attention goes
through ops/attention.py — a pallas flash kernel on TPU, XLA composition
elsewhere — with head-batched (B, H, S, D) layout feeding the MXU.
"""
from __future__ import annotations

import collections

from .. import functional as F
from .common import Dropout, LayerList, LayerNorm, Linear
from .layers import Layer


class MultiHeadAttention(Layer):
    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])
    # decode-engine cache: preallocated [B, H, max_len, D] K/V buffers
    # plus the lockstep int32 write index ([B], every row equal — the
    # leading dim makes it a valid lax.scan carry AND lets beam search
    # tile/regather it like any other state leaf). Leaves are raw jax
    # arrays, NOT Tensors: the whole point is to ride jitted scans.
    StaticKVCache = collections.namedtuple("StaticKVCache",
                                           ["k", "v", "index"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _split_heads(self, x):
        b, s, _ = x.shape
        return x.reshape([b, s, self.num_heads, self.head_dim]).transpose(
            [0, 2, 1, 3])

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None, segment_ids=None):
        key = query if key is None else key
        value = key if value is None else value
        if cache is None:
            # transpose-free path: [B, S, h, d] operands — the head
            # transpose folds into the attention einsums (1.3x on the
            # short-seq XLA path; flash transposes internally when it
            # engages). segment_ids: packed-varlen feed (several LoD
            # sequences per row); rides to the segment-masked flash
            # kernel through the sdpa dispatcher.
            b, s, _ = query.shape
            q = self.q_proj(query).reshape(
                [b, s, self.num_heads, self.head_dim])
            k = self.k_proj(key).reshape(
                [b, key.shape[1], self.num_heads, self.head_dim])
            v = self.v_proj(value).reshape(
                [b, value.shape[1], self.num_heads, self.head_dim])
            out = F.scaled_dot_product_attention(q, k, v, attn_mask,
                                                 self.dropout,
                                                 training=self.training,
                                                 layout="BSHD",
                                                 segment_ids=segment_ids)
            out = out.reshape([b, s, self.num_heads * self.head_dim])
            return self.out_proj(out)
        q = self._split_heads(self.q_proj(query))
        k = self._split_heads(self.k_proj(key))
        v = self._split_heads(self.v_proj(value))
        from ...serving.paging import PagedKVCache

        if isinstance(cache, PagedKVCache):
            out, cache = self._paged_kv_attention(q, k, v, attn_mask,
                                                  cache)
            return self.out_proj(out), cache
        if isinstance(cache, self.StaticKVCache):
            out, cache = self._static_kv_attention(q, k, v, attn_mask,
                                                   cache)
            return self.out_proj(out), cache
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        else:
            from ...tensor import ops as T

            k = T.concat([cache.k, k], axis=2)
            v = T.concat([cache.v, v], axis=2)
            cache = self.Cache(k, v)
        out = F.scaled_dot_product_attention(q, k, v, attn_mask,
                                             self.dropout,
                                             training=self.training)
        b, h, s, d = out.shape
        out = out.transpose([0, 2, 1, 3]).reshape([b, s, h * d])
        out = self.out_proj(out)
        if not isinstance(cache, self.StaticCache):
            return out, cache
        return out

    def _static_kv_attention(self, q, k, v, attn_mask, cache):
        """Preallocated-cache attention (inference-only, raw jnp — the
        static path exists to run inside jitted decode scans, outside
        the autograd tape). The new K/V block lands at the write index
        via lax.dynamic_update_slice; queries see written positions
        only (position mask), composed with an optional [B, max_len]
        (or [B, 1, 1, max_len]) additive key bias for padded-prompt
        holes. Contract: a multi-token write (S > 1) is the PREFILL of
        an empty cache — it attends within the prompt block itself on
        the regular flash-capable path; S == 1 is a decode step through
        the flash-decode kernel. Inside `ops.attention.kv_verify_scope`
        a multi-token write is instead a speculative-decoding VERIFY
        block: the S tokens land at each row's OWN write offset (per-row
        vmapped writes, the decode-step layout) and attend causally
        within the block via `verify_attention` — rolling the write
        index back afterwards is the caller's acceptance logic."""
        import jax
        import jax.numpy as jnp

        from ...core.tensor import Tensor
        from ...ops import attention as A

        def raw(x):
            return x._data if isinstance(x, Tensor) else jnp.asarray(x)

        qd, kd, vd = raw(q), raw(k), raw(v)
        kbuf, vbuf, idx = raw(cache.k), raw(cache.v), raw(cache.index)
        b, h, s, d = qd.shape
        idx = (idx if idx.ndim else idx[None]).astype(jnp.int32)
        z = jnp.int32(0)
        verify = s > 1 and A.in_kv_verify_scope()
        if s == 1 or verify:
            # decode step (or a verify block): per-ROW write positions —
            # the serving slot pool holds requests at independent
            # offsets; lockstep batches (DecodeEngine) are the all-equal
            # special case. The same vmapped dynamic_update_slice covers
            # one token or a k-token verify block.
            def _write(buf, new, i):
                return jax.lax.dynamic_update_slice(buf, new, (z, i, z))

            kbuf = jax.vmap(_write)(kbuf, kd.astype(kbuf.dtype), idx)
            vbuf = jax.vmap(_write)(vbuf, vd.astype(vbuf.dtype), idx)
        else:
            # multi-token prefill of an empty cache: lockstep by
            # contract, one dynamic_update_slice covers every row
            pos = idx[0]
            kbuf = jax.lax.dynamic_update_slice(
                kbuf, kd.astype(kbuf.dtype), (z, z, pos, z))
            vbuf = jax.lax.dynamic_update_slice(
                vbuf, vd.astype(vbuf.dtype), (z, z, pos, z))
        new_cache = MultiHeadAttention.StaticKVCache(
            kbuf, vbuf, (idx + s).astype(jnp.int32))
        mask = None if attn_mask is None else raw(attn_mask)
        if mask is not None and mask.ndim > 2:
            mask = mask.reshape(mask.shape[0], mask.shape[-1])
        if s == 1:
            out = A.decode_attention(qd, kbuf, vbuf, idx + 1, bias=mask)
        elif verify:
            out = A.verify_attention(qd, kbuf, vbuf, idx + s, bias=mask)
        else:
            bias4 = None if mask is None else \
                mask.astype(jnp.float32)[:, None, None, :]
            out = A.sdpa(qd, kd, vd, bias4, is_causal=True)
        out = jnp.swapaxes(out, 1, 2).reshape(b, s, h * d)
        return Tensor._wrap(out), new_cache

    def _paged_kv_attention(self, q, k, v, attn_mask, cache):
        """Decode attention through a paged pool (serving-only, raw
        jnp): the single token's K/V is quantized and scattered into
        the physical page the slot's table maps for its write position
        (rescaling an int8 page whose scale it outranges), then the
        query attends over the pages — through the scalar-prefetched
        page table in the pallas kernel on TPU, or a gathered dense
        logical view on the XLA fallback path (bit-identical to the
        dense StaticKVCache when pages keep the compute dtype).
        Contract: decode steps only (S == 1 query token); prompt
        prefill runs on the regular flash path into a dense batch-1
        cache whose pages the serving join scatters separately."""
        import jax.numpy as jnp

        from ...core.tensor import Tensor
        from ...ops import attention as A
        from ...serving import paging as PG

        def raw(x):
            return x._data if isinstance(x, Tensor) else jnp.asarray(x)

        qd, kd, vd = raw(q), raw(k), raw(v)
        b, h, s, d = qd.shape
        verify = s > 1 and A.in_kv_verify_scope()
        if s != 1 and not verify:
            raise ValueError(
                "PagedKVCache attention is decode-only (one query "
                "token per slot); prefill goes through the join path, "
                "and a multi-token speculative verify block rides "
                "ops.attention.kv_verify_scope")
        idx = raw(cache.index).astype(jnp.int32)
        table = raw(cache.table).astype(jnp.int32)
        if verify:
            # speculative verify block: the s tokens land at each
            # slot's own offset, crossing page boundaries as they
            # fall; the caller's acceptance logic rolls the per-slot
            # index back afterwards (no page frees on reject)
            kp, ks = PG.write_tokens(cache.k, cache.k_scale, table,
                                     idx, kd)
            vp, vs = PG.write_tokens(cache.v, cache.v_scale, table,
                                     idx, vd)
        else:
            kp, ks = PG.write_token(cache.k, cache.k_scale, table, idx,
                                    kd[:, :, 0, :])
            vp, vs = PG.write_token(cache.v, cache.v_scale, table, idx,
                                    vd[:, :, 0, :])
        new_cache = PG.PagedKVCache(kp, vp, ks, vs, table,
                                    (idx + s).astype(jnp.int32))
        mask = None if attn_mask is None else raw(attn_mask)
        if mask is not None and mask.ndim > 2:
            mask = mask.reshape(mask.shape[0], mask.shape[-1])
        if verify:
            out = A.paged_verify_attention(qd, kp, vp, ks, vs, table,
                                           idx + s, bias=mask)
        else:
            out = A.paged_decode_attention(qd, kp, vp, ks, vs, table,
                                           idx + 1, bias=mask)
        out = jnp.swapaxes(out, 1, 2).reshape(b, s, h * d)
        return Tensor._wrap(out), new_cache

    def gen_paged_cache(self, num_pages, page_size, num_slots,
                        max_pages, dtype, kv_dtype=None,
                        page_sharding=None):
        """Per-layer paged pool: zeroed [num_pages + 1, H, page_size,
        D] K/V page arrays (the +1 row is the trash page inactive
        slots' masked writes land on), per-page scales when kv_dtype
        is int8, an unmapped (trash-clipped) table and zero write
        indices. The serving engine owns the host-side PageAllocator /
        page table; this just shapes the device state.
        `page_sharding`: optional NamedSharding laying the page axis
        out across the mesh (the sharded engine's data-parallel page
        pool); page reads/writes stay pure selection, so placement
        never changes the math."""
        import jax.numpy as jnp

        from ...serving import paging as PG

        storage, quantized = PG.resolve_kv_dtype(kv_dtype, dtype)
        buf = jnp.zeros((int(num_pages) + 1, self.num_heads,
                         int(page_size), self.head_dim), storage)
        sc = jnp.zeros((int(num_pages) + 1, self.num_heads, 1, 1),
                       jnp.float32) if quantized else None
        if page_sharding is not None:
            import jax

            buf = jax.device_put(buf, page_sharding)
            if sc is not None:
                sc = jax.device_put(sc, page_sharding)
        return PG.PagedKVCache(
            buf, buf, sc, sc,
            jnp.full((int(num_slots), int(max_pages)), int(num_pages),
                     jnp.int32),
            jnp.zeros((int(num_slots),), jnp.int32))

    @staticmethod
    def paged_prompt_splice(cache, page_ids, k_new, v_new):
        """Slot JOIN for paged pools: scatter a prefilled [1, H, P, D]
        K/V block into the physical pages `page_ids` (traced int32
        [ceil(P / page_size)]), quantizing per page on the way in.
        Like `static_kv_splice`, every operand that varies per join is
        traced, so joining any slot at any admitted prompt length
        reuses one compiled program per prompt bucket."""
        from ...serving import paging as PG

        quantized = cache.k_scale is not None
        kp, ks = PG.write_prompt_pages(cache.k, cache.k_scale, page_ids,
                                       k_new, quantized)
        vp, vs = PG.write_prompt_pages(cache.v, cache.v_scale, page_ids,
                                       v_new, quantized)
        return cache._replace(k=kp, v=vp, k_scale=ks, v_scale=vs)

    @staticmethod
    def static_kv_splice(cache, slot, k_new, v_new, n_written,
                         constraint=None):
        """Slot JOIN for pooled serving caches: write a prefilled
        [1, H, P, D] K/V block into row `slot` of a pooled [S, H, L, D]
        StaticKVCache (P <= L) and set that row's write index to
        `n_written`, leaving every other slot's buffers and index
        untouched. `slot` and `n_written` are traced int32 scalars, so
        joining ANY slot at ANY admitted prompt length reuses one
        compiled program — slot join never retraces. `constraint`:
        optional (kv_NamedSharding, index_NamedSharding) pinning the
        spliced pool back onto its mesh layout (the sharded engine's
        slot-on-data carry contract)."""
        import jax
        import jax.numpy as jnp

        z = jnp.int32(0)
        slot = jnp.asarray(slot, jnp.int32)
        k = jax.lax.dynamic_update_slice(
            cache.k, k_new.astype(cache.k.dtype), (slot, z, z, z))
        v = jax.lax.dynamic_update_slice(
            cache.v, v_new.astype(cache.v.dtype), (slot, z, z, z))
        index = jax.lax.dynamic_update_slice(
            cache.index,
            jnp.asarray(n_written, jnp.int32).reshape(1), (slot,))
        if constraint is not None:
            kv_ns, idx_ns = constraint
            k = jax.lax.with_sharding_constraint(k, kv_ns)
            v = jax.lax.with_sharding_constraint(v, kv_ns)
            index = jax.lax.with_sharding_constraint(index, idx_ns)
        return MultiHeadAttention.StaticKVCache(k, v, index)

    @staticmethod
    def splice_rows(buf, slot, rows, constraint=None):
        """Row splice for any pooled per-slot buffer ([S, ...]): write
        `rows` ([1, ...], trailing dims <= buf's) at row `slot` (traced
        int32). Used for the serving pool's cross-attention StaticCache
        K/V, pad-bias rows, and memory rows on slot join. `constraint`:
        optional NamedSharding pinned on the result."""
        import jax
        import jax.numpy as jnp

        z = jnp.int32(0)
        start = (jnp.asarray(slot, jnp.int32),) + (z,) * (buf.ndim - 1)
        out = jax.lax.dynamic_update_slice(
            buf, rows.astype(buf.dtype), start)
        if constraint is not None:
            out = jax.lax.with_sharding_constraint(out, constraint)
        return out

    def gen_cache(self, key, value=None, type=None, max_length=None,
                  batch_size=None, dtype=None, kv_sharding=None,
                  index_sharding=None):
        """Cache constructors. type=StaticCache precomputes K/V from
        `key` (cross-attention). max_length=N preallocates a
        StaticKVCache of [B, H, N, D] zero buffers + a zero write index
        — the decode-engine carry; B/dtype default to key's.
        `kv_sharding`/`index_sharding`: optional NamedShardings placing
        the pooled buffers straight onto a mesh (slot axis
        data-parallel in the sharded serving engine) instead of a
        single device."""
        if max_length is not None:
            import jax.numpy as jnp

            b = batch_size if batch_size is not None else key.shape[0]
            if dtype is None:
                dtype = self.q_proj.param_dtype
            buf = jnp.zeros(
                (int(b), self.num_heads, int(max_length), self.head_dim),
                dtype)
            idx = jnp.zeros((int(b),), jnp.int32)
            if kv_sharding is not None:
                import jax

                buf = jax.device_put(buf, kv_sharding)
                if index_sharding is not None:
                    idx = jax.device_put(idx, index_sharding)
            return self.StaticKVCache(buf, buf, idx)
        if type == MultiHeadAttention.StaticCache:
            k = self._split_heads(self.k_proj(key))
            v = self._split_heads(self.v_proj(value if value is not None
                                              else key))
            return self.StaticCache(k, v)
        from ...tensor import ops as T

        b = key.shape[0]
        k = T.zeros([b, self.num_heads, 0, self.head_dim], key.dtype)
        v = T.zeros([b, self.num_heads, 0, self.head_dim], key.dtype)
        return self.Cache(k, v)


#: module-level aliases for the class-scoped cache namedtuples: their
#: __qualname__ is the bare typename, so pickle resolves them as
#: attributes of THIS module — the persistent AOT compile cache
#: (paddle_tpu.tuning.aot_cache) pickles PyTreeDefs that reference
#: them when serializing the engines' compiled programs
Cache = MultiHeadAttention.Cache
StaticCache = MultiHeadAttention.StaticCache
StaticKVCache = MultiHeadAttention.StaticKVCache


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 moe_experts=0, moe_capacity_factor=1.25):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, attn_dropout if attn_dropout is not None
            else dropout, weight_attr=weight_attr, bias_attr=bias_attr)
        if moe_experts:
            from .moe import MoELayer

            self.moe = MoELayer(d_model, dim_feedforward,
                                num_experts=moe_experts,
                                capacity_factor=moe_capacity_factor,
                                activation=activation)
            self.linear1 = self.linear2 = None
        else:
            self.moe = None
            self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                                  bias_attr)
            self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                                  bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout_act = Dropout(act_dropout if act_dropout is not None
                                   else dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None, segment_ids=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask,
                                 segment_ids=segment_ids)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        if self.moe is not None:
            src = self.moe(src)
        else:
            src = self.linear2(self.dropout_act(self.activation(
                self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList(
            [encoder_layer] + [copy.deepcopy(encoder_layer)
                               for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None, segment_ids=None):
        output = src
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, src_mask,
                               segment_ids=segment_ids)
            else:
                output, c = layer(output, src_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.normalize_before = normalize_before
        ad = attn_dropout if attn_dropout is not None else dropout
        self.self_attn = MultiHeadAttention(d_model, nhead, ad)
        self.cross_attn = MultiHeadAttention(d_model, nhead, ad)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.dropout_act = Dropout(act_dropout if act_dropout is not None
                                   else dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
            incremental_cache = None
        else:
            tgt, incremental_cache = self.self_attn(tgt, tgt, tgt, tgt_mask,
                                                    cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
            static_cache = None
        else:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask, cache[1])
            static_cache = cache[1]
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout_act(self.activation(
            self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        if cache is None:
            return tgt
        return tgt, (incremental_cache, static_cache)

    def gen_cache(self, memory, max_length=None, batch_size=None,
                  dtype=None):
        if max_length is not None:
            incremental = self.self_attn.gen_cache(
                memory, max_length=max_length, batch_size=batch_size,
                dtype=dtype)
        else:
            incremental = self.self_attn.gen_cache(memory)
        static = self.cross_attn.gen_cache(
            memory, type=MultiHeadAttention.StaticCache)
        return incremental, static


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList(
            [decoder_layer] + [copy.deepcopy(decoder_layer)
                               for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        output = tgt
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, memory, tgt_mask, memory_mask)
            else:
                output, c = layer(output, memory, tgt_mask, memory_mask,
                                  cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False, max_length=None,
                  batch_size=None, dtype=None):
        return [layer.gen_cache(memory, max_length=max_length,
                                batch_size=batch_size, dtype=dtype)
                for layer in self.layers]

    def generate(self, memory, embed, project, **kwargs):
        """Fused autoregressive generation on the static KV-cache path:
        prefill through the flash-capable prompt pass, then the whole
        decode as ONE jitted lax.scan (greedy or beam) with
        StaticKVCache as carry. embed/project: the token-embedding and
        logits-projection Layers around this decoder stack. See
        paddle_tpu.text.generation.DecodeEngine for the full contract
        (bucketing, max_new_tokens, prompts)."""
        from ...text.generation import DecodeEngine

        eng = getattr(self, "_decode_engine", None)
        if eng is None or eng.embed_ref is not embed \
                or eng.project_ref is not project:
            eng = DecodeEngine(self, embed, project)
            self._decode_engine = eng
        return eng.generate(memory, **kwargs)


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        self.d_model = d_model
        self.nhead = nhead
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before)
            norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before)
            norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              norm)

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        import jax.numpy as jnp

        from ...core.tensor import Tensor

        m = jnp.where(jnp.tril(jnp.ones((length, length), bool)), 0.0, -1e9)
        return Tensor._wrap(m.astype(jnp.float32))
