"""Layer: the dygraph module system.

Reference parity: python/paddle/fluid/dygraph/layers.py:63 (Layer,
__call__ :678 with forward pre/post hooks, sublayers/parameters traversal,
state_dict/set_state_dict) and ParamAttr (fluid/param_attr.py). TPU-native
design: parameters are eager Tensors (jax arrays) registered on the module
tree; the functional state_dict view doubles as the pytree handed to jitted
train steps and to pjit shardings.
"""
from __future__ import annotations

import collections
from typing import Iterator

import numpy as np

from ...core.dtypes import convert_dtype, get_default_dtype
from ...core.tensor import Tensor
from .. import initializer as I


class ParamAttr:
    """fluid/param_attr.py:31 parity."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if attr is False:
            return False
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, I.Initializer):
            return ParamAttr(initializer=attr)
        raise TypeError(f"bad param attr {attr!r}")


class Parameter(Tensor):
    """A trainable Tensor (framework.py:5053 Parameter parity)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip")

    def __init__(self, data, trainable=True, name=None, learning_rate=1.0,
                 regularizer=None, need_clip=True):
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.persistable = True
        self.optimize_attr = {"learning_rate": learning_rate}
        self.regularizer = regularizer
        self.need_clip = need_clip


_name_counters = collections.defaultdict(int)


def _unique_name(prefix):
    _name_counters[prefix] += 1
    return f"{prefix}_{_name_counters[prefix] - 1}"


class Layer:
    def __init__(self, name_scope=None, dtype=None):
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self.training = True
        self._dtype = dtype or get_default_dtype()
        self._full_name = _unique_name(
            name_scope or type(self).__name__.lower())

    # ---------------- parameter creation ----------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtype or self._dtype
        if default_initializer is None:
            default_initializer = I.Constant(0.0) if is_bias else \
                I.XavierUniform()
        init = attr.initializer or default_initializer
        data = init(shape, dtype)
        p = Parameter(data, trainable=attr.trainable,
                      name=attr.name or _unique_name("param"),
                      learning_rate=attr.learning_rate,
                      regularizer=attr.regularizer,
                      need_clip=attr.need_clip)
        return p

    def add_parameter(self, name, parameter):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ---------------- attribute magic ----------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ first")
            params[name] = value
            for d in (layers, buffers):
                if d is not None and name in d:
                    del d[name]
            object.__setattr__(self, name, value)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ first")
            layers[name] = value
            object.__setattr__(self, name, value)
        else:
            if params is not None and name in params and value is None:
                params[name] = None
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}")

    # ---------------- traversal ----------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (f"{prefix}.{name}" if prefix else name), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                for n, p in layer.named_parameters(sub_prefix, True):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from layer.named_buffers(sub_prefix, True)

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self):
        for name, l in self._sub_layers.items():
            if l is not None:
                yield name, l

    def sublayers(self, include_self=False):
        out = [self] if include_self else []
        for l in self.children():
            out.extend(l.sublayers(include_self=True))
        return out

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, l in self.named_children():
            sub = f"{prefix}.{name}" if prefix else name
            yield from l.named_sublayers(sub, include_self=True)

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    def full_name(self):
        return self._full_name

    # ---------------- modes ----------------
    def train(self):
        self.training = True
        for l in self.children():
            l.train()
        return self

    def eval(self):
        self.training = False
        for l in self.children():
            l.eval()
        return self

    # ---------------- state dict ----------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = collections.OrderedDict() if destination is None else \
            destination
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip(".")):
            dest[name] = p
        for name, b in self.named_buffers(prefix=structured_name_prefix.rstrip(".")):
            short = name.rsplit(".", 1)[-1]
            if short not in self._non_persistable_buffer_names:
                dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, t in own.items():
            if name in state_dict:
                v = state_dict[name]
                arr = v.numpy() if isinstance(v, Tensor) else np.asarray(v)
                t.set_value(arr)
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # ---------------- hooks ----------------
    def register_forward_pre_hook(self, hook):
        h = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[h._id] = hook
        return h

    def register_forward_post_hook(self, hook):
        h = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[h._id] = hook
        return h

    # ---------------- call ----------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks.values():
            res = hook(self, args)
            if res is not None:
                args = res if isinstance(res, tuple) else (res,)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, args, out)
            if res is not None:
                out = res
        return out

    # ---------------- dtype / device movement ----------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = convert_dtype(dtype)
            for p in self.parameters():
                p._data = p._data.astype(dt)
            for b in self.buffers():
                if b is not None and hasattr(b, "_data") and \
                        np.issubdtype(np.dtype(b._data.dtype), np.floating):
                    b._data = b._data.astype(dt)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    # ---------------- functional bridge (TPU-native extra) ----------------
    def raw_state(self):
        """Pytree of raw jax arrays {name: array} — the functional view used
        by jitted train steps and pjit shardings."""
        return {k: v._data for k, v in self.state_dict().items()}

    def load_raw_state(self, tree):
        sd = self.state_dict()
        for k, v in tree.items():
            sd[k]._data = v


class HookRemoveHelper:
    _next = [0]

    def __init__(self, store):
        self._store = store
        self._id = HookRemoveHelper._next[0]
        HookRemoveHelper._next[0] += 1

    def remove(self):
        self._store.pop(self._id, None)
