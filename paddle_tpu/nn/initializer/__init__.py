"""Weight initializers.

Reference parity: python/paddle/fluid/initializer.py (ConstantInitializer,
UniformInitializer, NormalInitializer, TruncatedNormal, Xavier, MSRA/Kaiming,
NumpyArrayInitializer) and paddle.nn.initializer. Each initializer is a
callable (shape, dtype) -> jax array; the same objects drive both eager
parameter creation and static-graph startup programs.
"""
from __future__ import annotations

import math

import numpy as np

from ...core import random as _random
from ...core.dtypes import convert_dtype


def _jnp():
    import jax.numpy as jnp

    return jnp


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError

    def _fan(self, shape):
        shape = list(shape)
        if len(shape) == 0:
            return 1, 1
        if len(shape) == 1:
            return shape[0], shape[0]
        if len(shape) == 2:
            return shape[0], shape[1]
        # conv kernels: paddle weight layout OIHW → fan_in = I*k, fan_out = O*k
        rf = int(np.prod(shape[2:]))
        return shape[1] * rf, shape[0] * rf


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return _jnp().full(tuple(shape), self.value, convert_dtype(dtype))


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        import jax

        return jax.random.uniform(_random.next_key(), tuple(shape),
                                  convert_dtype(dtype), self.low, self.high)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, seed=0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        import jax

        return (jax.random.normal(_random.next_key(), tuple(shape),
                                  convert_dtype(dtype)) * self.std
                + self.mean)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, seed=0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        import jax

        return (jax.random.truncated_normal(
            _random.next_key(), -2.0, 2.0, tuple(shape),
            convert_dtype(dtype)) * self.std + self.mean)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        import jax

        fi, fo = self._fan(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(_random.next_key(), tuple(shape),
                                  convert_dtype(dtype), -limit, limit)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        import jax

        fi, fo = self._fan(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return jax.random.normal(_random.next_key(), tuple(shape),
                                 convert_dtype(dtype)) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype):
        import jax

        fi, _ = self._fan(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        limit = math.sqrt(6.0 / fi)
        return jax.random.uniform(_random.next_key(), tuple(shape),
                                  convert_dtype(dtype), -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in

    def __call__(self, shape, dtype):
        import jax

        fi, _ = self._fan(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        std = math.sqrt(2.0 / fi)
        return jax.random.normal(_random.next_key(), tuple(shape),
                                 convert_dtype(dtype)) * std


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        from ...core.tensor import Tensor

        v = self.value
        if isinstance(v, Tensor):
            v = v.numpy()
        arr = _jnp().asarray(np.asarray(v), dtype=convert_dtype(dtype))
        return arr.reshape(tuple(shape)) if list(arr.shape) != list(shape) \
            else arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        import jax

        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        a = jax.random.normal(_random.next_key(), (max(rows, cols),
                                                   min(rows, cols)))
        q, r = _jnp().linalg.qr(a)
        q = q * _jnp().sign(_jnp().diag(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(
            convert_dtype(dtype))


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        arr = np.zeros(shape, dtype=np.float32)
        o, i = shape[0], shape[1]
        mid = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for k in range(min(o // self.groups, i)):
                idx = (g * (o // self.groups) + k, k) + tuple(mid)
                arr[idx] = 1.0
        return _jnp().asarray(arr, dtype=convert_dtype(dtype))


# fluid-era aliases (fluid/initializer.py)
ConstantInitializer = Constant
UniformInitializer = Uniform
NormalInitializer = Normal
TruncatedNormalInitializer = TruncatedNormal
XavierInitializer = XavierUniform
MSRAInitializer = KaimingNormal
NumpyArrayInitializer = Assign


def _to_initializer(attr, default):
    """Resolve a ParamAttr-ish spec into an Initializer instance."""
    if attr is None:
        return default
    if isinstance(attr, Initializer):
        return attr
    if callable(attr):
        return attr
    return default


def calculate_gain(nonlinearity, param=None):
    if nonlinearity == "tanh":
        return 5.0 / 3.0
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity == "selu":
        return 3.0 / 4.0
    return 1.0
