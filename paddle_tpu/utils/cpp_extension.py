"""Custom C++ op loading (tests/custom_op + utils/cpp_extension parity).

The reference JIT-compiles user .cc files against its op registry and
dlopens them. TPU-native design: the user writes a plain C kernel
(float arrays in/out), `load()` compiles it with g++ into a shared
library, and `register_custom_op` exposes it BOTH as an eager Tensor op
and as a static-graph lowering — the host kernel runs inside XLA
programs through jax.pure_callback (the supported escape hatch for
host-side custom code; device-side custom kernels are written in pallas
instead, see ops/attention.py).

Expected C symbol:  void <name>(const float* x, float* out, long long n)
(elementwise contract; richer signatures can be bound manually from the
returned ctypes library).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile

import numpy as np

_LOADED = {}


def load(name, sources, extra_cxx_cflags=None, build_directory=None,
         verbose=False):
    """Compile `sources` (.cc/.cpp) into <build>/<name>.so and dlopen it.
    Returns the ctypes CDLL."""
    flags = tuple(extra_cxx_cflags or [])
    key = (name, tuple(sources), flags, build_directory)
    if key in _LOADED:
        return _LOADED[key]
    build = build_directory or os.path.join(
        tempfile.gettempdir(), "paddle_tpu_extensions")
    os.makedirs(build, exist_ok=True)
    tag = hashlib.md5(("".join(
        open(s).read() for s in sources) +
        "|".join(flags)).encode()).hexdigest()[:10]
    so = os.path.join(build, f"{name}_{tag}.so")
    if not os.path.exists(so):
        cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-shared",
               *list(sources), *(extra_cxx_cflags or []), "-o", so]
        if verbose:
            print("[cpp_extension]", " ".join(cmd))
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"custom op build failed:\n{proc.stderr[-2000:]}")
    lib = ctypes.CDLL(so)
    _LOADED[key] = lib
    return lib


def register_custom_op(op_name, lib, symbol=None):
    """Bind lib.<symbol> (elementwise float contract) as:
      - an eager callable paddle-style: fn(tensor) -> tensor
      - a static op lowering of type `op_name` (inputs {X}, outputs {Out})
    The kernel runs on HOST via jax.pure_callback, so it composes with
    jit/grad-free graphs (reference custom ops are likewise opaque to
    autodiff unless a grad kernel is registered)."""
    fn_c = getattr(lib, symbol or op_name)
    fn_c.restype = None
    fn_c.argtypes = [ctypes.POINTER(ctypes.c_float),
                     ctypes.POINTER(ctypes.c_float), ctypes.c_longlong]

    def host_kernel(x):
        x = np.ascontiguousarray(x, np.float32)
        out = np.empty_like(x)
        fn_c(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
             out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
             x.size)
        return out

    def jax_op(x):
        import jax

        return jax.pure_callback(
            host_kernel, jax.ShapeDtypeStruct(x.shape, np.float32),
            x.astype(np.float32))

    # eager surface
    def eager(x):
        from ..core.tensor import Tensor

        raw = x._data if isinstance(x, Tensor) else x
        return Tensor._wrap(jax_op(raw))

    # static lowering
    from ..fluid import lowering

    @lowering.register(op_name)
    def _lower(ctx, op):  # noqa: F811
        ctx.out(op, "Out", jax_op(ctx.inp(op, "X")))

    # fluid layer sugar
    def layer(x, name=None):
        from ..fluid.layer_helper import LayerHelper

        helper = LayerHelper(op_name, name=name)
        out = helper.create_variable_for_type_inference(x.dtype, x.shape)
        helper.append_op(type=op_name, inputs={"X": [x]},
                         outputs={"Out": [out]}, attrs={})
        return out

    eager.static_layer = layer
    return eager
