from . import flags  # noqa: F401
from .flags import get_flags, set_flags  # noqa: F401


def try_import(name):
    import importlib

    try:
        return importlib.import_module(name)
    except ImportError as e:
        raise ImportError(f"optional dependency {name} not available: {e}")


def unique_name(prefix="tmp"):
    from ..nn.layer.layers import _unique_name

    return _unique_name(prefix)
