"""Runtime flag registry.

Reference parity: platform/flags.cc (29 gflags) + fluid.set_flags/get_flags
(framework.py:5576/5599) + pybind/global_value_getter_setter.cc. TPU-native:
most allocator/cudnn flags are meaningless under XLA; we keep the registry,
honour the semantically relevant ones, and accept-and-ignore the rest so
reference programs run unmodified. FLAGS_* env vars are read at import.
"""
from __future__ import annotations

import os

_FLAGS = {
    # kept + honoured
    "FLAGS_check_nan_inf": False,            # debug_nans equivalent
    "FLAGS_cudnn_deterministic": False,      # maps to XLA deterministic ops
    "FLAGS_benchmark": False,
    "FLAGS_eager_delete_tensor_gb": 0.0,     # XLA owns buffers; accepted
    "FLAGS_allocator_strategy": "auto_growth",
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,
    "FLAGS_paddle_num_threads": 1,
    "FLAGS_use_pinned_memory": True,
    "FLAGS_seed": 0,
    "FLAGS_max_inplace_grad_add": 0,
    "FLAGS_sync_nccl_allreduce": True,
    "FLAGS_communicator_max_merge_var_num": 20,
    "FLAGS_communicator_send_queue_size": 20,
    "FLAGS_communicator_independent_recv_thread": True,
    "FLAGS_rpc_deadline": 180000,
    "FLAGS_rpc_retry_times": 3,
}


def _coerce(cur, val):
    if isinstance(cur, bool):
        return str(val).lower() in ("1", "true", "yes", "on")
    if isinstance(cur, int):
        return int(val)
    if isinstance(cur, float):
        return float(val)
    return val


for _k in list(_FLAGS):
    if _k in os.environ:
        _FLAGS[_k] = _coerce(_FLAGS[_k], os.environ[_k])


def set_flags(flags: dict):
    for k, v in flags.items():
        if k in _FLAGS:
            _FLAGS[k] = _coerce(_FLAGS[k], v)
        else:
            _FLAGS[k] = v
        if k == "FLAGS_check_nan_inf":
            _apply_nan_check()


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    return {k: _FLAGS.get(k) for k in flags}


def flag(name, default=None):
    return _FLAGS.get(name, default)


def _apply_nan_check():
    import jax

    jax.config.update("jax_debug_nans", bool(_FLAGS["FLAGS_check_nan_inf"]))
