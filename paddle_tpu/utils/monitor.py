"""Runtime stats registry (platform/monitor.h:76 StatRegistry parity).

Thread-safe named counters/gauges exported process-wide — the
reference's VT memory stats / communicator counters surface. Values are
plain ints/floats updated from Python or native callers via the update
helpers; `export()` snapshots everything for logging.
"""
from __future__ import annotations

import threading
import time


class _Stat:
    __slots__ = ("value", "_mu")

    def __init__(self):
        self.value = 0
        self._mu = threading.Lock()

    def add(self, v=1):
        with self._mu:
            self.value += v
            return self.value

    def set(self, v):
        with self._mu:
            self.value = v

    def get(self):
        with self._mu:
            return self.value


class StatRegistry:
    _instance = None
    _cls_mu = threading.Lock()

    def __init__(self):
        self._stats = {}
        self._mu = threading.Lock()

    @classmethod
    def instance(cls):
        with cls._cls_mu:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def stat(self, name):
        with self._mu:
            s = self._stats.get(name)
            if s is None:
                s = self._stats[name] = _Stat()
            return s

    def update(self, name, increment=1):
        return self.stat(name).add(increment)

    def set(self, name, value):
        self.stat(name).set(value)

    def get(self, name):
        with self._mu:
            s = self._stats.get(name)
        return s.get() if s is not None else 0

    def export(self):
        with self._mu:
            items = list(self._stats.items())
        return {k: s.get() for k, s in items}

    def reset(self):
        with self._mu:
            self._stats.clear()


def stat_update(name, increment=1):
    """STAT_ADD macro parity."""
    return StatRegistry.instance().update(name, increment)


def stat_set(name, value):
    StatRegistry.instance().set(name, value)


def get_stats():
    """pybind global getter parity: snapshot of every stat."""
    return StatRegistry.instance().export()


class Timer:
    """RecordEvent-adjacent scoped timer feeding a stat (microseconds)."""

    def __init__(self, name):
        self.name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        us = int((time.perf_counter() - self._t0) * 1e6)
        stat_update(self.name + ".total_us", us)
        stat_update(self.name + ".count", 1)
        return False
