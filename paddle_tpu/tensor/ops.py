"""Eager functional API: paddle.* tensor functions.

Reference parity: python/paddle/tensor/ (7.7k LoC op wrappers) and the
generated core.ops.* entry points (pybind/op_function_generator.cc:204).
TPU-native design: each function unwraps Tensors, runs the pure-jnp kernel
through the autograd tape (core/tensor.py apply_op), and wraps results.
"""
from __future__ import annotations

import numpy as np

from ..core import random as _random
from ..core.dtypes import convert_dtype, get_default_dtype
from ..core.tensor import Tensor, apply_op, to_tensor
from ..ops import kernels as K


def _jnp():
    import jax.numpy as jnp

    return jnp


def _t(x, dtype=None):
    if isinstance(x, Tensor):
        return x
    return Tensor(x, dtype=dtype)


def _op(name, fn, *tensors, n_outputs=1):
    return apply_op(name, fn, [_t(x) for x in tensors], n_outputs=n_outputs)


# ----------------------------- creation -----------------------------

def zeros(shape, dtype=None):
    dt = convert_dtype(dtype) or get_default_dtype()
    return Tensor._wrap(_jnp().zeros(_shape(shape), dt))


def ones(shape, dtype=None):
    dt = convert_dtype(dtype) or get_default_dtype()
    return Tensor._wrap(_jnp().ones(_shape(shape), dt))


def full(shape, fill_value, dtype=None):
    dt = convert_dtype(dtype) or get_default_dtype()
    return Tensor._wrap(_jnp().full(_shape(shape), fill_value, dt))


def zeros_like(x, dtype=None):
    return Tensor._wrap(_jnp().zeros_like(_t(x)._data,
                                          dtype=convert_dtype(dtype)))


def ones_like(x, dtype=None):
    return Tensor._wrap(_jnp().ones_like(_t(x)._data,
                                         dtype=convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None):
    return Tensor._wrap(_jnp().full_like(_t(x)._data, fill_value,
                                         dtype=convert_dtype(dtype)))


def arange(start=0, end=None, step=1, dtype=None):
    dt = convert_dtype(dtype)
    if end is None:
        start, end = 0, start
    if dt is None:
        dt = np.int64 if all(
            isinstance(v, (int, np.integer)) for v in (start, end, step)) \
            else get_default_dtype()
    return Tensor._wrap(_jnp().arange(start, end, step, dtype=dt))


def linspace(start, stop, num, dtype=None):
    dt = convert_dtype(dtype) or get_default_dtype()
    return Tensor._wrap(_jnp().linspace(start, stop, int(num), dtype=dt))


def eye(num_rows, num_columns=None, dtype=None):
    dt = convert_dtype(dtype) or get_default_dtype()
    return Tensor._wrap(_jnp().eye(num_rows, num_columns, dtype=dt))


def empty(shape, dtype=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None):
    return zeros_like(x, dtype)


def diag(x, offset=0, padding_value=0.0):
    return _op("diag", lambda a: K.diag(a, offset, padding_value), x)


def clone(x):
    return _t(x).clone()


def assign(x, output=None):
    src = _t(x)
    if output is not None:
        output.set_value(src)
        return output
    return src.clone()


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._data if isinstance(s, Tensor) else s) for s in shape)


# ----------------------------- random -----------------------------

def rand(shape, dtype=None):
    dt = convert_dtype(dtype) or get_default_dtype()
    return Tensor._wrap(K.uniform(_random.next_key(), _shape(shape), dt, 0.0,
                                  1.0))


def randn(shape, dtype=None):
    dt = convert_dtype(dtype) or get_default_dtype()
    return Tensor._wrap(K.gaussian(_random.next_key(), _shape(shape), dt))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0):
    dt = convert_dtype(dtype) or get_default_dtype()
    return Tensor._wrap(K.uniform(_random.next_key(), _shape(shape), dt, min,
                                  max))


def normal(mean=0.0, std=1.0, shape=None):
    dt = get_default_dtype()
    return Tensor._wrap(K.gaussian(_random.next_key(), _shape(shape), dt,
                                   mean, std))


def randint(low=0, high=None, shape=(1,), dtype="int64"):
    if high is None:
        low, high = 0, low
    return Tensor._wrap(K.randint(_random.next_key(), low, high,
                                  _shape(shape), convert_dtype(dtype)))


def randperm(n, dtype="int64"):
    return Tensor._wrap(K.randperm(_random.next_key(), n,
                                   convert_dtype(dtype)))


def bernoulli(x):
    import jax

    t = _t(x)
    return Tensor._wrap(jax.random.bernoulli(
        _random.next_key(), t._data, t._data.shape).astype(t._data.dtype))


def multinomial(x, num_samples=1, replacement=False):
    import jax

    t = _t(x)
    p = t._data / t._data.sum(axis=-1, keepdims=True)
    key = _random.next_key()
    logits = _jnp().log(_jnp().clip(p, 1e-30, None))
    if replacement or num_samples == 1:
        out = jax.random.categorical(key, logits,
                                     shape=(num_samples,) + t._data.shape[:-1])
        out = _jnp().moveaxis(out, 0, -1)
    else:
        g = -_jnp().log(-_jnp().log(
            jax.random.uniform(key, t._data.shape, dtype=_jnp().float32)))
        _, out = K.topk(logits + g, num_samples)
    return Tensor._wrap(out.astype(_jnp().int64))


# ----------------------------- math -----------------------------

def _unary(name, fn):
    def f(x, name_=None, **kw):
        return _op(name, fn, x)

    f.__name__ = name
    return f


def _unary_attr(name, fn):
    def f(x, *args, **kw):
        return _op(name, lambda a: fn(a, *args, **kw), x)

    f.__name__ = name
    return f


exp = _unary("exp", lambda x: _jnp().exp(x))
log = _unary("log", lambda x: _jnp().log(x))
log2 = _unary("log2", lambda x: _jnp().log2(x))
log10 = _unary("log10", lambda x: _jnp().log10(x))
log1p = _unary("log1p", lambda x: _jnp().log1p(x))
sqrt = _unary("sqrt", lambda x: _jnp().sqrt(x))
rsqrt = _unary("rsqrt", lambda x: 1.0 / _jnp().sqrt(x))
square = _unary("square", lambda x: x * x)
abs = _unary("abs", lambda x: _jnp().abs(x))  # noqa: A001
floor = _unary("floor", lambda x: _jnp().floor(x))
ceil = _unary("ceil", lambda x: _jnp().ceil(x))
round = _unary("round", lambda x: _jnp().round(x))  # noqa: A001
sin = _unary("sin", lambda x: _jnp().sin(x))
cos = _unary("cos", lambda x: _jnp().cos(x))
tan = _unary("tan", lambda x: _jnp().tan(x))
asin = _unary("asin", lambda x: _jnp().arcsin(x))
acos = _unary("acos", lambda x: _jnp().arccos(x))
atan = _unary("atan", lambda x: _jnp().arctan(x))
sinh = _unary("sinh", lambda x: _jnp().sinh(x))
cosh = _unary("cosh", lambda x: _jnp().cosh(x))
tanh = _unary("tanh", lambda x: _jnp().tanh(x))
erf = _unary("erf", lambda x: __import__("jax").scipy.special.erf(x))
sign = _unary("sign", lambda x: _jnp().sign(x))
reciprocal = _unary("reciprocal", lambda x: 1.0 / x)
neg = _unary("neg", lambda x: -x)
logit = _unary("logit", lambda x: _jnp().log(x / (1.0 - x)))
expm1 = _unary("expm1", lambda x: _jnp().expm1(x))
digamma = _unary("digamma", lambda x: __import__("jax").scipy.special.digamma(x))
lgamma = _unary("lgamma", lambda x: __import__("jax").scipy.special.gammaln(x))
trunc = _unary("trunc", lambda x: _jnp().trunc(x))
frac = _unary("frac", lambda x: x - _jnp().trunc(x))
isnan = _unary("isnan", lambda x: _jnp().isnan(x))
isinf = _unary("isinf", lambda x: _jnp().isinf(x))
isfinite = _unary("isfinite", lambda x: _jnp().isfinite(x))


def add(x, y, name=None):
    return _t(x) + y


def subtract(x, y, name=None):
    return _t(x) - y


def multiply(x, y, name=None):
    return _t(x) * y


def divide(x, y, name=None):
    return _t(x) / y


def floor_divide(x, y, name=None):
    return _t(x) // y


def remainder(x, y, name=None):
    return _t(x) % y


mod = remainder


def pow(x, y, name=None):  # noqa: A001
    return _t(x) ** (y if not isinstance(y, Tensor) else y)


def maximum(x, y, name=None):
    return _op("maximum", K.maximum, x, y)


def minimum(x, y, name=None):
    return _op("minimum", K.minimum, x, y)


def fmax(x, y, name=None):
    return _op("fmax", lambda a, b: _jnp().fmax(a, b), x, y)


def fmin(x, y, name=None):
    return _op("fmin", lambda a, b: _jnp().fmin(a, b), x, y)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    out = _op("scale", lambda a: K.scale(a, scale, bias, bias_after_scale), x)
    if act:
        out = globals()[act](out)
    return out


def clip(x, min=None, max=None, name=None):
    mn = float(min) if isinstance(min, (int, float)) else (
        min._data if isinstance(min, Tensor) else min)
    mx = float(max) if isinstance(max, (int, float)) else (
        max._data if isinstance(max, Tensor) else max)
    return _op("clip", lambda a: K.clip(a, mn, mx), x)


def add_n(inputs):
    if isinstance(inputs, Tensor):
        return inputs

    def _sum_all(*xs):
        out = xs[0]
        for v in xs[1:]:
            out = out + v
        return out

    return _op("add_n", _sum_all, *inputs)


def multiply_list(xs):
    out = xs[0]
    for x in xs[1:]:
        out = out * x
    return out


def atan2(x, y):
    return _op("atan2", lambda a, b: _jnp().arctan2(a, b), x, y)


def hypot(x, y):
    return _op("hypot", lambda a, b: _jnp().hypot(a, b), x, y)


def lerp(x, y, weight):
    if isinstance(weight, Tensor):
        return _op("lerp", lambda a, b, w: a + w * (b - a), x, y, weight)
    return _op("lerp", lambda a, b: a + weight * (b - a), x, y)


def stanh(x, scale_a=0.67, scale_b=1.7159):
    return _op("stanh", lambda a: scale_b * _jnp().tanh(scale_a * a), x)


# ----------------------------- reductions -----------------------------

def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    dt = convert_dtype(dtype)
    def fn(a):
        out = K.reduce_sum(a, axis, keepdim)
        return out.astype(dt) if dt is not None else out
    return _op("reduce_sum", fn, x)


def mean(x, axis=None, keepdim=False, name=None):
    return _op("reduce_mean", lambda a: K.reduce_mean(a, axis, keepdim), x)


def max(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return _op("reduce_max", lambda a: K.reduce_max(a, axis, keepdim), x)


def min(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return _op("reduce_min", lambda a: K.reduce_min(a, axis, keepdim), x)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    return _op("reduce_prod", lambda a: K.reduce_prod(a, axis, keepdim), x)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return _op("logsumexp", lambda a: K.logsumexp(a, axis, keepdim), x)


def all(x, axis=None, keepdim=False, name=None):  # noqa: A001
    t = _t(x)
    return Tensor._wrap(t._data.all(axis=K._norm_axis(axis), keepdims=keepdim))


def any(x, axis=None, keepdim=False, name=None):  # noqa: A001
    t = _t(x)
    return Tensor._wrap(t._data.any(axis=K._norm_axis(axis), keepdims=keepdim))


def std(x, axis=None, unbiased=True, keepdim=False):
    return _op("std", lambda a: _jnp().std(
        a, axis=K._norm_axis(axis), ddof=1 if unbiased else 0,
        keepdims=keepdim), x)


def var(x, axis=None, unbiased=True, keepdim=False):
    return _op("var", lambda a: _jnp().var(
        a, axis=K._norm_axis(axis), ddof=1 if unbiased else 0,
        keepdims=keepdim), x)


def median(x, axis=None, keepdim=False):
    return _op("median", lambda a: _jnp().median(
        a, axis=K._norm_axis(axis), keepdims=keepdim), x)


def quantile(x, q, axis=None, keepdim=False):
    return _op("quantile", lambda a: _jnp().quantile(
        a, q, axis=K._norm_axis(axis), keepdims=keepdim), x)


def cumsum(x, axis=None, dtype=None, name=None):
    return _op("cumsum", lambda a: K.cumsum(a, axis), x)


def cumprod(x, dim=None, dtype=None, name=None):
    return _op("cumprod", lambda a: K.cumprod(a, dim), x)


def count_nonzero(x, axis=None, keepdim=False):
    t = _t(x)
    return Tensor._wrap(_jnp().count_nonzero(
        t._data, axis=K._norm_axis(axis), keepdims=keepdim))


def nansum(x, axis=None, keepdim=False):
    return _op("nansum", lambda a: _jnp().nansum(
        a, axis=K._norm_axis(axis), keepdims=keepdim), x)


def nanmean(x, axis=None, keepdim=False):
    return _op("nanmean", lambda a: _jnp().nanmean(
        a, axis=K._norm_axis(axis), keepdims=keepdim), x)


def amax(x, axis=None, keepdim=False):
    return max(x, axis, keepdim)


def amin(x, axis=None, keepdim=False):
    return min(x, axis, keepdim)


# ----------------------------- linalg -----------------------------

def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return _op("matmul",
               lambda a, b: K.matmul(a, b, transpose_x, transpose_y), x, y)


def mm(x, y):
    return matmul(x, y)


def bmm(x, y):
    return _op("bmm", K.bmm, x, y)


def dot(x, y):
    return _op("dot", K.dot, x, y)


def t(x):
    return _op("t", K.t, x)


def transpose(x, perm, name=None):
    return _op("transpose", lambda a: K.transpose(a, perm), x)


def norm(x, p=2, axis=None, keepdim=False, name=None):
    return _op("norm", lambda a: K.norm(a, p, K._norm_axis(axis), keepdim), x)


def dist(x, y, p=2):
    return _op("dist", lambda a, b: K.norm(a - b, p), x, y)


def cross(x, y, axis=None):
    return _op("cross",
               lambda a, b: _jnp().cross(a, b, axis=axis if axis is not None
                                         else -1), x, y)


def matrix_power(x, n):
    return _op("matrix_power",
               lambda a: _jnp().linalg.matrix_power(a, n), x)


def einsum(eq, *xs):
    return _op("einsum", lambda *a: K.einsum(eq, *a), *xs)


def tril(x, diagonal=0):
    return _op("tril", lambda a: K.tril(a, diagonal), x)


def triu(x, diagonal=0):
    return _op("triu", lambda a: K.triu(a, diagonal), x)


def kron(x, y):
    return _op("kron", lambda a, b: _jnp().kron(a, b), x, y)


def outer(x, y):
    return _op("outer", lambda a, b: _jnp().outer(a, b), x, y)


def inner(x, y):
    return _op("inner", lambda a, b: _jnp().inner(a, b), x, y)


def trace(x, offset=0, axis1=0, axis2=1):
    return _op("trace", lambda a: _jnp().trace(a, offset, axis1, axis2), x)


# ----------------------------- manipulation -----------------------------

def reshape(x, shape, name=None):
    return _op("reshape", lambda a: K.reshape(a, _shape_dyn(shape)), x)


def _shape_dyn(shape):
    out = []
    for s in (shape if isinstance(shape, (list, tuple)) else [shape]):
        if isinstance(s, Tensor):
            out.append(int(s._data))
        else:
            out.append(int(s))
    return out


def concat(x, axis=0, name=None):
    axis = int(axis._data) if isinstance(axis, Tensor) else int(axis)
    return _op("concat", lambda *xs: K.concat(list(xs), axis), *x)


def split(x, num_or_sections, axis=0, name=None):
    t_ = _t(x)
    axis = int(axis._data) if isinstance(axis, Tensor) else int(axis)
    if isinstance(num_or_sections, int):
        n = num_or_sections
    else:
        n = len(num_or_sections)
    return _op("split", lambda a: tuple(K.split(a, num_or_sections, axis)),
               t_, n_outputs=n)


def chunk(x, chunks, axis=0):
    return split(x, chunks, axis)


def stack(x, axis=0, name=None):
    return _op("stack", lambda *xs: K.stack(list(xs), axis), *x)


def unstack(x, axis=0, num=None):
    t_ = _t(x)
    n = num if num is not None else t_.shape[axis]
    return _op("unstack", lambda a: tuple(K.unstack(a, axis)), t_,
               n_outputs=n)


def unbind(x, axis=0):
    return unstack(x, axis)


def squeeze(x, axis=None, name=None):
    return _op("squeeze", lambda a: K.squeeze(a, axis), x)


def unsqueeze(x, axis, name=None):
    return _op("unsqueeze", lambda a: K.unsqueeze(a, axis), x)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    return _op("flatten", lambda a: K.flatten(a, start_axis, stop_axis), x)


def expand(x, shape, name=None):
    return _op("expand", lambda a: K.expand(a, _shape_dyn(shape)), x)


def expand_as(x, y, name=None):
    return _op("expand_as", K.expand_as, x, y)


def broadcast_to(x, shape, name=None):
    return _op("broadcast_to", lambda a: K.broadcast_to(a, _shape_dyn(shape)),
               x)


def tile(x, repeat_times, name=None):
    return _op("tile", lambda a: K.tile(a, _shape_dyn(repeat_times)), x)


def slice(x, axes, starts, ends):  # noqa: A001
    starts = [int(s._data) if isinstance(s, Tensor) else int(s)
              for s in starts]
    ends = [int(e._data) if isinstance(e, Tensor) else int(e) for e in ends]
    return _op("slice", lambda a: K.slice_op(a, axes, starts, ends), x)


def strided_slice(x, axes, starts, ends, strides):
    return _op("strided_slice",
               lambda a: K.strided_slice(a, axes, starts, ends, strides), x)


def gather(x, index, axis=0, name=None):
    return _op("gather", lambda a, i: K.gather(a, i, axis), x, index)


def gather_nd(x, index, name=None):
    return _op("gather_nd", K.gather_nd, x, index)


def scatter(x, index, updates, overwrite=True, name=None):
    return _op("scatter",
               lambda a, i, u: K.scatter(a, i, u, overwrite), x, index,
               updates)


def scatter_nd_add(x, index, updates, name=None):
    return _op("scatter_nd_add", K.scatter_nd_add, x, index, updates)


def index_select(x, index, axis=0, name=None):
    return _op("index_select", lambda a, i: K.index_select(a, i, axis), x,
               index)


def index_sample(x, index):
    return _op("index_sample", K.index_sample, x, index)


def masked_select(x, mask, name=None):
    return _op("masked_select", K.masked_select, x, mask)


def masked_fill(x, mask, value):
    return _op("masked_fill",
               lambda a, m: _jnp().where(m, value, a), x, mask)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return _op("where", lambda c, a, b: K.where(c, a, b), condition, x, y)


def nonzero(x, as_tuple=False):
    t_ = _t(x)
    out = K.nonzero(t_._data)
    if as_tuple:
        return tuple(Tensor._wrap(out[:, i]) for i in range(out.shape[1]))
    return Tensor._wrap(out)


def pad(x, paddings, mode="constant", value=0.0, data_format="NCHW",
        name=None):
    pads = [int(p._data) if isinstance(p, Tensor) else int(p)
            for p in paddings]
    return _op("pad", lambda a: K.pad(a, pads, mode, value), x)


def roll(x, shifts, axis=None, name=None):
    return _op("roll", lambda a: K.roll(a, shifts, axis), x)


def flip(x, axis, name=None):
    return _op("flip", lambda a: K.flip(a, axis), x)


def rot90(x, k=1, axes=(0, 1)):
    return _op("rot90", lambda a: _jnp().rot90(a, k, axes), x)


def cast(x, dtype):
    return _t(x).astype(dtype)


def crop(x, shape=None, offsets=None):
    import builtins

    t_ = _t(x)
    offsets = offsets or [0] * t_.ndim
    idx = tuple(builtins.slice(int(o), int(o) + int(s))
                for o, s in zip(offsets, _shape_dyn(shape)))
    return _op("crop", lambda a: a[idx], t_)


def repeat_interleave(x, repeats, axis=None):
    return _op("repeat_interleave",
               lambda a: _jnp().repeat(a, repeats, axis=axis), x)


def moveaxis(x, source, destination):
    return _op("moveaxis",
               lambda a: _jnp().moveaxis(a, source, destination), x)


def swapaxes(x, axis0, axis1):
    return _op("swapaxes", lambda a: _jnp().swapaxes(a, axis0, axis1), x)


def as_real(x):
    return _op("as_real", lambda a: _jnp().stack(
        [a.real, a.imag], axis=-1), x)


def as_complex(x):
    return _op("as_complex", lambda a: a[..., 0] + 1j * a[..., 1], x)


def meshgrid(*xs):
    ts = [_t(x) for x in xs]
    return _op("meshgrid", lambda *a: tuple(K.meshgrid(*a)), *ts,
               n_outputs=len(ts))


def atleast_1d(*xs):
    outs = [_op("atleast_1d", lambda a: _jnp().atleast_1d(a), x) for x in xs]
    return outs[0] if len(outs) == 1 else outs


def diff(x, n=1, axis=-1):
    return _op("diff", lambda a: _jnp().diff(a, n=n, axis=axis), x)


def take_along_axis(x, indices, axis):
    return _op("take_along_axis",
               lambda a, i: _jnp().take_along_axis(
                   a, i.astype(_jnp().int32), axis=axis), x, indices)


def put_along_axis(x, indices, values, axis):
    def fn(a, i, v):
        jnp = _jnp()
        return _jnp_put_along_axis(a, i.astype(jnp.int32), v, axis)
    return _op("put_along_axis", fn, x, indices, values)


def _jnp_put_along_axis(a, idx, v, axis):
    jnp = _jnp()
    idxs = list(jnp.meshgrid(*[jnp.arange(s) for s in idx.shape],
                             indexing="ij"))
    idxs[axis] = idx
    return a.at[tuple(idxs)].set(v)


# ----------------------------- search/sort -----------------------------

def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    t_ = _t(x)
    return Tensor._wrap(K.argmax(t_._data, axis, keepdim, convert_dtype(dtype)))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    t_ = _t(x)
    return Tensor._wrap(K.argmin(t_._data, axis, keepdim, convert_dtype(dtype)))


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):  # noqa: A002
    kk = int(k._data) if isinstance(k, Tensor) else int(k)
    return _op("topk", lambda a: K.topk(a, kk, axis, largest, sorted), x,
               n_outputs=2)


def argsort(x, axis=-1, descending=False, name=None):
    t_ = _t(x)
    return Tensor._wrap(K.argsort(t_._data, axis, descending))


def sort(x, axis=-1, descending=False, name=None):  # noqa: A001
    return _op("sort", lambda a: K.sort(a, axis, descending), x)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64"):
    t_ = _t(x)
    out = K.unique(t_._data, return_index, return_inverse, return_counts)
    if isinstance(out, tuple):
        return tuple(Tensor._wrap(o) for o in out)
    return Tensor._wrap(out)


def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    t_ = _t(sorted_sequence)
    v = _t(values)
    out = K.searchsorted(t_._data, v._data, right)
    return Tensor._wrap(out.astype(_jnp().int32 if out_int32 else
                                   _jnp().int64))


def histogram(x, bins=100, min=0, max=0):
    t_ = _t(x)
    rng = None if (min == 0 and max == 0) else (min, max)
    h, _ = _jnp().histogram(t_._data, bins=bins, range=rng)
    return Tensor._wrap(h)


def bincount(x, weights=None, minlength=0):
    t_ = _t(x)
    w = _t(weights)._data if weights is not None else None
    return Tensor._wrap(_jnp().bincount(t_._data, w, minlength=minlength))


def mode(x, axis=-1, keepdim=False):
    t_ = _t(x)
    import scipy.stats  # noqa - fallback via numpy

    arr = np.asarray(t_._data)
    vals, counts = scipy.stats.mode(arr, axis=axis, keepdims=keepdim)
    return Tensor._wrap(_jnp().asarray(vals)), Tensor._wrap(
        _jnp().asarray(counts))


def kthvalue(x, k, axis=-1, keepdim=False):
    t_ = _t(x)
    jnp = _jnp()
    s = jnp.sort(t_._data, axis=axis)
    i = jnp.argsort(t_._data, axis=axis)
    v = jnp.take(s, k - 1, axis=axis)
    ix = jnp.take(i, k - 1, axis=axis)
    if keepdim:
        v = jnp.expand_dims(v, axis)
        ix = jnp.expand_dims(ix, axis)
    return Tensor._wrap(v), Tensor._wrap(ix.astype(jnp.int64))


# ----------------------------- logic -----------------------------

def equal(x, y):
    return _t(x) == y


def not_equal(x, y):
    return _t(x) != y


def less_than(x, y):
    return _t(x) < y


def less_equal(x, y):
    return _t(x) <= y


def greater_than(x, y):
    return _t(x) > y


def greater_equal(x, y):
    return _t(x) >= y


def equal_all(x, y):
    return Tensor._wrap((_t(x)._data == _t(y)._data).all())


def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return Tensor._wrap(_jnp().allclose(_t(x)._data, _t(y)._data, rtol, atol,
                                        equal_nan))


def isclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return Tensor._wrap(_jnp().isclose(_t(x)._data, _t(y)._data, rtol, atol,
                                       equal_nan))


def logical_and(x, y, out=None):
    return Tensor._wrap(_jnp().logical_and(_t(x)._data, _t(y)._data))


def logical_or(x, y, out=None):
    return Tensor._wrap(_jnp().logical_or(_t(x)._data, _t(y)._data))


def logical_xor(x, y, out=None):
    return Tensor._wrap(_jnp().logical_xor(_t(x)._data, _t(y)._data))


def logical_not(x, out=None):
    return Tensor._wrap(_jnp().logical_not(_t(x)._data))


def bitwise_and(x, y):
    return Tensor._wrap(_jnp().bitwise_and(_t(x)._data, _t(y)._data))


def bitwise_or(x, y):
    return Tensor._wrap(_jnp().bitwise_or(_t(x)._data, _t(y)._data))


def bitwise_xor(x, y):
    return Tensor._wrap(_jnp().bitwise_xor(_t(x)._data, _t(y)._data))


def bitwise_not(x):
    return Tensor._wrap(_jnp().bitwise_not(_t(x)._data))


def is_empty(x):
    return Tensor._wrap(_jnp().asarray(_t(x)._data.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


# ----------------------------- misc -----------------------------

def numel(x):
    return Tensor._wrap(_jnp().asarray(_t(x)._data.size, dtype=_jnp().int64))


def shape(x):
    return Tensor._wrap(_jnp().asarray(_t(x)._data.shape, dtype=_jnp().int32))


def rank(x):
    return Tensor._wrap(_jnp().asarray(_t(x)._data.ndim, dtype=_jnp().int32))


def increment(x, value=1.0):
    x.set_value(x._data + value)
    return x


def one_hot(x, num_classes, name=None):
    return _op("one_hot", lambda a: K.one_hot(a, num_classes), x)


def multiplex(inputs, index, name=None):
    ts = [_t(i) for i in inputs]
    return _op("multiplex",
               lambda *args: K.multiplex(list(args[:-1]), args[-1]),
               *(ts + [_t(index)]))
