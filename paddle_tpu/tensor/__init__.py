from .ops import *  # noqa: F401,F403
from . import ops as _ops


def _patch_tensor_methods():
    """Attach functional ops as Tensor methods, mirroring the reference's
    monkey_patch_varbase (python/paddle/fluid/dygraph/varbase_patch_methods.py)."""
    from ..core.tensor import Tensor

    method_names = [
        "exp", "log", "log2", "log10", "log1p", "sqrt", "rsqrt", "square",
        "abs", "floor", "ceil", "round", "sin", "cos", "tan", "tanh", "erf",
        "sign", "reciprocal", "expm1", "isnan", "isinf", "isfinite",
        "add", "subtract", "multiply", "divide", "floor_divide", "remainder",
        "mod", "pow", "maximum", "minimum", "scale", "clip",
        "sum", "mean", "max", "min", "prod", "logsumexp", "all", "any",
        "std", "var", "median", "cumsum", "cumprod",
        "matmul", "mm", "bmm", "dot", "t", "transpose", "norm", "dist",
        "tril", "triu", "trace",
        "reshape", "concat", "split", "chunk", "squeeze", "unsqueeze",
        "flatten", "expand", "expand_as", "broadcast_to", "tile",
        "gather", "gather_nd", "scatter", "index_select", "masked_select",
        "roll", "flip", "unbind", "repeat_interleave", "moveaxis",
        "swapaxes", "take_along_axis",
        "argmax", "argmin", "topk", "argsort", "sort", "unique", "nonzero",
        "equal", "not_equal", "less_than", "less_equal", "greater_than",
        "greater_equal", "equal_all", "allclose", "isclose",
        "logical_and", "logical_or", "logical_xor", "logical_not",
        "numel", "rank", "one_hot", "where", "kthvalue",
    ]
    for name in method_names:
        fn = getattr(_ops, name, None)
        if fn is not None and not hasattr(Tensor, name):
            setattr(Tensor, name, fn)


_patch_tensor_methods()
