"""paddle.distribution parity (fluid/distribution.py: Uniform, Normal,
Categorical, MultivariateNormalDiag)."""
from __future__ import annotations

import math

import numpy as np

from ..core import random as _random
from ..core.tensor import Tensor
from ..tensor.ops import _t


def _jnp():
    import jax.numpy as jnp

    return jnp


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        import jax.numpy as jnp

        return Tensor._wrap(jnp.exp(self.log_prob(value)._data))

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)

    def sample(self, shape=(), seed=0):
        import jax

        jnp = _jnp()
        shape = tuple(shape) + tuple(jnp.broadcast_shapes(
            self.low._data.shape, self.high._data.shape))
        u = jax.random.uniform(_random.next_key(), shape,
                               dtype=(self.low._data.dtype
                                      if jnp.issubdtype(self.low._data.dtype,
                                                        jnp.floating)
                                      else jnp.float32))
        return Tensor._wrap(self.low._data + u * (self.high._data -
                                                  self.low._data))

    def log_prob(self, value):
        jnp = _jnp()
        v = _t(value)._data
        inside = (v >= self.low._data) & (v < self.high._data)
        lp = jnp.where(inside,
                       -jnp.log(self.high._data - self.low._data), -np.inf)
        return Tensor._wrap(lp)

    def entropy(self):
        jnp = _jnp()
        return Tensor._wrap(jnp.log(self.high._data - self.low._data))


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def sample(self, shape=(), seed=0):
        import jax

        jnp = _jnp()
        shape = tuple(shape) + tuple(jnp.broadcast_shapes(
            self.loc._data.shape, self.scale._data.shape))
        z = jax.random.normal(_random.next_key(), shape,
                              dtype=(self.loc._data.dtype
                                     if jnp.issubdtype(self.loc._data.dtype,
                                                       jnp.floating)
                                     else jnp.float32))
        return Tensor._wrap(self.loc._data + z * self.scale._data)

    def log_prob(self, value):
        jnp = _jnp()
        v = _t(value)._data
        var = self.scale._data ** 2
        return Tensor._wrap(-((v - self.loc._data) ** 2) / (2 * var)
                            - jnp.log(self.scale._data)
                            - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        jnp = _jnp()
        return Tensor._wrap(0.5 + 0.5 * math.log(2 * math.pi) +
                            jnp.log(self.scale._data))

    def kl_divergence(self, other):
        jnp = _jnp()
        var_ratio = (self.scale._data / other.scale._data) ** 2
        t1 = ((self.loc._data - other.loc._data) / other.scale._data) ** 2
        return Tensor._wrap(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _t(logits)

    def sample(self, shape=()):
        import jax

        out = jax.random.categorical(_random.next_key(), self.logits._data,
                                     shape=tuple(shape) +
                                     self.logits._data.shape[:-1])
        return Tensor._wrap(out)

    def log_prob(self, value):
        import jax

        jnp = _jnp()
        logp = jax.nn.log_softmax(self.logits._data)
        idx = _t(value)._data.astype(jnp.int32)
        return Tensor._wrap(jnp.take_along_axis(
            logp, idx[..., None], axis=-1)[..., 0])

    def entropy(self):
        import jax

        jnp = _jnp()
        logp = jax.nn.log_softmax(self.logits._data)
        p = jnp.exp(logp)
        return Tensor._wrap(-(p * logp).sum(axis=-1))


class Bernoulli(Distribution):
    def __init__(self, probs):
        self.p = _t(probs)

    def sample(self, shape=()):
        import jax

        out = jax.random.bernoulli(_random.next_key(), self.p._data,
                                   tuple(shape) + self.p._data.shape)
        return Tensor._wrap(out.astype(self.p._data.dtype))

    def log_prob(self, value):
        jnp = _jnp()
        v = _t(value)._data
        p = jnp.clip(self.p._data, 1e-7, 1 - 1e-7)
        return Tensor._wrap(v * jnp.log(p) + (1 - v) * jnp.log(1 - p))

    def entropy(self):
        jnp = _jnp()
        p = jnp.clip(self.p._data, 1e-7, 1 - 1e-7)
        return Tensor._wrap(-(p * jnp.log(p) + (1 - p) * jnp.log(1 - p)))


class MultivariateNormalDiag(Distribution):
    """Multivariate normal parameterized by loc [k] and a DIAGONAL
    COVARIANCE matrix scale [k, k] (reference
    fluid/layers/distributions.py:531 MultivariateNormalDiag — despite
    the name, its docstring and closed forms treat `scale` as the
    covariance). entropy()/kl_divergence() reproduce the reference's
    documented values; sample()/log_prob() are the natural diag-MVN
    extensions the reference lacked."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def _var(self):
        jnp = _jnp()
        return jnp.diagonal(self.scale._data, axis1=-2, axis2=-1)

    def sample(self, shape=(), seed=0):
        import jax

        jnp = _jnp()
        std = jnp.sqrt(self._var())
        shape = tuple(shape) + tuple(self.loc._data.shape)
        z = jax.random.normal(_random.next_key(), shape,
                              dtype=(self.loc._data.dtype
                                     if jnp.issubdtype(self.loc._data.dtype,
                                                       jnp.floating)
                                     else jnp.float32))
        return Tensor._wrap(self.loc._data + z * std)

    def log_prob(self, value):
        jnp = _jnp()
        v = _t(value)._data
        var = self._var()
        k = self.loc._data.shape[-1]
        y = (v - self.loc._data) ** 2 / var
        return Tensor._wrap(
            -0.5 * y.sum(-1) - 0.5 * jnp.log(var).sum(-1)
            - 0.5 * k * math.log(2 * math.pi))

    def entropy(self):
        # 0.5 * (k * (1 + log 2pi) + log det(cov))
        jnp = _jnp()
        var = self._var()
        k = self.loc._data.shape[-1]
        return Tensor._wrap(
            (0.5 * k * (1.0 + math.log(2 * math.pi))
             + 0.5 * jnp.log(var).sum(-1)).reshape(1))

    def kl_divergence(self, other):
        # KL(N(mu1, V1) || N(mu2, V2)), V diagonal covariances:
        # 0.5 * (tr(V2^-1 V1) - k + (mu2-mu1)^T V2^-1 (mu2-mu1)
        #        + log det V2 - log det V1)
        jnp = _jnp()
        if not isinstance(other, MultivariateNormalDiag):
            raise TypeError(
                "MultivariateNormalDiag.kl_divergence expects another "
                f"MultivariateNormalDiag, got {type(other).__name__}")
        v1 = self._var()
        v2 = other._var()
        mu1, mu2 = self.loc._data, _t(other.loc)._data
        k = mu1.shape[-1]
        return Tensor._wrap(
            (0.5 * ((v1 / v2).sum(-1) - k
                    + ((mu2 - mu1) ** 2 / v2).sum(-1)
                    + jnp.log(v2).sum(-1)
                    - jnp.log(v1).sum(-1))).reshape(1))


def kl_divergence(p, q):
    return p.kl_divergence(q)
