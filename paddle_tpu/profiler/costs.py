"""Cost/memory accounting over every jitted program the engines own.

PR 8 made the serving stack *traceable* (who did what, when); this
module makes it *accountable* (what did it cost). Three views, all
keyed by the SAME cache keys the engines' `trace_counts` counters and
the retrace sentinel already use — cost, compile, and trace records
join on one identity:

  * **Program costs** — while an accounting session is armed
    (`accounting_scope()`), every detected trace+compile re-lowers the
    program AOT and records XLA's `cost_analysis()` (flops, bytes
    accessed) and `memory_analysis()` (argument/output/temp/generated
    bytes) into the session's `CostBook`. When the backend returns
    nothing (or capture is disabled), the owner's `cost_hint(key)` —
    analytic flops/bytes for the known decode/prefill/join shapes —
    fills in, tagged ``source="analytic"``.
  * **DeviceSpec / MFU** — `mfu(flops, dt, spec)` and
    `bw_util(bytes, dt, spec)` turn per-step costs into
    model-flops-utilization and bandwidth-utilization gauges against a
    device roofline. `CPU_SPEC` ships for deterministic tests; real
    TPU generations are tabled in `DEVICE_SPECS` and `detect_spec()`
    picks by `device_kind`.
  * **HBM ledger plumbing** — `temp_high_water()` exposes the compile
    temp-buffer high-water across the book, which
    `ServingMetrics.snapshot()["memory"]` reports next to the
    weights/pool footprint the engines compute (see
    `ServingEngine.memory_ledger`).

Discipline (same as profiler.trace): a disarmed hot path pays ONE
module-global read (`costs._BOOK is None`). Armed capture happens only
at trace time — never on warm calls — and suppresses counter
observation during its deliberate re-lower so the retrace sentinel
stays silent.
"""
from __future__ import annotations

import contextlib
import threading

from . import trace as _trace

__all__ = [
    "DeviceSpec", "ProgramCost", "CostBook", "CPU_SPEC",
    "DEVICE_SPECS", "detect_spec", "start_accounting",
    "end_accounting", "accounting_scope", "book", "mfu", "bw_util",
    "temp_high_water", "transformer_decode_flops",
    "transformer_prefill_flops", "capture_compiled",
]


class DeviceSpec:
    """Peak-rate roofline for one accelerator generation: the
    denominators of the MFU / bandwidth-utilization gauges plus the HBM
    capacity the memory ledger budgets against."""

    __slots__ = ("name", "peak_flops", "peak_bytes_per_s", "hbm_bytes")

    def __init__(self, name, peak_flops, peak_bytes_per_s, hbm_bytes):
        self.name = name
        self.peak_flops = float(peak_flops)
        self.peak_bytes_per_s = float(peak_bytes_per_s)
        self.hbm_bytes = int(hbm_bytes)

    def as_dict(self):
        return {"name": self.name,
                "peak_tflops": round(self.peak_flops / 1e12, 3),
                "peak_gbps": round(self.peak_bytes_per_s / 1e9, 1),
                "hbm_gb": round(self.hbm_bytes / 2**30, 1)}

    def __repr__(self):
        return (f"DeviceSpec({self.name!r}, "
                f"{self.peak_flops / 1e12:.2f} TFLOP/s, "
                f"{self.peak_bytes_per_s / 1e9:.0f} GB/s)")


#: NOMINAL single-core CPU roofline — a fixed constant, not a
#: measurement, so MFU numbers in tests are deterministic functions of
#: (flops, dt). ~one AVX2 core: 8 lanes x 2 FMA ports x 2 flops @ 3GHz.
CPU_SPEC = DeviceSpec("cpu", 96e9, 40e9, 16 * 2**30)

#: per-chip published peaks (bf16 matmul flops, HBM bandwidth, HBM)
DEVICE_SPECS = {
    "cpu": CPU_SPEC,
    "TPU v2": DeviceSpec("TPU v2", 22.5e12, 700e9, 8 * 2**30),
    "TPU v3": DeviceSpec("TPU v3", 61.5e12, 900e9, 16 * 2**30),
    "TPU v4": DeviceSpec("TPU v4", 137.5e12, 1228e9, 32 * 2**30),
    "TPU v5e": DeviceSpec("TPU v5e", 98.5e12, 819e9, 16 * 2**30),
    "TPU v5p": DeviceSpec("TPU v5p", 229.5e12, 2765e9, 95 * 2**30),
}


def detect_spec(default=CPU_SPEC):
    """Spec for jax's default device by `device_kind` (prefix match, so
    "TPU v4 lite" variants resolve); `default` when unknown."""
    try:
        import jax

        kind = jax.devices()[0].device_kind
    except Exception:
        return default
    for name, spec in DEVICE_SPECS.items():
        if kind.lower().startswith(name.lower()):
            return spec
    return default


def mfu(flops, dt_s, spec):
    """Model-flops-utilization: achieved flop rate / the spec peak."""
    if dt_s <= 0:
        return 0.0
    return flops / dt_s / spec.peak_flops


def bw_util(bytes_accessed, dt_s, spec):
    """Achieved memory traffic / the spec's peak HBM bandwidth."""
    if dt_s <= 0:
        return 0.0
    return bytes_accessed / dt_s / spec.peak_bytes_per_s


# ----------------------------------------------------------------------
# analytic transformer costs (the CPU-safe fallback + hint vocabulary)
# ----------------------------------------------------------------------

def transformer_decode_flops(n_params, batch, kv_len, n_layers,
                             n_heads, head_dim, mem_len=0):
    """One decode step over `batch` rows: 2 flops per (dense param,
    row) for the matmul stack, plus attention reads over `kv_len` live
    keys (QK^T + AV = 4 per key position per head dim) and `mem_len`
    cross-attention keys."""
    dense = 2.0 * float(n_params) * batch
    attn = 4.0 * n_layers * batch * n_heads * head_dim * \
        (kv_len + mem_len)
    return dense + attn


def transformer_prefill_flops(n_params, batch, prompt_len, n_layers,
                              n_heads, head_dim, mem_len=0):
    """Prefill over a `prompt_len`-token (bucketed) prompt: the dense
    stack touches every token; self-attention is causal quadratic."""
    dense = 2.0 * float(n_params) * batch * prompt_len
    attn = 4.0 * n_layers * batch * n_heads * head_dim * \
        (prompt_len * (prompt_len + 1) / 2.0 + prompt_len * mem_len)
    return dense + attn


class ProgramCost:
    """Cost/memory record for ONE compiled program (one cache key)."""

    __slots__ = ("owner", "key", "flops", "bytes_accessed",
                 "argument_bytes", "output_bytes", "temp_bytes",
                 "generated_code_bytes", "compile_s", "source")

    def __init__(self, owner, key, *, flops=0.0, bytes_accessed=0.0,
                 argument_bytes=0, output_bytes=0, temp_bytes=0,
                 generated_code_bytes=0, compile_s=0.0, source="xla"):
        self.owner = owner
        self.key = key
        self.flops = float(flops)
        self.bytes_accessed = float(bytes_accessed)
        self.argument_bytes = int(argument_bytes)
        self.output_bytes = int(output_bytes)
        self.temp_bytes = int(temp_bytes)
        self.generated_code_bytes = int(generated_code_bytes)
        self.compile_s = float(compile_s)
        self.source = source

    def as_dict(self):
        return {"owner": self.owner, "key": _trace._key_str(self.key),
                "flops": self.flops,
                "bytes_accessed": self.bytes_accessed,
                "argument_bytes": self.argument_bytes,
                "output_bytes": self.output_bytes,
                "temp_bytes": self.temp_bytes,
                "generated_code_bytes": self.generated_code_bytes,
                "compile_s": round(self.compile_s, 4),
                "source": self.source}

    def __repr__(self):
        return (f"ProgramCost({self.owner}:{self.key!r}, "
                f"{self.flops:.3g} flops, "
                f"{self.bytes_accessed:.3g} B, {self.source})")


class CostBook:
    """Thread-safe {(owner_name, cache_key): ProgramCost} — the armed
    accounting session's sink. Keys are the engines' jit-cache /
    trace_counts keys verbatim, so cost records join the retrace
    sentinel's counters and the tracer's compile spans on one
    identity."""

    def __init__(self, spec=None, capture_xla=True):
        self.spec = spec if spec is not None else detect_spec()
        #: False: skip the AOT re-lower+compile and record analytic
        #: hints only (arming mid-serve without paying a second compile
        #: per not-yet-captured key)
        self.capture_xla = bool(capture_xla)
        self._lock = threading.Lock()
        self._costs = {}
        self.compiles = 0

    def get(self, owner_name, key):
        with self._lock:
            return self._costs.get((owner_name, key))

    def put(self, cost):
        with self._lock:
            self._costs[(cost.owner, cost.key)] = cost
        return cost

    def keys(self):
        with self._lock:
            return list(self._costs)

    def costs(self):
        with self._lock:
            return list(self._costs.values())

    def temp_high_water(self):
        """Peak XLA temp-buffer bytes across every recorded program:
        the compile-cache contribution to the HBM ledger (programs
        don't run concurrently, so the max — not the sum — is what the
        allocator must hold in reserve)."""
        with self._lock:
            return max((c.temp_bytes for c in self._costs.values()),
                       default=0)

    def report(self):
        """Rows sorted by flops, heaviest first (tools render this)."""
        with self._lock:
            rows = sorted(self._costs.values(),
                          key=lambda c: -c.flops)
        return [c.as_dict() for c in rows]


# ----------------------------------------------------------------------
# the armed accounting session
# ----------------------------------------------------------------------

#: the ONE global the hot paths read; None = accounting disarmed
_BOOK = None
_LOCK = threading.Lock()


def book():
    """The armed CostBook, or None."""
    return _BOOK


def _cost_from_compiled(owner_name, key, compiled, compile_s):
    """Pull XLA's cost/memory analyses off an ALREADY-compiled
    executable (no lowering, no trace). Shared by the warm-path
    re-lower capture and the startup precompile capture — AOT-loaded
    programs never compile through the observer, so precompile hands
    them here directly. Returns None when the backend can't answer."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        ca = None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict) or "flops" not in ca:
        return None
    cost = ProgramCost(
        owner_name, key,
        flops=ca.get("flops", 0.0),
        bytes_accessed=ca.get("bytes accessed", 0.0),
        compile_s=compile_s, source="xla")
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is not None:
        cost.argument_bytes = int(
            getattr(ma, "argument_size_in_bytes", 0))
        cost.output_bytes = int(getattr(ma, "output_size_in_bytes", 0))
        cost.temp_bytes = int(getattr(ma, "temp_size_in_bytes", 0))
        cost.generated_code_bytes = int(
            getattr(ma, "generated_code_size_in_bytes", 0))
    return cost


def _extract_xla(owner, key, fn, args, kw, compile_s):
    """AOT re-lower+compile the jitted `fn` at the observed call's
    arguments and pull XLA's cost/memory analyses. The deliberate
    second trace runs under `suppress_observation` with the trace
    counter restored, so neither the retrace sentinel nor session
    counters see it. Returns None when the backend can't answer."""
    counter = getattr(owner, "trace_counts", None)
    with _trace.suppress_observation():
        before = None if counter is None else counter[key]
        try:
            compiled = fn.lower(*args, **kw).compile()
        except Exception:
            return None
        finally:
            if counter is not None:
                counter[key] = before
    return _cost_from_compiled(type(owner).__name__, key, compiled,
                               compile_s)


def capture_compiled(owner, key, compiled, compile_s=0.0):
    """Record one startup-precompiled program into the armed book
    (no-op when accounting is disarmed). The engines' precompile()
    path calls this for every readied program — including
    cache-DESERIALIZED executables, which never pass through the
    compile observer because they never compile — so a warm start
    still arrives with a fully populated cost book. Falls back to the
    owner's analytic hint exactly like the warm-path capture."""
    bk = _BOOK
    if bk is None:
        return None
    name = type(owner).__name__
    if bk.get(name, key) is not None:
        return bk.get(name, key)
    cost = None
    if bk.capture_xla:
        cost = _cost_from_compiled(name, key, compiled, compile_s)
    if cost is None:
        cost = analytic_cost(owner, key, compile_s=compile_s)
    if cost is not None:
        bk.put(cost)
    return cost


def analytic_cost(owner, key, compile_s=0.0):
    """The owner's `cost_hint(key)` as a ProgramCost (source
    "analytic"), or None when the owner declines the key."""
    hint = getattr(owner, "cost_hint", None)
    if hint is None:
        return None
    try:
        h = hint(key)
    except Exception:
        return None
    if h is None:
        return None
    return ProgramCost(type(owner).__name__, key,
                       flops=h.get("flops", 0.0),
                       bytes_accessed=h.get("bytes_accessed", 0.0),
                       temp_bytes=h.get("temp_bytes", 0),
                       argument_bytes=h.get("argument_bytes", 0),
                       compile_s=compile_s, source="analytic")


def _on_compile(owner, key, fn, args, kw, t0, t1):
    bk = _BOOK
    if bk is None:
        return
    bk.compiles += 1
    name = type(owner).__name__
    if bk.get(name, key) is not None:
        return
    cost = None
    if bk.capture_xla:
        cost = _extract_xla(owner, key, fn, args, kw, t1 - t0)
    if cost is None:
        cost = analytic_cost(owner, key, compile_s=t1 - t0)
    if cost is not None:
        bk.put(cost)


def cost_for(owner, key):
    """The armed book's record for (owner, key), materializing the
    analytic fallback on first ask (programs compiled BEFORE arming
    have no capture; the hint keeps the MFU gauges live without
    forcing a recompile). None when disarmed or unknowable."""
    bk = _BOOK
    if bk is None:
        return None
    name = type(owner).__name__
    c = bk.get(name, key)
    if c is None:
        c = analytic_cost(owner, key)
        if c is not None:
            bk.put(c)
    return c


def start_accounting(spec=None, capture_xla=True, book=None):
    """Arm the module-wide accounting session: every trace+compile in
    any `trace.JitCache` is captured into the returned CostBook, and
    the engines' per-step MFU/goodput gauges start recording. One
    session at a time."""
    global _BOOK
    with _LOCK:
        if _BOOK is not None:
            raise RuntimeError("a cost-accounting session is already "
                               "armed; end_accounting() it first")
        _BOOK = book if book is not None else \
            CostBook(spec=spec, capture_xla=capture_xla)
        _trace.add_compile_hook(_on_compile)
        return _BOOK


def end_accounting():
    """Disarm; returns the CostBook (or None if nothing was armed)."""
    global _BOOK
    with _LOCK:
        bk = _BOOK
        _BOOK = None
        _trace.remove_compile_hook(_on_compile)
        return bk


@contextlib.contextmanager
def accounting_scope(spec=None, capture_xla=True):
    bk = start_accounting(spec=spec, capture_xla=capture_xla)
    try:
        yield bk
    finally:
        end_accounting()


def temp_high_water():
    """Compile temp high-water of the armed book (0 when disarmed)."""
    bk = _BOOK
    return 0 if bk is None else bk.temp_high_water()


def reset():
    """Disarm unconditionally (conftest teardown symmetry)."""
    global _BOOK
    with _LOCK:
        _BOOK = None
        _trace.remove_compile_hook(_on_compile)
