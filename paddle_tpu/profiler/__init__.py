"""Profiler.

Reference parity: platform/profiler.h:126 RecordEvent RAII +
fluid/profiler.py (start_profiler/stop_profiler/profiler context). TPU-native
design: host annotations forward to jax.profiler.TraceAnnotation; device
timelines come from the XLA/XPlane trace (`start_profiler` starts a
jax.profiler trace whose output loads in TensorBoard / Perfetto — the
chrome://tracing equivalent of platform/device_tracer.cc).
"""
from __future__ import annotations

import contextlib
import os
import time

_events = []
_trace_dir = None
_active = False


class RecordEvent:
    """platform/profiler.h:126 parity; also usable as a decorator."""

    def __init__(self, name, event_type="op"):
        self.name = name
        self._ann = None
        self._t0 = None

    def __enter__(self):
        import jax

        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        _events.append((self.name, dt))
        # _host_lib is only non-None after enable_host_trace(): the native
        # build/load never happens (nor does any lock) on the hot path
        # unless host tracing was explicitly turned on.
        if _host_lib is not None and _host_lib.pt_prof_enabled():
            now = _host_lib.pt_prof_now_ns()
            _host_lib.pt_prof_record(self.name.encode(),
                                     now - int(dt * 1e9), now)
        self._ann.__exit__(*exc)
        return False


_host_lib = None


def _native():
    """Native host-event recorder (csrc/ptcore/profiler.cc) when built."""
    global _host_lib
    if _host_lib is None:
        try:
            from ..core.native import load_library

            _host_lib = load_library()
        except Exception:
            return None
    return _host_lib


def export_chrome_tracing(path):
    """Dump host RecordEvents as a chrome://tracing JSON file
    (platform/device_tracer.cc GenProfile capability)."""
    lib = _native()
    if lib is None:
        raise RuntimeError("native profiler unavailable")
    if lib.pt_prof_dump(path.encode()) != 0:
        raise IOError(f"trace dump failed: {path}")
    return path


def enable_host_trace():
    lib = _native()
    if lib is not None:
        lib.pt_prof_enable()


def disable_host_trace():
    lib = _native()
    if lib is not None:
        lib.pt_prof_disable()


def start_profiler(state="All", tracer_option="Default",
                   trace_dir="/tmp/paddle_tpu_trace"):
    global _trace_dir, _active
    import jax

    _trace_dir = trace_dir
    os.makedirs(trace_dir, exist_ok=True)
    jax.profiler.start_trace(trace_dir)
    _active = True


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    global _active
    import jax

    if _active:
        jax.profiler.stop_trace()
        _active = False
    return summary()


def reset_profiler():
    _events.clear()


def summary():
    agg = {}
    for name, dt in _events:
        tot, cnt = agg.get(name, (0.0, 0))
        agg[name] = (tot + dt, cnt + 1)
    lines = ["Event                          Calls    Total(ms)   Avg(ms)"]
    for name, (tot, cnt) in sorted(agg.items(), key=lambda kv: -kv[1][0]):
        lines.append(f"{name:<30} {cnt:>6} {tot * 1e3:>11.3f} "
                     f"{tot / cnt * 1e3:>9.3f}")
    return "\n".join(lines)


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile"):
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)
