"""Profiler.

Reference parity: platform/profiler.h:126 RecordEvent RAII +
fluid/profiler.py (start_profiler/stop_profiler/profiler context). TPU-native
design: host annotations forward to jax.profiler.TraceAnnotation; device
timelines come from the XLA/XPlane trace (`start_profiler` starts a
jax.profiler trace whose output loads in TensorBoard / Perfetto — the
chrome://tracing equivalent of platform/device_tracer.cc).

`profiler.trace` adds the span-based host tracer the serving stack
reports into (per-request timelines, compile observer, retrace
sentinel); `start_profiler`/`stop_profiler` start and stop a tracer
session in lockstep with the XPlane trace, so the host span dump
(`<trace_dir>/host_trace.json`) loads in Perfetto next to the device
timeline.
"""
from __future__ import annotations

import collections
import contextlib
import os
import time

from . import trace  # noqa: F401  (paddle_tpu.profiler.trace)

#: host RecordEvent ring: bounded so an always-on process can leave
#: profiling annotations in place without unbounded growth
_EVENTS_CAP = 65536
_events = collections.deque(maxlen=_EVENTS_CAP)
_trace_dir = None
_active = False
_own_tracer = False
last_host_trace = None


def set_events_capacity(cap):
    """Resize the RecordEvent ring buffer (keeps the newest events)."""
    global _events, _EVENTS_CAP
    _EVENTS_CAP = int(cap)
    _events = collections.deque(_events, maxlen=_EVENTS_CAP)


class RecordEvent:
    """platform/profiler.h:126 parity; also usable as a decorator.
    Records (name, event_type, duration) host-side, forwards the name
    to jax.profiler.TraceAnnotation, and — when a `profiler.trace`
    session is active — surfaces the event as a span in the tracer.

    Span links: when the XPlane device trace is running in lockstep
    with a tracer session (`start_profiler`), the span is opened at
    __enter__ so its (trace_id, span_id) identity EXISTS before the
    device work runs, and both ids are stamped into the
    TraceAnnotation metadata — Perfetto shows them on the XPlane
    event's args, so a host span and its device timeline region
    correlate by id. Pass `trace_id=` to link the event to a request's
    trace (serving code passes the request id)."""

    def __init__(self, name, event_type="op", trace_id=0):
        self.name = name
        self.event_type = event_type
        self.trace_id = int(trace_id)
        self._ann = None
        self._t0 = None
        self._span = None

    def __enter__(self):
        import jax

        tr = trace._SESSION
        if tr is not None:
            # open the span FIRST so its id can ride into the XPlane
            self._span = tr.begin(self.name, cat="record_event",
                                  trace_id=self.trace_id,
                                  attrs={"event_type": self.event_type})
            if _active:
                # lockstep XPlane trace: stamp the span identity into
                # the device-timeline event metadata (span links)
                self._ann = jax.profiler.TraceAnnotation(
                    self.name, trace_id=self.trace_id,
                    span_id=self._span.span_id)
        if self._ann is None:
            self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        dt = t1 - self._t0
        _events.append((self.name, self.event_type, dt))
        tr = trace._SESSION
        if self._span is not None:
            if tr is not None:
                tr.end(self._span)
            self._span = None
        elif tr is not None:
            tr.add_complete(self.name, self._t0, t1, cat="record_event",
                            attrs={"event_type": self.event_type})
        # _host_lib is only non-None after enable_host_trace(): the native
        # build/load never happens (nor does any lock) on the hot path
        # unless host tracing was explicitly turned on.
        if _host_lib is not None and _host_lib.pt_prof_enabled():
            now = _host_lib.pt_prof_now_ns()
            _host_lib.pt_prof_record(self.name.encode(),
                                     now - int(dt * 1e9), now)
        self._ann.__exit__(*exc)
        return False


_host_lib = None


def _native():
    """Native host-event recorder (csrc/ptcore/profiler.cc) when built."""
    global _host_lib
    if _host_lib is None:
        try:
            from ..core.native import load_library

            _host_lib = load_library()
        except Exception:
            return None
    return _host_lib


def export_chrome_tracing(path):
    """Dump host RecordEvents as a chrome://tracing JSON file
    (platform/device_tracer.cc GenProfile capability)."""
    lib = _native()
    if lib is None:
        raise RuntimeError("native profiler unavailable")
    if lib.pt_prof_dump(path.encode()) != 0:
        raise IOError(f"trace dump failed: {path}")
    return path


def enable_host_trace():
    lib = _native()
    if lib is not None:
        lib.pt_prof_enable()


def disable_host_trace():
    lib = _native()
    if lib is not None:
        lib.pt_prof_disable()


def start_profiler(state="All", tracer_option="Default",
                   trace_dir="/tmp/paddle_tpu_trace"):
    """Start the XPlane device trace AND a `profiler.trace` span
    session in lockstep (unless one is already active, which is then
    left under its owner's control)."""
    global _trace_dir, _active, _own_tracer
    import jax

    _trace_dir = trace_dir
    os.makedirs(trace_dir, exist_ok=True)
    jax.profiler.start_trace(trace_dir)
    if trace._SESSION is None:
        trace.start_session()
        _own_tracer = True
    _active = True


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    """Stop the XPlane trace; the lockstep tracer session (if this
    module started it) is ended and exported to
    `<trace_dir>/host_trace.json` (`profiler.last_host_trace`)."""
    global _active, _own_tracer, last_host_trace
    import jax

    if _active:
        jax.profiler.stop_trace()
        _active = False
        if _own_tracer:
            _own_tracer = False
            tr = trace.end_session()
            if tr is not None and _trace_dir is not None:
                last_host_trace = tr.export_chrome_trace(
                    os.path.join(_trace_dir, "host_trace.json"))
    return summary()


def reset_profiler():
    _events.clear()


def reset():
    """Clear the host RecordEvent buffer (alias of reset_profiler)."""
    reset_profiler()


def events():
    """The recorded (name, event_type, duration_s) host events, newest
    `set_events_capacity()` of them."""
    return list(_events)


def summary():
    agg = {}
    for name, etype, dt in _events:
        tot, cnt = agg.get((name, etype), (0.0, 0))
        agg[(name, etype)] = (tot + dt, cnt + 1)
    lines = ["Event                          Type     Calls    "
             "Total(ms)   Avg(ms)"]
    for (name, etype), (tot, cnt) in sorted(agg.items(),
                                            key=lambda kv: -kv[1][0]):
        lines.append(f"{name:<30} {etype:<8} {cnt:>6} "
                     f"{tot * 1e3:>11.3f} {tot / cnt * 1e3:>9.3f}")
    return "\n".join(lines)


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile"):
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)
