"""Span-based host tracer: per-request timelines for the serving stack.

The serving runtime's only lens so far was `ServingMetrics.snapshot()`
aggregates. This module adds the missing per-request dimension — the
host-side analogue of the reference's chrome://tracing device timeline
(platform/device_tracer.cc): a `Tracer` records nested spans (name,
category, trace-id, monotonic start/end, attributes) into a thread-safe
bounded ring buffer and exports them as Chrome-trace/Perfetto JSON that
loads next to the `jax.profiler` XPlane dump.

Discipline (same as testing/faults.py): the hot paths pay ONE
module-global read per hit when nothing is armed. Production code
guards every tracing call site with ``if trace._SESSION is not None:``
— no function call, no allocation, when disabled.

Three cooperating pieces:

  * **Tracer / sessions** — `start_session()` installs the module-wide
    tracer every instrumented call site reports into;
    `session_scope()` is the context-manager form. `Tracer.
    export_chrome_trace(path)` writes the Perfetto-loadable artifact.
  * **Compile observer** — the engines' jit caches (`_compiled` dicts
    keyed identically to their `trace_counts` Counters) are `JitCache`
    instances: every stored program is wrapped so a call that bumps its
    trace count (one bump per jax trace = one per compile) is recorded
    as a ``compile`` span with its wall duration and cache key.
  * **Retrace sentinel** — `retrace_sentinel(*engines)` turns the
    per-PR "never retraces" claims into a standing assertion: any key
    compiling more than its declared budget (default: once) raises
    `RetraceError` at the offending trace (or records it, with
    ``mode="log"``). `ObservedCounter` (the `trace_counts` type) is
    what makes the sentinel see every trace as it happens.
"""
from __future__ import annotations

import collections
import contextlib
import itertools
import json
import logging
import threading
import time

__all__ = [
    "Span", "Tracer", "start_session", "end_session", "session",
    "session_scope", "ObservedCounter", "JitCache", "RetraceError",
    "RetraceSentinel", "retrace_sentinel", "add_compile_hook",
    "remove_compile_hook", "suppress_observation", "record_precompile",
]

_LOG = logging.getLogger("paddle_tpu.trace")

_LOCK = threading.RLock()
#: the ONE global every instrumented hot path reads; None = disabled
_SESSION = None
#: True while a session OR a sentinel OR a compile hook (the cost
#: accounting layer, profiler.costs) is armed — gates the compile
#: observer and counter notifications (trace-time only, never hot)
_WATCH = False
_GLOBAL_SENTINELS = []
_SENTINEL_COUNT = 0
#: observers of every detected trace+compile: fn(owner, key, raw_fn,
#: args, kw, t0, t1). profiler.costs registers one while an accounting
#: session is armed — this is how program cost/memory analysis attaches
#: to the SAME cache keys the retrace sentinel and compile spans use.
_COMPILE_HOOKS = []
#: armed while the cost layer re-lowers a program to extract XLA
#: analyses: the re-trace's counter bump must not look like a retrace
_SUPPRESS = False


def _recompute_watch():
    global _WATCH
    _WATCH = (_SESSION is not None or _SENTINEL_COUNT > 0
              or len(_COMPILE_HOOKS) > 0)


def add_compile_hook(hook):
    """Register a compile observer: called as fn(owner, key, raw_fn,
    args, kw, t0, t1) after every detected trace+compile while armed.
    Arms the jit-cache observation (same switch as sessions/sentinels)."""
    with _LOCK:
        _COMPILE_HOOKS.append(hook)
        _recompute_watch()


def remove_compile_hook(hook):
    with _LOCK:
        if hook in _COMPILE_HOOKS:
            _COMPILE_HOOKS.remove(hook)
        _recompute_watch()


@contextlib.contextmanager
def suppress_observation():
    """Silence ObservedCounter notifications (sentinels, session trace
    counts) for the duration: the cost layer's `fn.lower()` re-traces a
    program that already compiled, and that deliberate second trace
    must not fire the retrace sentinel or skew session counters."""
    global _SUPPRESS
    with _LOCK:
        prev, _SUPPRESS = _SUPPRESS, True
    try:
        yield
    finally:
        with _LOCK:
            _SUPPRESS = prev


def _key_str(key):
    s = str(key)
    return s if len(s) <= 120 else s[:117] + "..."


class Span:
    """One timed event. `t1 is None` while still open; times are
    `time.perf_counter()` seconds (monotonic, host-side)."""

    __slots__ = ("name", "cat", "trace_id", "span_id", "parent_id",
                 "t0", "t1", "attrs")

    def __init__(self, name, cat, trace_id, span_id, parent_id, t0,
                 attrs):
        self.name = name
        self.cat = cat
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = t0
        self.t1 = None
        self.attrs = attrs

    @property
    def duration_s(self):
        return None if self.t1 is None else self.t1 - self.t0

    def __repr__(self):
        return (f"Span({self.name!r}, trace={self.trace_id}, "
                f"dur={self.duration_s})")


class Tracer:
    """Thread-safe span sink with a bounded ring buffer (the oldest
    finished spans are overwritten past `capacity` — `dropped` counts
    them) plus a plain counter surface for scalar telemetry."""

    def __init__(self, capacity=65536, clock=time.perf_counter,
                 sample=None):
        self.capacity = int(capacity)
        self._clock = clock
        self._lock = threading.Lock()
        self._spans = collections.deque(maxlen=self.capacity)
        self._open = {}                 # span_id -> Span (not ended)
        self._ids = itertools.count(1)
        self.counters = collections.Counter()
        self.dropped = 0
        self.t_origin = clock()
        # request sampling: None = trace everything; a float in (0, 1]
        # traces ~that fraction of requests (deterministic in the
        # request id), bounding a multi-hour always-on session by
        # sampling rather than just ring capacity. An unsampled request
        # costs one branch at submit and nothing afterwards.
        if sample is not None:
            sample = float(sample)
            if not 0.0 < sample <= 1.0:
                raise ValueError(
                    f"sample must be in (0, 1], got {sample}")
        self.sample = sample

    def should_sample(self, trace_id):
        """Deterministic per-request sampling decision (Knuth
        multiplicative hash of the trace id vs the sample fraction), so
        a given request id samples identically across runs/processes."""
        if self.sample is None:
            return True
        h = (int(trace_id) * 2654435761) & 0xFFFFFFFF
        return h < self.sample * 4294967296.0

    # ---- recording ----
    def now(self):
        return self._clock()

    def begin(self, name, *, cat="span", trace_id=0, parent=None,
              attrs=None):
        sp = Span(name, cat, int(trace_id), next(self._ids),
                  None if parent is None else parent.span_id,
                  self._clock(), dict(attrs) if attrs else {})
        with self._lock:
            self._open[sp.span_id] = sp
        return sp

    def end(self, span, **attrs):
        if span is None or span.t1 is not None:
            return span
        span.t1 = self._clock()
        if attrs:
            span.attrs.update(attrs)
        with self._lock:
            self._open.pop(span.span_id, None)
            if len(self._spans) == self.capacity:
                self.dropped += 1
            self._spans.append(span)
        return span

    def add_complete(self, name, t0, t1, *, cat="span", trace_id=0,
                     parent=None, attrs=None):
        sp = Span(name, cat, int(trace_id), next(self._ids),
                  None if parent is None else parent.span_id,
                  t0, dict(attrs) if attrs else {})
        sp.t1 = t1
        with self._lock:
            if len(self._spans) == self.capacity:
                self.dropped += 1
            self._spans.append(sp)
        return sp

    def instant(self, name, *, cat="span", trace_id=0, parent=None,
                attrs=None):
        t = self._clock()
        return self.add_complete(name, t, t, cat=cat, trace_id=trace_id,
                                 parent=parent, attrs=attrs)

    @contextlib.contextmanager
    def span(self, name, **kw):
        sp = self.begin(name, **kw)
        try:
            yield sp
        finally:
            self.end(sp)

    def count(self, name, n=1):
        with self._lock:
            self.counters[name] += n

    # ---- reading ----
    def spans(self, include_open=False):
        with self._lock:
            out = list(self._spans)
            if include_open:
                out.extend(self._open.values())
        return out

    def open_spans(self):
        with self._lock:
            return list(self._open.values())

    # ---- export ----
    def chrome_trace_events(self, include_open=True):
        """The Chrome Trace Event Format list (Perfetto/chrome://tracing
        loadable): one complete ("ph": "X") event per span on pid 1,
        tid = trace_id + 1 for request tracks (tid 0 is the engine
        track), timestamps in microseconds from the tracer origin."""
        evs = [{"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
                "args": {"name": "paddle_tpu.serving"}},
               {"name": "thread_name", "ph": "M", "pid": 1, "tid": 0,
                "args": {"name": "engine"}}]
        named = set()
        now = self._clock()
        for sp in self.spans(include_open=include_open):
            tid = 0 if sp.trace_id == 0 else int(sp.trace_id) + 1
            if tid and tid not in named:
                named.add(tid)
                evs.append({"name": "thread_name", "ph": "M", "pid": 1,
                            "tid": tid,
                            "args": {"name": f"req {sp.trace_id}"}})
            t1 = sp.t1 if sp.t1 is not None else now
            args = {k: v for k, v in sp.attrs.items()}
            args["trace_id"] = sp.trace_id
            args["span_id"] = sp.span_id
            if sp.parent_id is not None:
                args["parent_id"] = sp.parent_id
            if sp.t1 is None:
                args["open"] = True
            evs.append({
                "name": sp.name, "cat": sp.cat, "ph": "X",
                "ts": round((sp.t0 - self.t_origin) * 1e6, 3),
                "dur": round((t1 - sp.t0) * 1e6, 3),
                "pid": 1, "tid": tid, "args": args})
        for name, v in sorted(self.counters.items()):
            evs.append({"name": _key_str(name), "ph": "C", "pid": 1,
                        "ts": round((now - self.t_origin) * 1e6, 3),
                        "args": {"value": v}})
        return evs

    def export_chrome_trace(self, path, include_open=True):
        """Write the trace as Chrome-trace JSON; load it in Perfetto
        (ui.perfetto.dev) or chrome://tracing, next to the XPlane dump
        `profiler.start_profiler` produces."""
        payload = {"traceEvents":
                   self.chrome_trace_events(include_open=include_open),
                   "displayTimeUnit": "ms"}
        with open(path, "w") as f:
            json.dump(payload, f)
        return path


# ----------------------------------------------------------------------
# session management
# ----------------------------------------------------------------------

def start_session(capacity=65536, tracer=None, sample=None):
    """Install the module-wide tracer session every instrumented call
    site reports into. Raises if a session is already active.
    `sample` (float in (0, 1], e.g. 1/16) traces only that fraction of
    requests — the always-on mode for multi-hour sessions."""
    global _SESSION
    with _LOCK:
        if _SESSION is not None:
            raise RuntimeError("a tracer session is already active; "
                               "end_session() it first")
        _SESSION = tracer if tracer is not None else \
            Tracer(capacity, sample=sample)
        _recompute_watch()
        return _SESSION


def end_session():
    """Tear down the active session; returns the Tracer (export it
    afterwards) or None when no session was active."""
    global _SESSION
    with _LOCK:
        tr = _SESSION
        _SESSION = None
        _recompute_watch()
        return tr


def session():
    """The active Tracer, or None. Hot paths read the module global
    `_SESSION` directly instead (one attribute load, no call)."""
    return _SESSION


@contextlib.contextmanager
def session_scope(capacity=65536, sample=None):
    tr = start_session(capacity, sample=sample)
    try:
        yield tr
    finally:
        end_session()


# ----------------------------------------------------------------------
# compile observation: trace_counts + jit caches
# ----------------------------------------------------------------------

class ObservedCounter(collections.Counter):
    """`trace_counts` Counter whose increments — one per jax trace =
    one per compile, the engines bump it as a python side effect inside
    every jitted body — notify the active retrace sentinel / tracer.
    Disarmed cost is one module-global boolean read, and only at trace
    time (never on warm calls)."""

    def __init__(self, *args, owner=None, **kw):
        super().__init__(*args, **kw)
        self.owner = owner
        self._sentinels = []

    def __setitem__(self, key, value):
        super().__setitem__(key, value)
        if _WATCH and not _SUPPRESS:
            _on_trace(self, key, value)


def _on_trace(counter, key, value):
    tr = _SESSION
    if tr is not None:
        tr.count("traces")
    for s in tuple(counter._sentinels) + tuple(_GLOBAL_SENTINELS):
        s._observe(counter, key, value)


class _CacheEntry:
    __slots__ = ("raw", "observed")

    def __init__(self, raw, observed):
        self.raw = raw
        self.observed = observed


class JitCache(dict):
    """The engines' `_compiled` dict. Lookups return the RAW compiled
    program while nothing is armed (the disabled hot path has zero
    tracing frames and zero allocations) and an observing wrapper
    while a session/sentinel is active: a call that traces+compiles
    (detected by its trace_counts key bumping — cache keys and count
    keys coincide by construction) is recorded as a ``compile`` span
    with its wall duration."""

    def __init__(self, owner):
        super().__init__()
        self._owner = owner

    def __setitem__(self, key, fn):
        super().__setitem__(key, _CacheEntry(
            fn, _observed_compiled(self._owner, key, fn)))

    def __getitem__(self, key):
        e = dict.__getitem__(self, key)
        return e.observed if _WATCH else e.raw

    def get(self, key, default=None):
        e = dict.get(self, key)
        if e is None:
            return default
        return e.observed if _WATCH else e.raw


def record_precompile(owner, key, t0, t1, source):
    """Startup-precompile observability: one ``precompile`` span per
    program the engine readied before serving (cat "compile", so it
    lands on the same Perfetto track as warm-path compiles), with
    `source` = "cache" (deserialized, no compile paid) or "compile"
    (AOT lower+compile at startup). The warm-start proof pivots on
    the session's counters: a warm start shows only
    ``precompile_cache_hits``, and the ``compiles`` counter stays 0
    through the first token."""
    tr = _SESSION
    if tr is None:
        return
    tr.add_complete("precompile", t0, t1, cat="compile",
                    attrs={"engine": type(owner).__name__,
                           "key": _key_str(key), "source": source})
    tr.count("precompiles")
    if source == "cache":
        tr.count("precompile_cache_hits")


def _observed_compiled(owner, key, fn):
    def call(*args, **kw):
        tc = owner.trace_counts
        n0 = tc[key]
        t0 = time.perf_counter()
        out = fn(*args, **kw)
        n1 = tc[key]
        if n1 != n0:
            t1 = time.perf_counter()
            tr = _SESSION
            if tr is not None:
                tr.add_complete(
                    "compile", t0, t1, cat="compile",
                    attrs={"engine": type(owner).__name__,
                           "key": _key_str(key), "count": n1})
                tr.count("compiles")
            for h in tuple(_COMPILE_HOOKS):
                try:
                    h(owner, key, fn, args, kw, t0, t1)
                except Exception:
                    _LOG.exception("compile hook %r failed", h)
        return out
    return call


# ----------------------------------------------------------------------
# retrace sentinel
# ----------------------------------------------------------------------

class RetraceError(RuntimeError):
    """A jit-cache key compiled more often than its declared budget —
    a retrace regression (joins/evictions/page-maps/steps are supposed
    to compile once per key, ever)."""


class RetraceSentinel:
    """Standing "never retraces" assertion over one or more engines
    (anything with an `ObservedCounter` trace_counts — the serving
    engines and `DecodeEngine`), or globally with no engines given.

        with trace.retrace_sentinel(eng):      # budget 1 per key
            ... serve ...                      # any retrace raises

    `budget` is the allowed number of traces per exact cache key;
    `budgets` overrides per key *kind* (the tuple's leading element:
    "step", "join", "pjoin", "pstep", "attach", "cow", "prefill",
    "splice", ...). ``mode="log"`` records `violations` (and warns)
    instead of raising; `assert_ok()` turns them into a RetraceError.
    """

    def __init__(self, *engines, budget=1, budgets=None, mode="raise"):
        if mode not in ("raise", "log"):
            raise ValueError(f"mode must be 'raise' or 'log', got "
                             f"{mode!r}")
        self.engines = engines
        self.budget = int(budget)
        self.budgets = dict(budgets or {})
        self.mode = mode
        self.violations = []
        self._attached = []

    def budget_for(self, key):
        kind = key[0] if isinstance(key, tuple) and key else key
        return int(self.budgets.get(kind, self.budget))

    def _observe(self, counter, key, value):
        b = self.budget_for(key)
        if value <= b:
            return
        v = {"engine": getattr(counter, "owner", None) or
             "<unknown>", "key": key, "count": value, "budget": b}
        self.violations.append(v)
        tr = _SESSION
        if tr is not None:
            tr.instant("retrace", cat="compile",
                       attrs={"key": _key_str(key), "count": value,
                              "budget": b})
        msg = (f"retrace sentinel: key {key!r} on {v['engine']} "
               f"traced {value} times (budget {b})")
        if self.mode == "raise":
            raise RetraceError(msg)
        _LOG.warning(msg)

    def assert_ok(self):
        if self.violations:
            raise RetraceError(
                f"{len(self.violations)} retrace violation(s): "
                f"{self.violations}")

    # ---- arming ----
    def __enter__(self):
        global _SENTINEL_COUNT
        with _LOCK:
            if self.engines:
                for e in self.engines:
                    c = e.trace_counts
                    if not isinstance(c, ObservedCounter):
                        # engines built before this module: upgrade the
                        # counter in place (contents preserved)
                        c = ObservedCounter(c, owner=type(e).__name__)
                        e.trace_counts = c
                    c._sentinels.append(self)
                    self._attached.append(c)
            else:
                _GLOBAL_SENTINELS.append(self)
            _SENTINEL_COUNT += 1
            _recompute_watch()
        return self

    def __exit__(self, *exc):
        global _SENTINEL_COUNT
        with _LOCK:
            for c in self._attached:
                if self in c._sentinels:
                    c._sentinels.remove(self)
            self._attached = []
            if self in _GLOBAL_SENTINELS:
                _GLOBAL_SENTINELS.remove(self)
            _SENTINEL_COUNT -= 1
            _recompute_watch()
        return False


def retrace_sentinel(*engines, budget=1, budgets=None, mode="raise"):
    """Arm a `RetraceSentinel` (context manager) over the given
    engines, or over every engine when none are given."""
    return RetraceSentinel(*engines, budget=budget, budgets=budgets,
                           mode=mode)


def reset():
    """Drop the active session, every armed sentinel and compile hook,
    disarm the watch flag. Test teardowns call this (conftest autouse)
    so a failing test never leaks an armed tracer into the next."""
    global _SESSION, _SENTINEL_COUNT, _SUPPRESS
    with _LOCK:
        _SESSION = None
        _GLOBAL_SENTINELS.clear()
        _COMPILE_HOOKS.clear()
        _SENTINEL_COUNT = 0
        _SUPPRESS = False
        _recompute_watch()
