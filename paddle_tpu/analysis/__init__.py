"""Static analyzer for the serving stack: program + host-state lints.

Every load-bearing guarantee in this codebase — never-retraces, donated
hot-path buffers, sharding constraints on every carry, documented
metric schema, registered fault points, lock-guarded shared state — was
enforced only *dynamically* (retrace sentinel, soaks, chaos cells): a
violation surfaced at runtime on one lucky code path, or not at all.
This package checks the same contracts at the source/jaxpr level, so a
whole defect class fails CI before any runtime exercises it.

Two halves (see README "Static analysis" for the rule table):

  * **Program analyzer** (`analysis.program`) — traces every program
    `ServingEngine.precompile()` would ready (dense/paged/sharded/spec
    + the fused optimizer step) and lints the closed jaxprs: baked-in
    constants (PTA101), un-donated large carries (PTA102), float
    promotion surprises (PTA103), host callbacks in jitted bodies
    (PTA104), sharded carries without constraint coverage (PTA105).
  * **Host-state + repo lints** (`analysis.hoststate`,
    `analysis.repo_rules`) — AST checks over serving/, tuning/ and
    profiler/: mutations of lock-owning classes outside their lock
    (PTA201, with the `# analysis: single-threaded` escape hatch),
    snapshot()/SNAPSHOT_DOCS drift (PTA202), unregistered fault points
    (PTA203), np./time. calls inside jitted bodies (PTA204).

`tools/static_check.py` is the CLI gate; findings carry stable rule
ids + baseline keys matched against the committed
`ANALYSIS_BASELINE.json` allowlist (start green, ratchet down).
"""
from .findings import RULES, Baseline, Finding, render_text
from .hoststate import check_paths, check_source
from .program import (analyze_engine, analyze_fused_optimizer,
                      analyze_program)
from .repo_rules import (RULE_FAULT_POINT, RULE_SNAPSHOT_DOC,
                         fault_point_findings, snapshot_doc_findings)
from .runner import (build_check_engines, program_findings, repo_root,
                     run, static_findings)

__all__ = [
    "RULES", "Finding", "Baseline", "render_text",
    "check_source", "check_paths",
    "analyze_program", "analyze_engine", "analyze_fused_optimizer",
    "snapshot_doc_findings", "fault_point_findings",
    "RULE_SNAPSHOT_DOC", "RULE_FAULT_POINT",
    "run", "static_findings", "program_findings",
    "build_check_engines", "repo_root",
]
