"""Orchestration: the whole-analyzer run the CLI gate and the tier-1
tests share.

Two halves with different costs:

  * `static_findings(root)` — AST lints (PTA201/PTA204 over serving/,
    tuning/, profiler/ and optimizer/fused.py) + the repo rules
    (PTA202 snapshot/doc sync, PTA203 fault-point registry). Pure
    source reads, sub-second.
  * `program_findings()` — builds throwaway tiny engines (dense step,
    dense spec, paged+prefix, and — when the process has a multi-device
    mesh — sharded disaggregated), traces every program `precompile()`
    would ready, plus the fused optimizer step, and runs the jaxpr
    rules. A few seconds of tracing, NO compiles.

`run()` combines them, filters against the committed
`ANALYSIS_BASELINE.json`, and (for the budget-aware CI fast mode)
caches program findings keyed on a digest of every package source —
any edit under paddle_tpu/ invalidates the cache, so a stale pass is
impossible.
"""
from __future__ import annotations

import hashlib
import json
import os

from .findings import Baseline, Finding
from . import hoststate, repo_rules

__all__ = ["repo_root", "static_findings", "program_findings",
           "build_check_engines", "run", "BASELINE_NAME", "CACHE_NAME"]

BASELINE_NAME = "ANALYSIS_BASELINE.json"
CACHE_NAME = ".static_check_cache.json"

#: tiny-stack program analysis treats buffers past this as "large" —
#: low on purpose so the check engines' KV pools qualify (production
#: pools are GBs; the invariant is the same)
CHECK_LARGE_BYTES = 4096


def repo_root():
    """The repository root (two levels above this package)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _pkg_dir():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------------------
# static half
# ----------------------------------------------------------------------

def static_findings(root=None):
    root = root or repo_root()
    pkg = os.path.join(root, "paddle_tpu")
    ast_paths = [os.path.join(pkg, d)
                 for d in ("serving", "tuning", "profiler")]
    ast_paths.append(os.path.join(pkg, "optimizer", "fused.py"))
    findings = hoststate.check_paths([p for p in ast_paths
                                      if os.path.exists(p)])
    findings += repo_rules.snapshot_doc_findings()
    findings += repo_rules.fault_point_findings(
        point_paths=[pkg],
        inject_paths=[pkg, os.path.join(root, "tests"),
                      os.path.join(root, "tools")])
    return findings


# ----------------------------------------------------------------------
# program half
# ----------------------------------------------------------------------

def _small_stack(seed=7, D=32, H=2, V=17, layers=2):
    import numpy as np

    from .. import nn
    from ..nn.layer.transformer import (TransformerDecoder,
                                        TransformerDecoderLayer)

    np.random.seed(seed)
    layer = TransformerDecoderLayer(D, H, 64, dropout=0.0)
    dec = TransformerDecoder(layer, layers)
    dec.eval()
    return dec, nn.Embedding(V, D), nn.Linear(D, V)


def _local_mesh(dp=2):
    """A dp-only DeviceMesh over the first `dp` devices, NOT installed
    globally — the analyzer must not disturb the process mesh."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from ..parallel.mesh import DeviceMesh

    devs = jax.devices()
    if len(devs) < dp:
        return None
    return DeviceMesh(Mesh(np.asarray(devs[:dp]).reshape(dp), ("dp",)),
                      ("dp",))


def build_check_engines(include_sharded=True):
    """[(label, engine)] throwaway tiny engines covering the program
    matrix: dense step, dense spec (draft + verify), paged (pjoin /
    attach / cow / pstep), paged spec (draft + pverify) and — when
    >= 2 devices exist — sharded disaggregated (join / step /
    prefill / splice)."""
    from ..serving import ServingEngine

    out = []
    dec, emb, proj = _small_stack(seed=7)
    out.append(("dense", ServingEngine(dec, emb, proj, num_slots=4,
                                       max_len=32)))
    dec, emb, proj = _small_stack(seed=8)
    out.append(("spec", ServingEngine(dec, emb, proj, num_slots=4,
                                      max_len=32, spec_k=4)))
    dec, emb, proj = _small_stack(seed=9)
    out.append(("paged", ServingEngine(dec, emb, proj, num_slots=4,
                                       max_len=32, paged=True,
                                       page_size=8)))
    dec, emb, proj = _small_stack(seed=12)
    out.append(("paged_spec", ServingEngine(
        dec, emb, proj, num_slots=4, max_len=32, paged=True,
        page_size=8, spec_k=4)))
    # multi-tenant: int8 base weights + an adapter-carrying program
    # set (ids + banks ride every join/step — the donation audit must
    # see the banks stay undonated and the state carry donated)
    from ..serving import AdapterPool

    dec, emb, proj = _small_stack(seed=13)
    # rank 8 puts the stacked banks past CHECK_LARGE_BYTES, so the
    # donation audit sees them as the large undonated args they are
    # in production (baselined: shared read-only across slots)
    pool = AdapterPool(dec, capacity=3, rank=8)
    pool.register_random("t1", seed=1)
    out.append(("tenant", ServingEngine(
        dec, emb, proj, num_slots=4, max_len=32, adapters=pool,
        quantize="int8")))
    # radix (PR 16): the paged cell's prefix cache enumerates the
    # `pattach` partial-attach pair for the admitted prompt bucket;
    # this cell adds the ADAPTER-carrying shape of the same family —
    # pattach rides ids + stacked banks like every other join, so the
    # donation audit sees the radix path exactly as multi-tenant
    # traffic runs it (banks undonated/shared, pool state carry
    # audited under the join-family baseline rule)
    dec, emb, proj = _small_stack(seed=14)
    pool = AdapterPool(dec, capacity=3, rank=8)
    pool.register_random("t1", seed=1)
    out.append(("radix", ServingEngine(
        dec, emb, proj, num_slots=4, max_len=32, paged=True,
        page_size=8, adapters=pool)))
    # traffic shaping (PR 19): the chunked-prefill program family —
    # the dense cjoin and the paged pcjoin both carry the pool state
    # at arg 4 (see _DONATED_KINDS), so the donation audit verifies
    # every per-chunk dispatch splices in place instead of copying
    # the pool once per chunk
    dec, emb, proj = _small_stack(seed=15)
    out.append(("chunked", ServingEngine(
        dec, emb, proj, num_slots=4, max_len=32, prefill_chunk=4)))
    dec, emb, proj = _small_stack(seed=16)
    out.append(("chunked_paged", ServingEngine(
        dec, emb, proj, num_slots=4, max_len=32, paged=True,
        page_size=4, prefill_chunk=8)))
    if include_sharded:
        mesh = _local_mesh(dp=2)
        if mesh is not None:
            from ..serving import ShardedServingEngine

            dec, emb, proj = _small_stack(seed=10)
            out.append(("sharded", ShardedServingEngine(
                dec, emb, proj, mesh=mesh, num_slots=2, max_len=32,
                prefill="disaggregated")))
            dec, emb, proj = _small_stack(seed=11)
            out.append(("sharded_paged", ShardedServingEngine(
                dec, emb, proj, mesh=mesh, num_slots=2, max_len=32,
                paged=True, page_size=8)))
    return out


def program_findings(include_sharded=True,
                     large_bytes=CHECK_LARGE_BYTES):
    from .program import analyze_engine, analyze_fused_optimizer

    findings = []
    for _label, eng in build_check_engines(include_sharded):
        findings.extend(analyze_engine(eng, (4, 32), prompt_buckets=(8,),
                                       large_bytes=large_bytes))
    findings += analyze_fused_optimizer(large_bytes=large_bytes)
    return findings


# ----------------------------------------------------------------------
# the combined gate
# ----------------------------------------------------------------------

def _source_digest():
    """sha256 over every package source + the jax version: the fast
    cache's validity key (any paddle_tpu edit invalidates it)."""
    import jax

    h = hashlib.sha256()
    h.update(jax.__version__.encode())
    pkg = _pkg_dir()
    for base, dirs, names in os.walk(pkg):
        if "__pycache__" in base:
            continue
        dirs.sort()
        for n in sorted(names):
            if not n.endswith(".py"):
                continue
            fp = os.path.join(base, n)
            h.update(fp.encode())
            with open(fp, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


def _cached_program_findings(root, fast, include_sharded):
    cache_path = os.path.join(root, CACHE_NAME)
    digest = _source_digest()
    if fast and os.path.exists(cache_path):
        try:
            with open(cache_path) as f:
                raw = json.load(f)
            if raw.get("digest") == digest and \
                    raw.get("sharded") == bool(include_sharded):
                return [Finding(d["rule"], d["where"], d["message"],
                                d["baseline_key"])
                        for d in raw["findings"]], "hit"
        except (OSError, ValueError, KeyError):
            pass
    findings = program_findings(include_sharded=include_sharded)
    try:
        tmp = cache_path + f".tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"digest": digest,
                       "sharded": bool(include_sharded),
                       "findings": [x.as_dict() for x in findings]}, f)
        os.replace(tmp, cache_path)
    except OSError:
        pass
    return findings, "miss"


def run(root=None, *, programs=True, include_sharded=True, fast=False,
        baseline_path=None):
    """The full gate. Returns a report dict:

        {"findings", "new", "baselined", "stale_baseline",
         "cache", "ok"}

    `ok` is the gate verdict: no finding outside the baseline. Stale
    baseline entries are reported (delete them — the ratchet) but do
    not fail the gate on their own."""
    root = root or repo_root()
    findings = static_findings(root)
    cache = None
    if programs:
        prog, cache = _cached_program_findings(root, fast,
                                               include_sharded)
        findings = findings + prog
    if baseline_path is None:
        baseline_path = os.path.join(root, BASELINE_NAME)
    if os.path.exists(baseline_path):
        baseline = Baseline.load(baseline_path)
    else:
        baseline = Baseline()
    new, baselined, stale = baseline.split(findings)
    return {
        "findings": findings,
        "new": new,
        "baselined": baselined,
        "stale_baseline": stale,
        "cache": cache,
        "ok": not new,
    }
