"""Jaxpr-level program analyzer: the compiled-program half of the
static checker.

Walks the closed jaxpr of every serving/decode program an engine's
`precompile()` enumeration (`_startup_programs`) would ready — plus the
fused optimizer step — WITHOUT compiling anything (`jitted.trace(*args)`
is a pure trace), and lints the invariants the runtime sentinels can
only catch when a lucky code path trips them:

  PTA101  large baked-in constants (closed-over arrays: a changed value
          retraces AND keeps a resident duplicate per program)
  PTA102  un-donated large carries — an input whose shape/dtype round-
          trips to an output; without `donate_argnums` XLA must copy it
          (for the serving pool: the whole KV cache) every dispatch
  PTA103  dtype-promotion surprises: float-widening converts and any
          float64 appearing in a program
  PTA104  host callbacks / transfers inside the jitted body
  PTA105  (sharded programs) carries with no `with_sharding_constraint`
          coverage — the every-carry contract of serving/sharded.py

Tracing happens under `trace.suppress_observation()` with the owner's
trace counter restored, so analyzing a LIVE engine never trips the
retrace sentinel or skews session counters (the same discipline as
profiler.costs' deliberate re-lower).
"""
from __future__ import annotations

import numpy as np

from ..profiler import trace as _trace
from .findings import Finding

__all__ = ["analyze_program", "analyze_engine",
           "analyze_fused_optimizer", "engine_programs"]

#: primitives that call back into the host / move data across the
#: host-device boundary from inside a compiled body
CALLBACK_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "infeed", "outfeed",
})

_FLOATS = ("bfloat16", "float16", "float32", "float64")


def _nbytes(aval):
    try:
        return int(np.prod(aval.shape, dtype=np.int64)) * \
            np.dtype(aval.dtype).itemsize
    except Exception:
        return 0


def _kind_of(key):
    return key[0] if isinstance(key, tuple) and key else str(key)


def _trace_restoring(owner, key, jitted, args):
    """jitted.trace(*args) with observation suppressed and the owner's
    trace counter restored — the body's `trace_counts[key] += 1` side
    effect must not look like a compile to sentinels/sessions."""
    counter = getattr(owner, "trace_counts", None)
    with _trace.suppress_observation():
        before = None if counter is None else counter[key]
        try:
            return jitted.trace(*args)
        finally:
            if counter is not None:
                counter[key] = before


def _flat_donated(traced):
    """Per-flat-invar donated flags, aligned with jaxpr invar order."""
    import jax

    try:
        info = traced.args_info
    except Exception:
        return None
    leaves = jax.tree_util.tree_leaves(
        info, is_leaf=lambda x: hasattr(x, "donated"))
    return [bool(getattr(x, "donated", False)) for x in leaves]


def _flat_argnums(args):
    """argnum per flat leaf, aligned with jaxpr invar order."""
    import jax

    out = []
    for i, a in enumerate(args):
        out.extend([i] * len(jax.tree_util.tree_leaves(a)))
    return out


def analyze_program(key, jitted, args, *, owner="program",
                    sharded=False, large_bytes=1 << 20,
                    declared_donated=(), owner_obj=None):
    """Lint ONE compiled program. `jitted` is the jax.jit-wrapped
    callable (an engine `build()` result), `args` example arguments
    shaped exactly like the runtime calls. `declared_donated` marks
    argnums the caller donates by contract even where the live wrapper
    skips it (backends without aliasing support). Returns findings."""
    kind = _kind_of(key)
    where = f"{owner}:{key!r}"
    traced = _trace_restoring(owner_obj, key, jitted, args)
    closed = traced.jaxpr
    jaxpr = closed.jaxpr
    findings = []

    # ---- PTA101: large baked-in constants ----
    for c in closed.consts:
        size = getattr(c, "size", None)
        dt = getattr(c, "dtype", None)
        if size is None or dt is None:
            continue
        nb = int(size) * np.dtype(str(dt)).itemsize
        if nb >= large_bytes:
            findings.append(Finding(
                "PTA101", where,
                f"program bakes in a {nb}-byte constant "
                f"{getattr(c, 'shape', ())}:{dt} — pass it as an "
                f"argument (a changed value retraces; the literal "
                f"stays resident per executable)",
                baseline_key=f"{owner}:{kind}:const"))

    # ---- PTA102: un-donated large carries ----
    donated = _flat_donated(traced) or [False] * len(jaxpr.invars)
    try:
        explicit = set(traced.donate_argnums)
    except Exception:
        explicit = set()
    explicit |= set(declared_donated)
    argnums = _flat_argnums(args)
    out_sigs = {}
    for v in jaxpr.outvars:
        av = getattr(v, "aval", None)
        if av is not None:
            out_sigs[(tuple(av.shape), str(av.dtype))] = \
                out_sigs.get((tuple(av.shape), str(av.dtype)), 0) + 1
    undonated = {}
    for i, v in enumerate(jaxpr.invars):
        av = getattr(v, "aval", None)
        if av is None or _nbytes(av) < large_bytes:
            continue
        sig = (tuple(av.shape), str(av.dtype))
        if sig not in out_sigs:
            continue                      # not a carry (params etc.)
        argnum = argnums[i] if i < len(argnums) else -1
        if donated[i] or argnum in explicit:
            continue
        undonated.setdefault(argnum, []).append(
            f"{sig[1]}{list(sig[0])}")
    for argnum, leaves in sorted(undonated.items()):
        findings.append(Finding(
            "PTA102", where,
            f"arg {argnum} carries {len(leaves)} large un-donated "
            f"buffer(s) that round-trip to outputs "
            f"(e.g. {leaves[0]}) — donate_argnums would alias them "
            f"in place instead of copying per dispatch",
            baseline_key=f"{owner}:{kind}:arg{argnum}"))

    # ---- PTA103 / PTA104: eqn sweep ----
    f64_hit = False
    for eqn in jaxpr.eqns:
        prim = str(eqn.primitive)
        if prim in CALLBACK_PRIMITIVES:
            cb = eqn.params.get("callback", "")
            findings.append(Finding(
                "PTA104", where,
                f"host primitive `{prim}` inside the compiled body "
                f"({cb!r}) — a host sync on every dispatch",
                baseline_key=f"{owner}:{kind}:{prim}"))
        if prim == "convert_element_type":
            src = str(eqn.invars[0].aval.dtype) \
                if getattr(eqn.invars[0], "aval", None) is not None \
                else "?"
            dst = str(eqn.params.get("new_dtype", "?"))
            if src in _FLOATS and dst in _FLOATS and \
                    _FLOATS.index(dst) > _FLOATS.index(src) and \
                    np.dtype(dst).itemsize > np.dtype(src).itemsize:
                findings.append(Finding(
                    "PTA103", where,
                    f"float widening {src} -> {dst} inside the "
                    f"program — check for a weak-type / mixed-"
                    f"precision promotion surprise",
                    baseline_key=f"{owner}:{kind}:promote:"
                                 f"{src}->{dst}"))
        if not f64_hit:
            for v in tuple(eqn.outvars):
                av = getattr(v, "aval", None)
                if av is not None and str(av.dtype) == "float64":
                    f64_hit = True
                    findings.append(Finding(
                        "PTA103", where,
                        "float64 value inside the program (x64 "
                        "upcast — 2x memory + off the TPU fast path)",
                        baseline_key=f"{owner}:{kind}:f64"))
                    break

    # ---- PTA105: sharding-constraint coverage over carries ----
    if sharded:
        constrained = set()
        for eqn in jaxpr.eqns:
            inp_hit = any(str(v) in constrained for v in eqn.invars
                          if not isinstance(v, (int, float)))
            if str(eqn.primitive) == "sharding_constraint" or inp_hit:
                for v in eqn.outvars:
                    constrained.add(str(v))
        invar_ids = {str(v) for v in jaxpr.invars}
        in_sigs = set()
        for v in jaxpr.invars:
            av = getattr(v, "aval", None)
            if av is not None:
                in_sigs.add((tuple(av.shape), str(av.dtype)))
        for idx, v in enumerate(jaxpr.outvars):
            av = getattr(v, "aval", None)
            if av is None or _nbytes(av) < large_bytes:
                continue
            sig = (tuple(av.shape), str(av.dtype))
            if sig not in in_sigs:
                continue                  # fresh output, not a carry
            if str(v) in invar_ids:
                continue                  # passthrough keeps its layout
            if str(v) not in constrained:
                findings.append(Finding(
                    "PTA105", where,
                    f"sharded program returns carry out[{idx}] "
                    f"{sig[1]}{list(sig[0])} with no "
                    f"with_sharding_constraint coverage — its layout "
                    f"is left to the partitioner",
                    baseline_key=f"{owner}:{kind}:out{idx}"))
    return findings


def engine_programs(engine, memory=(4, 32), *, dtype="float32",
                    prompt_buckets=(8,)):
    """The `(key, build, example_args)` enumeration `precompile()`
    readies, with the pool pinned exactly the way precompile pins it
    (memory shape tuple or example array) — but nothing compiled."""
    if hasattr(memory, "ndim") or isinstance(memory, np.ndarray):
        mem = np.asarray(memory)
    else:
        M, Dm = memory
        mem = np.zeros((int(M), int(Dm)), np.dtype(dtype))
    engine._ensure_state(mem)
    return engine._startup_programs(prompt_buckets)


def analyze_engine(engine, memory=(4, 32), *, dtype="float32",
                   prompt_buckets=(8,), large_bytes=4096):
    """Run `analyze_program` over every program of one engine's pool
    config. `large_bytes` defaults low enough that the tiny CI stacks'
    KV pools count as large (production pools are GBs)."""
    sharded = bool(getattr(engine, "_accepts_sharded_params", False))
    owner = type(engine).__name__
    findings = []
    for key, build, args in engine_programs(
            engine, memory, dtype=dtype, prompt_buckets=prompt_buckets):
        findings.extend(analyze_program(
            key, build(), args, owner=owner, sharded=sharded,
            large_bytes=large_bytes,
            declared_donated=engine._donate_argnums(key),
            owner_obj=engine))
    return findings


def analyze_fused_optimizer(large_bytes=4096, n=64):
    """Lint the fused whole-model optimizer step (optimizer/fused.py):
    build one Adam step over a small dense bag and audit it like a
    serving program. Donation is audited against the module's DECLARED
    `intended_donation()` — the live wrapper skips donation only where
    the backend can't alias, which is a capability gap, not a defect."""
    import jax.numpy as jnp

    from .. import optimizer as opt_mod
    from ..nn.layer.layers import Parameter
    from ..optimizer import fused

    rs = np.random.RandomState(0)
    params = [Parameter(rs.randn(n, n).astype("f4"), name=f"p{i}")
              for i in range(2)]
    opt = opt_mod.Adam(0.01, parameters=params)
    specs = []
    slot_lists = []
    for p in params:
        slots = opt._slots(p, opt._rule_slot_spec(p))
        slot_lists.append(tuple(slots[k] for k in opt._fused_slots))
        specs.append((tuple(p._data.shape), str(p._data.dtype),
                      str(p._data.dtype), 1.0, 0.0, False))
    fn = fused._build(opt, specs, None)
    grads = tuple(jnp.asarray(rs.randn(n, n).astype("f4"))
                  for _ in params)
    args = (tuple(p._data for p in params), grads, tuple(slot_lists),
            np.float32(0.01), np.int32(0))
    return analyze_program(
        ("fused_opt", "adam", n), fn, args, owner="FusedOptimizerStep",
        large_bytes=large_bytes,
        declared_donated=fused.intended_donation())
