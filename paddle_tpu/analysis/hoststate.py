"""AST half of the static checker: host-state concurrency + jit-body
hygiene over serving/, tuning/ and profiler/ sources.

PTA201 — lock discipline. The checker LEARNS each class's lock
attributes (any ``self.<name> = threading.Lock()/RLock()/Condition()``)
and then requires every mutation of ``self.<attr>`` in that class —
plain/aug/subscript assignment and mutating container calls
(``self._q.append(...)``) — to sit inside a ``with self.<lock>:``
block. Classes with no lock attribute are skipped entirely: the
engines are single-threaded by contract and say so in their
docstrings; the rule targets exactly the objects that CLAIM thread
safety by owning a lock.

Escape hatch (the ``# analysis:`` annotation grammar):

    def _read_manifest(self):   # analysis: single-threaded
        ...                     # whole function exempt

    self._hint = x              # analysis: single-threaded
                                # one statement exempt

A trailing ``# analysis: single-threaded`` comment on the ``def`` line
(or on the line directly above it) exempts the function; on a statement
line it exempts that statement. ``__init__``/``__new__`` are exempt by
construction (no second thread can hold an object mid-construction).

PTA204 — host calls in jitted bodies. Functions that become compiled
programs — any function nested inside a ``*_body`` method (the engine
convention) or passed directly to ``jax.jit(<name>, ...)`` in the same
scope — must not call ``np.*`` or ``time.*``: a host call inside a
traced body either bakes a host value into the program or drags a sync
point into every dispatch.
"""
from __future__ import annotations

import ast
import os

from .findings import Finding

__all__ = ["check_source", "check_paths", "ANNOTATION",
           "LOCK_FACTORIES", "MUTATOR_METHODS"]

ANNOTATION = "# analysis: single-threaded"

LOCK_FACTORIES = frozenset({"Lock", "RLock", "Condition"})

#: method names whose call on a self attribute mutates it in place
MUTATOR_METHODS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "update", "pop", "popleft", "popitem", "remove", "discard",
    "clear", "setdefault",
})

_HOST_MODULES = ("np", "numpy", "time")


def _is_lock_factory(node):
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in LOCK_FACTORIES and \
            isinstance(f.value, ast.Name) and f.value.id == "threading":
        return True
    return isinstance(f, ast.Name) and f.id in LOCK_FACTORIES


def _self_attr(node):
    """'x' for `self.x`, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _annotated(lines, lineno):
    """True when `lineno` (1-based) or the line above carries the
    single-threaded annotation."""
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines) and ANNOTATION in lines[ln - 1]:
            return True
    return False


class _LockScopeVisitor(ast.NodeVisitor):
    """Walks one method body tracking whether the current statement is
    inside a `with self.<lock>:` block; collects unguarded mutations."""

    def __init__(self, lock_attrs, lines, hits):
        self.lock_attrs = lock_attrs
        self.lines = lines
        self.hits = hits          # [(lineno, attr)]
        self._guarded = 0

    # ---- guard tracking ----
    def visit_With(self, node):
        locked = any(
            _self_attr(item.context_expr) in self.lock_attrs
            for item in node.items)
        if locked:
            self._guarded += 1
        self.generic_visit(node)
        if locked:
            self._guarded -= 1

    def _record(self, node, attr):
        if attr is None or attr in self.lock_attrs or self._guarded:
            return
        if _annotated(self.lines, node.lineno):
            return
        self.hits.append((node.lineno, attr))

    def _target_attr(self, t):
        a = _self_attr(t)
        if a is not None:
            return a
        if isinstance(t, ast.Subscript):      # self.stats["x"] = ...
            return _self_attr(t.value)
        if isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                a = self._target_attr(el)
                if a is not None:
                    return a
        return None

    # ---- mutation sites ----
    def visit_Assign(self, node):
        for t in node.targets:
            self._record(node, self._target_attr(t))
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._record(node, self._target_attr(node.target))
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._record(node, self._target_attr(node.target))
        self.generic_visit(node)

    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in MUTATOR_METHODS:
            self._record(node, _self_attr(f.value))
        self.generic_visit(node)

    # nested defs keep the surrounding guard state deliberately: a
    # closure defined under the lock usually RUNS under it too, and
    # the conservative alternative drowned real findings in noise
    def visit_FunctionDef(self, node):
        self.generic_visit(node)


def _check_locks(tree, lines, path):
    findings = []
    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        lock_attrs = set()
        for sub in ast.walk(cls):
            if isinstance(sub, ast.Assign) and \
                    _is_lock_factory(sub.value):
                for t in sub.targets:
                    a = _self_attr(t)
                    if a is not None:
                        lock_attrs.add(a)
        if not lock_attrs:
            continue
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if meth.name in ("__init__", "__new__"):
                continue
            if _annotated(lines, meth.lineno):
                continue
            hits = []
            v = _LockScopeVisitor(lock_attrs, lines, hits)
            for stmt in meth.body:
                v.visit(stmt)
            for lineno, attr in hits:
                lock = sorted(lock_attrs)[0]
                findings.append(Finding(
                    "PTA201", f"{path}:{lineno}",
                    f"{cls.name}.{meth.name} mutates self.{attr} "
                    f"outside `with self.{lock}:` (class owns a lock "
                    f"=> every mutation is guarded, or annotated "
                    f"'{ANNOTATION}')",
                    baseline_key=f"{os.path.basename(path)}:"
                                 f"{cls.name}.{meth.name}:{attr}"))
    return findings


def _jit_bodies(tree):
    """FunctionDef nodes that become compiled programs: every def
    nested inside a `*_body` method, plus local defs passed straight
    to jax.jit(<name>, ...)."""
    bodies = []
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and \
                node.name.endswith("_body"):
            for sub in ast.walk(node):
                if isinstance(sub, ast.FunctionDef) and sub is not node:
                    bodies.append(sub)
        if isinstance(node, ast.FunctionDef):
            local_defs = {n.name: n for n in ast.walk(node)
                          if isinstance(n, ast.FunctionDef)}
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr == "jit" and \
                        isinstance(sub.func.value, ast.Name) and \
                        sub.func.value.id == "jax" and sub.args and \
                        isinstance(sub.args[0], ast.Name):
                    target = local_defs.get(sub.args[0].id)
                    if target is not None:
                        bodies.append(target)
    uniq = []
    seen = set()
    for b in bodies:
        if id(b) not in seen:
            seen.add(id(b))
            uniq.append(b)
    return uniq


def _check_jit_bodies(tree, lines, path):
    findings = []
    for body in _jit_bodies(tree):
        for sub in ast.walk(body):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id in _HOST_MODULES:
                if _annotated(lines, sub.lineno):
                    continue
                findings.append(Finding(
                    "PTA204", f"{path}:{sub.lineno}",
                    f"jitted body `{body.name}` calls "
                    f"{f.value.id}.{f.attr}(...) — host work inside a "
                    f"traced program (bakes a host value in, or syncs "
                    f"per dispatch); use jnp/lax or hoist it out",
                    baseline_key=f"{os.path.basename(path)}:"
                                 f"{body.name}:{f.value.id}.{f.attr}"))
    return findings


def check_source(source, path="<source>"):
    """All AST findings for one module's source text."""
    tree = ast.parse(source)
    lines = source.splitlines()
    return _check_locks(tree, lines, path) + \
        _check_jit_bodies(tree, lines, path)


def check_paths(paths):
    """All AST findings across files/directories (``.py`` only)."""
    files = []
    for p in paths:
        if os.path.isdir(p):
            for base, _dirs, names in os.walk(p):
                if "__pycache__" in base:
                    continue
                files.extend(os.path.join(base, n)
                             for n in sorted(names)
                             if n.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    findings = []
    for fp in sorted(set(files)):
        with open(fp) as f:
            src = f.read()
        findings.extend(check_source(src, fp))
    return findings
