"""Finding/rule vocabulary + the committed-baseline ratchet.

Every check in `paddle_tpu.analysis` reports `Finding`s: a stable rule
id (the `PTA...` codes below — tools, tests and the baseline all key on
them), a `where` (file:line for AST findings, program key for jaxpr
findings), a human message, and a `baseline_key` — the STABLE identity
a committed `ANALYSIS_BASELINE.json` entry matches against
(`fnmatch`-style wildcards allowed), deliberately free of line numbers
and array shapes so refactors don't churn the baseline.

Baseline semantics (the ratchet): the gate starts green by committing
today's justified findings; every entry carries a one-line
`justification`; a finding with no matching entry fails the gate; an
entry matching no finding is reported `stale` so dead allowlist rows
get deleted, never accumulated.
"""
from __future__ import annotations

import fnmatch
import json

__all__ = ["RULES", "Finding", "Baseline", "render_text"]

#: rule id -> (slug, one-line description). The id is the STABLE
#: contract: tests, the baseline, README's rule table and the CI gate
#: all reference these strings verbatim.
RULES = {
    "PTA101": ("jaxpr-baked-const",
               "large constant baked into a compiled program (a new "
               "value means a retrace + resident duplicate)"),
    "PTA102": ("jaxpr-undonated-carry",
               "large carry buffer (input returned with identical "
               "shape/dtype) not donated on a compiled program — XLA "
               "must copy it every dispatch"),
    "PTA103": ("jaxpr-dtype-promotion",
               "float widening inside a compiled program (weak-type / "
               "mixed-precision upcast, or any float64)"),
    "PTA104": ("jaxpr-host-callback",
               "host callback / transfer primitive inside a jitted "
               "body (sync point on the hot path)"),
    "PTA105": ("jaxpr-unsharded-carry",
               "sharded program carry without with_sharding_constraint "
               "coverage (layout left to partitioner whim)"),
    "PTA201": ("lock-unguarded-mutation",
               "attribute of a lock-owning class mutated outside a "
               "`with self.<lock>:` scope"),
    "PTA202": ("snapshot-doc-drift",
               "ServingMetrics.snapshot() keys and SNAPSHOT_DOCS "
               "disagree (schema of record drifted)"),
    "PTA203": ("unregistered-fault-point",
               "faults.inject() names a point no faults.point() "
               "registers — the plan would never fire"),
    "PTA204": ("host-call-in-jit-body",
               "np./time. call inside a jitted body (host work baked "
               "into a traced program)"),
}


class Finding:
    """One analyzer result. `where` is display-oriented (file:line or
    program key); `baseline_key` is the stable matching identity."""

    __slots__ = ("rule", "where", "message", "baseline_key")

    def __init__(self, rule, where, message, baseline_key=None):
        if rule not in RULES:
            raise ValueError(f"unknown rule id {rule!r}")
        self.rule = rule
        self.where = str(where)
        self.message = str(message)
        self.baseline_key = (str(baseline_key) if baseline_key
                             is not None else self.where)

    @property
    def slug(self):
        return RULES[self.rule][0]

    def as_dict(self):
        return {"rule": self.rule, "slug": self.slug,
                "where": self.where, "message": self.message,
                "baseline_key": self.baseline_key}

    def __repr__(self):
        return f"Finding({self.rule} {self.where}: {self.message})"


class Baseline:
    """The committed allowlist: `{"version": 1, "entries": [{"rule",
    "match", "justification"}, ...]}`. `match` is fnmatch'd against
    each finding's `baseline_key` (rule must equal exactly)."""

    VERSION = 1

    def __init__(self, entries=()):
        self.entries = [dict(e) for e in entries]
        for e in self.entries:
            if not e.get("rule") or not e.get("match") or \
                    not e.get("justification"):
                raise ValueError(
                    f"baseline entry needs rule/match/justification: "
                    f"{e!r}")

    @classmethod
    def load(cls, path):
        with open(path) as f:
            raw = json.load(f)
        if not isinstance(raw, dict) or \
                raw.get("version") != cls.VERSION:
            raise ValueError(f"baseline {path} version "
                             f"{raw.get('version')!r} != {cls.VERSION}")
        return cls(raw.get("entries", ()))

    def save(self, path):
        with open(path, "w") as f:
            json.dump({"version": self.VERSION, "entries": self.entries},
                      f, indent=1, sort_keys=True)
            f.write("\n")
        return path

    def _matches(self, entry, finding):
        return entry["rule"] == finding.rule and fnmatch.fnmatchcase(
            finding.baseline_key, entry["match"])

    def split(self, findings):
        """(new, baselined, stale_entries): findings with no entry,
        findings an entry justifies, and entries justifying nothing
        (dead rows the ratchet wants deleted)."""
        new, baselined = [], []
        used = [False] * len(self.entries)
        for f in findings:
            hit = None
            for i, e in enumerate(self.entries):
                if self._matches(e, f):
                    hit = i
                    break
            if hit is None:
                new.append(f)
            else:
                used[hit] = True
                baselined.append(f)
        stale = [e for e, u in zip(self.entries, used) if not u]
        return new, baselined, stale


def render_text(findings, *, prefix="  "):
    lines = []
    for f in findings:
        lines.append(f"{prefix}{f.rule} [{f.slug}] {f.where}")
        lines.append(f"{prefix}    {f.message}")
    return "\n".join(lines)
