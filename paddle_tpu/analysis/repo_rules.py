"""Repo-convention rules: invariants that span modules.

PTA202 — snapshot/doc sync. `ServingMetrics.snapshot()` is the metric
surface of record and `SNAPSHOT_DOCS` its documented schema; the two
must never drift. This rule extracts the key set snapshot() PRODUCES
straight from its AST (dict literals, the ``**({} if .. else {..})``
conditional sections, and one level of local-variable indirection for
the "memory" dict) and diffs it against the `SNAPSHOT_DOCS` keys —
statically, so a key added to one side fails CI before any runtime
path renders it. The dynamic half (a fully-populated snapshot
flattening to exactly the documented keys) lives in
tests/test_tracing.py and references THIS rule id: one invariant, two
enforcement points, one source of truth.

PTA203 — fault-point registry. `faults.point(name)` registers points
idempotently, which means `faults.inject("typo.name")` self-registers
a fresh point that NO production code ever hits: the plan silently
never fires. This rule collects every literal `faults.point("...")`
(the registry) and checks every literal `faults.inject("...")` against
it.
"""
from __future__ import annotations

import ast
import os

from .findings import Finding

__all__ = ["RULE_SNAPSHOT_DOC", "RULE_FAULT_POINT",
           "snapshot_keys_from_source", "snapshot_doc_findings",
           "fault_point_findings", "collect_fault_names"]

RULE_SNAPSHOT_DOC = "PTA202"
RULE_FAULT_POINT = "PTA203"


# ----------------------------------------------------------------------
# PTA202: snapshot() AST key extraction vs SNAPSHOT_DOCS
# ----------------------------------------------------------------------

def _flatten_dict_node(node, prefix, local_dicts, out):
    """Collect dotted key paths produced by a dict-literal AST node.
    Values that are themselves dict literals (directly, via a local
    variable, or behind the `**({} if c else {...})` section idiom)
    recurse; anything else is a leaf."""
    for k, v in zip(node.keys, node.values):
        if k is None:                       # **expansion (a section)
            for branch in _dict_branches(v, local_dicts):
                _flatten_dict_node(branch, prefix, local_dicts, out)
            continue
        if not isinstance(k, ast.Constant) or \
                not isinstance(k.value, str):
            continue
        key = prefix + k.value
        v = _resolve(v, local_dicts)
        if isinstance(v, ast.Dict):
            _flatten_dict_node(v, key + ".", local_dicts, out)
        else:
            out.add(key)


def _resolve(node, local_dicts):
    if isinstance(node, ast.Name) and node.id in local_dicts:
        return local_dicts[node.id]
    return node


def _dict_branches(node, local_dicts):
    """Dict-literal branches of a `**`-expanded expression: handles
    `{...}`, a local name, and `{} if cond else {...}` (both arms)."""
    node = _resolve(node, local_dicts)
    if isinstance(node, ast.Dict):
        return [node]
    if isinstance(node, ast.IfExp):
        return _dict_branches(node.body, local_dicts) + \
            _dict_branches(node.orelse, local_dicts)
    return []


def snapshot_keys_from_source(source):
    """The dotted key set `ServingMetrics.snapshot()` can emit,
    extracted statically from the module source."""
    tree = ast.parse(source)
    fn = None
    for cls in ast.walk(tree):
        if isinstance(cls, ast.ClassDef) and cls.name == "ServingMetrics":
            for meth in cls.body:
                if isinstance(meth, ast.FunctionDef) and \
                        meth.name == "snapshot":
                    fn = meth
    if fn is None:
        raise ValueError("ServingMetrics.snapshot() not found")
    local_dicts = {}
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Dict):
            for t in sub.targets:
                if isinstance(t, ast.Name):
                    local_dicts[t.id] = sub.value
    keys = set()
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Return) and isinstance(sub.value, ast.Dict):
            _flatten_dict_node(sub.value, "", local_dicts, keys)
    return keys


def snapshot_doc_findings(metrics_path=None, docs=None):
    """PTA202 findings (empty = in sync). Defaults to the real
    serving.metrics module + its SNAPSHOT_DOCS; fixture tests pass a
    synthetic module path and doc set."""
    if metrics_path is None:
        from ..serving import metrics as _m

        metrics_path = _m.__file__
    if docs is None:
        from ..serving.metrics import SNAPSHOT_DOCS as docs

    with open(metrics_path) as f:
        src = f.read()
    produced = snapshot_keys_from_source(src)
    documented = set(docs)
    findings = []
    where = os.path.basename(metrics_path)
    for key in sorted(produced - documented):
        findings.append(Finding(
            RULE_SNAPSHOT_DOC, where,
            f"snapshot() emits `{key}` but SNAPSHOT_DOCS does not "
            f"document it — add the doc row (the schema of record)",
            baseline_key=f"snapshot:undocumented:{key}"))
    for key in sorted(documented - produced):
        findings.append(Finding(
            RULE_SNAPSHOT_DOC, where,
            f"SNAPSHOT_DOCS documents `{key}` but snapshot() never "
            f"emits it — dead doc row (or a dropped metric)",
            baseline_key=f"snapshot:unemitted:{key}"))
    return findings


# ----------------------------------------------------------------------
# PTA203: fault-point registry coverage
# ----------------------------------------------------------------------

def _literal_fault_calls(tree, attr):
    """(name, lineno) for every `faults.<attr>("literal", ...)` or bare
    `<attr>("literal", ...)` call in a module."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        named = (isinstance(f, ast.Attribute) and f.attr == attr) or \
            (isinstance(f, ast.Name) and f.id == attr)
        if not named:
            continue
        a0 = node.args[0]
        if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
            out.append((a0.value, node.lineno))
    return out


def _py_files(paths):
    for p in paths:
        if os.path.isdir(p):
            for base, _dirs, names in os.walk(p):
                if "__pycache__" in base:
                    continue
                for n in sorted(names):
                    if n.endswith(".py"):
                        yield os.path.join(base, n)
        elif p.endswith(".py"):
            yield p


def collect_fault_names(paths, attr="point"):
    """{name: [file:line, ...]} of literal faults.<attr>() calls."""
    out = {}
    for fp in sorted(set(_py_files(paths))):
        with open(fp) as f:
            try:
                tree = ast.parse(f.read())
            except SyntaxError:
                continue
        for name, lineno in _literal_fault_calls(tree, attr):
            out.setdefault(name, []).append(f"{fp}:{lineno}")
    return out


def fault_point_findings(point_paths, inject_paths):
    """PTA203 findings: inject() names with no point() registration
    anywhere in `point_paths` + `inject_paths` (tests register ad-hoc
    points next to their injections — that counts)."""
    registry = set(collect_fault_names(
        list(point_paths) + list(inject_paths), attr="point"))
    findings = []
    injected = collect_fault_names(inject_paths, attr="inject")
    for name, sites in sorted(injected.items()):
        if name in registry:
            continue
        findings.append(Finding(
            RULE_FAULT_POINT, sites[0],
            f"faults.inject({name!r}) names a point no faults.point() "
            f"registers — inject() self-registers it, so the plan "
            f"silently never fires",
            baseline_key=f"faults:{name}"))
    return findings
