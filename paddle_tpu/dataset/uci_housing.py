"""Legacy paddle.dataset.uci_housing (dataset/uci_housing.py parity)."""
from __future__ import annotations

from ._reader import dataset_reader

feature_names = ["CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS",
                 "RAD", "TAX", "PTRATIO", "B", "LSTAT", "convert"]


def _make(mode, data_file=None):
    from ..text.datasets import UCIHousing

    return UCIHousing(data_file=data_file, mode=mode,
                      download=data_file is None)


def train(data_file=None):
    return dataset_reader(lambda: _make("train", data_file))


def test(data_file=None):
    return dataset_reader(lambda: _make("test", data_file))
