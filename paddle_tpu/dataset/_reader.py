"""Shared shim: map-style Dataset -> legacy reader generator."""
from __future__ import annotations


def dataset_reader(make_dataset):
    """Wrap a Dataset factory into a reader() generator factory."""

    def reader():
        ds = make_dataset()
        for i in range(len(ds)):
            yield tuple(ds[i])

    return reader
