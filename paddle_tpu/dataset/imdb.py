"""Legacy paddle.dataset.imdb (dataset/imdb.py parity)."""
from __future__ import annotations

from ._reader import dataset_reader

_CACHE = {}


def _make(mode, data_file=None, cutoff=150):
    from ..text.datasets import Imdb

    key = (mode, data_file, cutoff)
    if key not in _CACHE:  # one tar scan per (mode, cutoff), not per epoch
        _CACHE[key] = Imdb(data_file=data_file, mode=mode, cutoff=cutoff,
                           download=data_file is None)
    return _CACHE[key]


def word_dict(data_file=None, cutoff=150):
    return _make("train", data_file, cutoff).word_idx


def train(word_idx=None, data_file=None, cutoff=150):
    return dataset_reader(lambda: _make("train", data_file, cutoff))


def test(word_idx=None, data_file=None, cutoff=150):
    return dataset_reader(lambda: _make("test", data_file, cutoff))
