"""Legacy paddle.dataset.imdb (dataset/imdb.py parity)."""
from __future__ import annotations

from ._reader import dataset_reader


def _make(mode, data_file=None, cutoff=150):
    from ..text.datasets import Imdb

    return Imdb(data_file=data_file, mode=mode, cutoff=cutoff,
                download=data_file is None)


def word_dict(data_file=None, cutoff=150):
    return _make("train", data_file, cutoff).word_idx


def train(word_idx=None, data_file=None):
    return dataset_reader(lambda: _make("train", data_file))


def test(word_idx=None, data_file=None):
    return dataset_reader(lambda: _make("test", data_file))
