"""Legacy paddle.dataset namespace (reader-generator style).

Reference parity: python/paddle/dataset/ — each module exposes
train()/test() functions returning sample GENERATORS (the fluid-1.x data
idiom consumed by DataLoader.from_generator / paddle.batch). Built over
the map-style datasets in paddle_tpu.text.datasets and
paddle_tpu.vision.datasets; local files only (zero-egress environment).
"""
from . import common  # noqa: F401
from . import conll05  # noqa: F401
from . import imdb  # noqa: F401
from . import imikolov  # noqa: F401
from . import mnist  # noqa: F401
from . import movielens  # noqa: F401
from . import uci_housing  # noqa: F401
from . import wmt14  # noqa: F401
from . import wmt16  # noqa: F401

__all__ = ["common", "conll05", "imdb", "imikolov", "mnist", "movielens",
           "uci_housing", "wmt14", "wmt16"]
