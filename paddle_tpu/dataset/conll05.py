"""Legacy paddle.dataset.conll05 (dataset/conll05.py parity)."""
from __future__ import annotations

from ._reader import dataset_reader


def _make(**kw):
    from ..text.datasets import Conll05st

    return Conll05st(**kw)


def get_dict(**kw):
    return _make(**kw).get_dict()


def get_embedding(emb_file=None, **kw):
    return _make(**kw).get_embedding(emb_file)


def test(**kw):
    return dataset_reader(lambda: _make(**kw))
