"""Legacy paddle.dataset.imikolov (dataset/imikolov.py parity)."""
from __future__ import annotations

from ._reader import dataset_reader

_CACHE = {}


def _make(mode, data_type, window_size, data_file=None, min_word_freq=50):
    from ..text.datasets import Imikolov

    key = (mode, data_type, window_size, data_file, min_word_freq)
    if key not in _CACHE:
        _CACHE[key] = Imikolov(
            data_file=data_file, data_type=data_type,
            window_size=window_size, mode=mode,
            min_word_freq=min_word_freq, download=data_file is None)
    return _CACHE[key]


def build_dict(min_word_freq=50, data_file=None):
    return _make("train", "SEQ", -1, data_file, min_word_freq).word_idx


def train(word_idx=None, n=5, data_type="NGRAM", data_file=None,
          min_word_freq=50):
    return dataset_reader(
        lambda: _make("train", data_type, n, data_file, min_word_freq))


def test(word_idx=None, n=5, data_type="NGRAM", data_file=None,
         min_word_freq=50):
    return dataset_reader(
        lambda: _make("test", data_type, n, data_file, min_word_freq))
