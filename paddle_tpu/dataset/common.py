"""paddle.dataset.common parity: data-home plumbing + file helpers.

Reference: python/paddle/dataset/common.py (DATA_HOME, md5file, download,
split/cluster_files_reader). Zero-egress environment: `download` raises a
clear error directing callers to pass local files instead.
"""
from __future__ import annotations

import glob
import hashlib
import os
import pickle

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def must_mkdirs(path):
    os.makedirs(path, exist_ok=True)


def download(url, module_name, md5sum, save_name=None):
    """Zero egress: look in DATA_HOME for an already-placed file; never
    fetch. The reference downloads from bcebos here."""
    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(
        dirname, save_name or url.split("/")[-1].split("%2F")[-1])
    if os.path.exists(filename):
        return filename
    raise RuntimeError(
        f"dataset file {filename!r} not found and this environment has no "
        f"network egress; place the file there manually (source: {url}) "
        f"or pass data_file= to the dataset constructor")


def _check_exists_and_download(path, url, md5, module_name, download_flag):
    if path and os.path.exists(path):
        return path
    if not download_flag:
        raise ValueError(
            f"{path!r} not found and download is disabled; pass a valid "
            f"local path")
    return download(url, module_name, md5)


def split(reader, line_count, suffix="%05d.pickle", dumper=pickle.dump):
    """Split a reader's samples into pickled chunk files (common.py:split
    parity — used by cluster data prep)."""
    indx_f = 0
    lines = []
    for i, d in enumerate(reader()):
        lines.append(d)
        if (i + 1) % line_count == 0:
            with open(suffix % indx_f, "wb") as f:
                dumper(lines, f)
            lines = []
            indx_f += 1
    if lines:
        with open(suffix % indx_f, "wb") as f:
            dumper(lines, f)


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=pickle.load):
    """Read this trainer's shard of chunked files (common.py parity)."""

    def reader():
        flist = sorted(glob.glob(files_pattern))
        my = flist[trainer_id::trainer_count]
        for fn in my:
            with open(fn, "rb") as f:
                for d in loader(f):
                    yield d

    return reader
