"""Legacy paddle.dataset.wmt14 (dataset/wmt14.py parity)."""
from __future__ import annotations

from ._reader import dataset_reader


def _make(mode, dict_size, data_file=None):
    from ..text.datasets import WMT14

    return WMT14(data_file=data_file, mode=mode, dict_size=dict_size,
                 download=data_file is None)


def train(dict_size, data_file=None):
    return dataset_reader(lambda: _make("train", dict_size, data_file))


def test(dict_size, data_file=None):
    return dataset_reader(lambda: _make("test", dict_size, data_file))


def gen(dict_size, data_file=None):
    return dataset_reader(lambda: _make("gen", dict_size, data_file))


def get_dict(dict_size, reverse=True, data_file=None):
    return _make("train", dict_size, data_file).get_dict(reverse)
