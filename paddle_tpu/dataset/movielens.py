"""Legacy paddle.dataset.movielens (dataset/movielens.py parity)."""
from __future__ import annotations

from ..text.datasets.movielens import (MovieInfo, UserInfo,  # noqa: F401
                                       age_table)
from ._reader import dataset_reader


def _make(mode, data_file=None):
    from ..text.datasets import Movielens

    return Movielens(data_file=data_file, mode=mode,
                     download=data_file is None)


def train(data_file=None):
    return dataset_reader(lambda: _make("train", data_file))


def test(data_file=None):
    return dataset_reader(lambda: _make("test", data_file))


def get_movie_title_dict(data_file=None):
    return _make("train", data_file).movie_title_dict


def max_movie_id(data_file=None):
    return max(_make("train", data_file).movie_info)


def max_user_id(data_file=None):
    return max(_make("train", data_file).user_info)


def movie_categories(data_file=None):
    return _make("train", data_file).categories_dict
