"""Legacy paddle.dataset.wmt16 (dataset/wmt16.py parity)."""
from __future__ import annotations

from ._reader import dataset_reader


def _make(mode, src_dict_size, trg_dict_size, src_lang, data_file=None):
    from ..text.datasets import WMT16

    return WMT16(data_file=data_file, mode=mode,
                 src_dict_size=src_dict_size, trg_dict_size=trg_dict_size,
                 lang=src_lang, download=data_file is None)


def train(src_dict_size, trg_dict_size, src_lang="en", data_file=None):
    return dataset_reader(
        lambda: _make("train", src_dict_size, trg_dict_size, src_lang,
                      data_file))


def test(src_dict_size, trg_dict_size, src_lang="en", data_file=None):
    return dataset_reader(
        lambda: _make("test", src_dict_size, trg_dict_size, src_lang,
                      data_file))


def validation(src_dict_size, trg_dict_size, src_lang="en", data_file=None):
    return dataset_reader(
        lambda: _make("val", src_dict_size, trg_dict_size, src_lang,
                      data_file))


def get_dict(lang, dict_size, reverse=False, data_file=None):
    return _make("train", dict_size, dict_size, "en",
                 data_file).get_dict(lang, reverse)
