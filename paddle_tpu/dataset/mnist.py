"""Legacy paddle.dataset.mnist (dataset/mnist.py parity): yields
(flattened normalized image, label) like the fluid-era reader."""
from __future__ import annotations

import numpy as np

from ._reader import dataset_reader


def _make(mode, image_path=None, label_path=None):
    from ..vision.datasets import MNIST

    return MNIST(image_path=image_path, label_path=label_path, mode=mode)


def _flatten(ds):
    def reader():
        for i in range(len(ds)):
            img, lbl = ds[i]
            img = np.asarray(img, np.float32).reshape(-1) / 127.5 - 1.0
            yield img, int(np.asarray(lbl).reshape(-1)[0])

    return reader


def train(image_path=None, label_path=None):
    return _flatten(_make("train", image_path, label_path))


def test(image_path=None, label_path=None):
    return _flatten(_make("test", image_path, label_path))
