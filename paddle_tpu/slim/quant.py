"""Quantization: post-training int8 + quantization-aware training.

Reference parity:
- PostTrainingQuantization (contrib/slim/quantization/
  post_training_quantization.py): load an inference model, run
  calibration batches collecting activation abs-max, quantize weights
  per-channel, rewrite the program, save the deployable artifact.
- QuantizationTransformPass (quantization_pass.py:211) — here the
  rewrite swaps quantizable ops for `quantized_*` op types whose
  lowerings do int8 MXU math (fluid/lowering.py).
- ImperativeQuantAware (imperative/qat.py): wrap Linear/Conv2D with
  straight-through fake-quant for QAT; export via paddle.jit.save.
"""
from __future__ import annotations

import copy
import os

import numpy as np

QUANTIZABLE_OP_TYPES = ("conv2d", "depthwise_conv2d", "mul", "matmul",
                        "matmul_v2")

# op type -> (activation input slot, weight input slot, weight out-channel
# axis for per-channel scales)
_OP_SLOTS = {
    "conv2d": ("Input", "Filter", 0),
    "depthwise_conv2d": ("Input", "Filter", 0),
    "mul": ("X", "Y", 1),
    "matmul": ("X", "Y", 1),
    "matmul_v2": ("X", "Y", 1),
}


class PostTrainingQuantization:
    """Calibrate + quantize a saved inference model.

    usage:
        ptq = PostTrainingQuantization(
            executor=exe, model_dir=fp32_dir,
            sample_generator=gen,       # yields feed dicts
            batch_nums=8)
        program = ptq.quantize()
        ptq.save_quantized_model(int8_dir)
    """

    def __init__(self, executor, model_dir, sample_generator=None,
                 data_loader=None, batch_nums=8, algo="abs_max",
                 quantizable_op_type=QUANTIZABLE_OP_TYPES,
                 weight_quantize_type="channel_wise_abs_max",
                 model_filename=None, params_filename=None, scope=None):
        from ..fluid.executor import Scope
        from ..fluid.io import load_inference_model

        self.exe = executor
        self.model_dir = model_dir
        self.samples = sample_generator or data_loader
        self.batch_nums = batch_nums
        self.algo = algo
        self.op_types = tuple(quantizable_op_type)
        self.weight_qtype = weight_quantize_type
        self.scope = scope or Scope()
        from ..fluid.executor import scope_guard

        with scope_guard(self.scope):
            prog, feeds, fetches = load_inference_model(
                model_dir, executor, model_filename, params_filename)
        self.program = prog
        self.feed_names = feeds
        self.fetch_vars = fetches
        self._quant_program = None

    # ------------------------------------------------------------------
    def _calibrate(self):
        """Per-quantizable-op activation abs-max over calibration batches
        (algo='abs_max'; the reference's KL/hist algos reduce to scale
        selection over the same collected maxima)."""
        from ..fluid.executor import scope_guard

        act_names = []
        for op in self.program.global_block().ops:
            if op.type in self.op_types and op.type in _OP_SLOTS:
                a_slot, _, _ = _OP_SLOTS[op.type]
                n = op.input(a_slot)
                if n:
                    act_names.append(n[0])
        act_names = sorted(set(act_names))
        maxima = {n: 0.0 for n in act_names}
        if not self.samples:
            raise ValueError("PostTrainingQuantization needs a "
                             "sample_generator/data_loader to calibrate")
        with scope_guard(self.scope):
            batches = self.samples() if callable(self.samples) \
                else iter(self.samples)
            for i, feed in enumerate(batches):
                if i >= self.batch_nums:
                    break
                if not isinstance(feed, dict):
                    # DataLoader batches: positional, in feed-name order
                    vals = feed if isinstance(feed, (list, tuple)) \
                        else [feed]
                    feed = {n: np.asarray(v)
                            for n, v in zip(self.feed_names, vals)}
                outs = self.exe.run(self.program, feed=feed,
                                    fetch_list=act_names,
                                    scope=self.scope)
                for n, v in zip(act_names, outs):
                    maxima[n] = max(maxima[n],
                                    float(np.abs(np.asarray(v)).max()))
        return maxima

    # ------------------------------------------------------------------
    def quantize(self):
        if self._quant_program is not None:
            return self._quant_program  # idempotent
        act_max = self._calibrate()
        prog = copy.deepcopy(self.program)
        blk = prog.global_block()

        def op_ch_axis(op):
            ch = _OP_SLOTS[op.type][2]
            if op.type in ("matmul", "matmul_v2") and op.attrs.get(
                    "transpose_Y", op.attrs.get("trans_y", False)):
                ch = 0
            return ch

        # pass 1: every consumer votes on its weight's channel axis; a
        # disagreement (e.g. a weight used both plain and transposed)
        # falls back to one per-tensor scale — decided BEFORE any attr or
        # scope write so all consumers see consistent scales
        consumers = {}
        for op in blk.ops:
            if op.type not in self.op_types or op.type not in _OP_SLOTS:
                continue
            a_slot, w_slot, _ = _OP_SLOTS[op.type]
            if not op.input(a_slot) or not op.input(w_slot):
                continue
            a_name, w_name = op.input(a_slot)[0], op.input(w_slot)[0]
            if a_name not in act_max:
                continue
            if self.scope.get_value(w_name) is None:
                continue
            consumers.setdefault(w_name, []).append((op, a_name,
                                                     op_ch_axis(op)))

        quantized = {}  # w_name -> (ch_axis or -1/-2, scales)
        for w_name, uses in consumers.items():
            w = np.asarray(self.scope.get_value(w_name), np.float32)
            axes = {ax for _, _, ax in uses}
            per_channel = (self.weight_qtype == "channel_wise_abs_max"
                           and len(axes) == 1)
            if per_channel:
                ch_axis = axes.pop()
                red = tuple(i for i in range(w.ndim) if i != ch_axis)
                s_w = np.maximum(np.abs(w).max(axis=red), 1e-8) / 127.0
                shape = [1] * w.ndim
                shape[ch_axis] = -1
                w_q = np.clip(np.round(w / s_w.reshape(shape)),
                              -127, 127).astype(np.int8)
                scales = [float(x) for x in np.atleast_1d(s_w)]
                quantized[w_name] = (ch_axis, scales)
            else:
                s_w = max(float(np.abs(w).max()), 1e-8) / 127.0
                w_q = np.clip(np.round(w / s_w),
                              -127, 127).astype(np.int8)
                quantized[w_name] = (-1, [s_w])
            self.scope.set_value(w_name, w_q)
            if blk.has_var(w_name):
                blk.var(w_name).dtype = np.dtype(np.int8)

        # pass 2: rewrite consumer ops with the final shared scales
        for w_name, uses in consumers.items():
            ch_axis, scales = quantized[w_name]
            for op, a_name, _ in uses:
                s_in = max(act_max[a_name], 1e-8) / 127.0
                op.type = "quantized_" + op.type
                op.attrs["in_scale"] = float(s_in)
                op.attrs["weight_scales"] = scales
                op.attrs["weight_channel_axis"] = ch_axis
        self._quant_program = prog
        return prog

    # ------------------------------------------------------------------
    def save_quantized_model(self, save_model_path, model_filename=None,
                             params_filename=None):
        from ..fluid.executor import scope_guard
        from ..fluid.io import save_inference_model

        if self._quant_program is None:
            self.quantize()
        with scope_guard(self.scope):
            save_inference_model(
                save_model_path, self.feed_names,
                [self._quant_program.global_block().var(v.name)
                 for v in self.fetch_vars],
                self.exe, main_program=self._quant_program,
                model_filename=model_filename,
                params_filename=params_filename)


def quant_post_static(executor, model_dir, quantize_model_path,
                      sample_generator=None, data_loader=None,
                      batch_nums=8, algo="abs_max",
                      quantizable_op_type=QUANTIZABLE_OP_TYPES, **kw):
    """paddleslim.quant.quant_post_static-shaped convenience wrapper."""
    ptq = PostTrainingQuantization(
        executor, model_dir, sample_generator=sample_generator,
        data_loader=data_loader, batch_nums=batch_nums, algo=algo,
        quantizable_op_type=quantizable_op_type, **kw)
    ptq.quantize()
    ptq.save_quantized_model(quantize_model_path)
    return ptq


# ==========================================================================
# QAT: straight-through fake quantization for eager layers
# ==========================================================================

def fake_quant(x, scale, bits=8):
    """Quantize-dequantize with a straight-through gradient
    (fake_quantize_dequantize ops + the STE the reference's QAT uses)."""
    import jax

    bound = 2.0 ** (bits - 1) - 1

    @jax.custom_vjp
    def f(x, s):
        q = jax.numpy.clip(jax.numpy.round(x / s), -bound, bound)
        return q * s

    def fwd(x, s):
        return f(x, s), (x, s)

    def bwd(res, g):
        x, s = res
        mask = (jax.numpy.abs(x) <= bound * s).astype(g.dtype)
        return g * mask, None

    f.defvjp(fwd, bwd)
    return f(x, scale)


class _QuantWrapper:
    """Mixin: weight abs-max fake quant + activation moving-max quant."""

    def _init_qat(self, inner, momentum=0.9, weight_bits=8,
                  activation_bits=8):
        self._inner = inner
        self._act_max = 1.0
        self._mom = momentum
        self._w_bits = weight_bits
        self._a_bits = activation_bits

    def _quant_act(self, x, training=True):
        from ..core.tensor import apply_op

        raw = x._data
        if training and not _is_tracer(raw):
            # numpy on the host: under an active jit trace every jnp op
            # is staged (omnistaging), but concrete arrays convert fine
            cur = float(np.abs(np.asarray(raw)).max())
            self._act_max = self._mom * self._act_max + \
                (1 - self._mom) * max(cur, 1e-8)
        bound = 2.0 ** (self._a_bits - 1) - 1
        s = max(self._act_max, 1e-8) / bound
        bits = self._a_bits
        # through the tape so the STE gradient reaches upstream layers
        return apply_op("fake_quant_act",
                        lambda r: fake_quant(r, s, bits), [x]), s

    def _quant_w(self, w):
        from ..core.tensor import apply_op

        bound = 2.0 ** (self._w_bits - 1) - 1
        if not _is_tracer(w._data):
            absmax = float(np.abs(np.asarray(w._data)).max())
            self._w_scale = max(absmax, 1e-8) / bound
        s = getattr(self, "_w_scale", 1.0 / bound)
        bits = self._w_bits
        return apply_op("fake_quant_weight",
                        lambda r: fake_quant(r, s, bits), [w])


def _is_tracer(v):
    import jax.core

    return isinstance(v, jax.core.Tracer)


class QuantedLinear(_QuantWrapper):
    """Declared as an nn.Layer holding the ORIGINAL parameters under the
    original names ('weight'/'bias'), so state_dict keys are unchanged
    after quantization (the reference ImperativeQuantAware contract)."""

    def __new__(cls, inner, weight_bits=8, activation_bits=8):
        from .. import nn

        class _Q(nn.Layer, _QuantWrapper):
            def __init__(self, inner):
                super().__init__()
                self._init_qat(inner, weight_bits=weight_bits,
                               activation_bits=activation_bits)
                self.weight = inner.weight
                self.bias = inner.bias

            def forward(self, x):
                import paddle_tpu.nn.functional as F

                xq, _ = self._quant_act(x, self.training)
                wq = self._quant_w(self.weight)
                return F.linear(xq, wq, self.bias)

        return _Q(inner)


class QuantedConv2D(_QuantWrapper):
    def __new__(cls, inner, weight_bits=8, activation_bits=8):
        from .. import nn

        class _Q(nn.Layer, _QuantWrapper):
            def __init__(self, inner):
                super().__init__()
                self._init_qat(inner, weight_bits=weight_bits,
                               activation_bits=activation_bits)
                self.weight = inner.weight
                self.bias = inner.bias
                self._cfg = (inner._stride, inner._padding,
                             inner._dilation, inner._groups)

            def forward(self, x):
                import paddle_tpu.nn.functional as F

                st, pad, dil, grp = self._cfg
                xq, _ = self._quant_act(x, self.training)
                wq = self._quant_w(self.weight)
                return F.conv2d(xq, wq, self.bias, stride=st, padding=pad,
                                dilation=dil, groups=grp)

        return _Q(inner)


class ImperativeQuantAware:
    """imperative/qat.py parity: wrap quantizable sublayers in place,
    preserving parameter names."""

    def __init__(self, quantizable_layer_type=("Linear", "Conv2D"),
                 weight_bits=8, activation_bits=8, **kw):
        self.types = tuple(quantizable_layer_type)
        self.weight_bits = int(weight_bits)
        self.activation_bits = int(activation_bits)

    def quantize(self, model):
        from .. import nn

        type_map = {"Linear": (nn.Linear, QuantedLinear),
                    "Conv2D": (nn.Conv2D, QuantedConv2D)}
        wanted = [type_map[t] for t in self.types if t in type_map]

        def walk(layer):
            for name, sub in list(getattr(layer, "_sub_layers",
                                          {}).items()):
                replaced = False
                for cls, qcls in wanted:
                    if isinstance(sub, cls):
                        layer._sub_layers[name] = qcls(
                            sub, self.weight_bits, self.activation_bits)
                        replaced = True
                        break
                if not replaced:
                    walk(sub)

        walk(model)
        return model

    def save_quantized_model(self, model, path, input_spec=None, **kw):
        from .. import jit

        jit.save(model, path, input_spec=input_spec)


# ==========================================================================
# Static-graph QAT: fake-quant ops in the program IR (VERDICT r02 #4)
# ==========================================================================

class QuantizationTransformPass:
    """Insert fake-quant ops around quantizable ops in a TRAINING program
    (reference contrib/slim/quantization/quantization_pass.py:211 +
    operators/fake_quantize_op.cc:182).

    Activations get `fake_quantize_moving_average_abs_max` (or abs_max /
    range_abs_max) with persistable scale/state/accum vars that stream
    across steps through the executor's persistable writeback; weights get
    `fake_channel_wise_quantize_abs_max` (or abs_max). All quantizers are
    straight-through estimators, so append_backward/minimize trains
    through them unchanged — run the pass BEFORE minimize().

    usage:
        pass_ = QuantizationTransformPass(scope=scope)
        pass_.apply(main_program)
        opt.minimize(loss)             # backward sees the fake ops
        ... train ...
        QuantizationFreezePass(scope).apply(main_program)  # -> int8
    """

    def __init__(self, scope=None, weight_bits=8, activation_bits=8,
                 activation_quantize_type="moving_average_abs_max",
                 weight_quantize_type="channel_wise_abs_max",
                 moving_rate=0.9, window_size=10000,
                 quantizable_op_type=QUANTIZABLE_OP_TYPES):
        from ..fluid.executor import global_scope

        self.scope = scope or global_scope()
        self.wbits = int(weight_bits)
        self.abits = int(activation_bits)
        self.act_type = activation_quantize_type
        self.weight_qtype = weight_quantize_type
        self.moving_rate = float(moving_rate)
        self.window_size = int(window_size)
        self.op_types = tuple(quantizable_op_type)

    # -- helpers -----------------------------------------------------------
    def _state_var(self, blk, name, value, dtype="float32"):
        if not blk.has_var(name):
            v = blk.create_var(name=name, shape=[1], dtype=dtype)
            v.persistable = True
        if self.scope.get_value(name) is None:
            self.scope.set_value(name, np.full((1,), value, dtype))
        return name

    def _insert_act_quant(self, blk, idx, name):
        q = f"{name}.quantized"
        blk.create_var(name=q)
        scale = self._state_var(blk, f"{name}.quant_scale", 1.0)
        if self.act_type == "moving_average_abs_max":
            state = self._state_var(blk, f"{name}.quant_state", 1.0)
            accum = self._state_var(blk, f"{name}.quant_accum", 1.0)
            blk._insert_op(
                idx, type="fake_quantize_moving_average_abs_max",
                inputs={"X": [name], "InScale": [scale],
                        "InState": [state], "InAccum": [accum]},
                outputs={"Out": [q], "OutScale": [scale],
                         "OutState": [state], "OutAccum": [accum]},
                attrs={"bit_length": self.abits,
                       "moving_rate": self.moving_rate})
        elif self.act_type == "range_abs_max":
            if not blk.has_var(f"{name}.quant_scales_arr"):
                v = blk.create_var(name=f"{name}.quant_scales_arr",
                                   shape=[self.window_size],
                                   dtype="float32")
                v.persistable = True
            if self.scope.get_value(f"{name}.quant_scales_arr") is None:
                self.scope.set_value(
                    f"{name}.quant_scales_arr",
                    np.zeros((self.window_size,), np.float32))
            it = self._state_var(blk, f"{name}.quant_iter", 0,
                                 dtype="int32")
            blk._insert_op(
                idx, type="fake_quantize_range_abs_max",
                inputs={"X": [name], "InScale": [scale],
                        "Iter": [it],
                        "InScales": [f"{name}.quant_scales_arr"]},
                outputs={"Out": [q], "OutScale": [scale],
                         "OutScales": [f"{name}.quant_scales_arr"],
                         "OutIter": [it]},
                attrs={"bit_length": self.abits,
                       "window_size": self.window_size})
        else:  # abs_max: stateless
            blk._insert_op(
                idx, type="fake_quantize_abs_max",
                inputs={"X": [name]},
                outputs={"Out": [q], "OutScale": [scale]},
                attrs={"bit_length": self.abits})
        return q

    def _insert_weight_quant(self, blk, idx, name, ch_axis):
        q = f"{name}.quantized"
        blk.create_var(name=q)
        scale = f"{name}.quant_scale_w"
        if not blk.has_var(scale):
            sv = blk.create_var(name=scale, dtype="float32")
            sv.persistable = True
        if self.weight_qtype == "channel_wise_abs_max":
            blk._insert_op(
                idx, type="fake_channel_wise_quantize_abs_max",
                inputs={"X": [name]},
                outputs={"Out": [q], "OutScale": [scale]},
                attrs={"bit_length": self.wbits, "quant_axis": ch_axis})
        else:
            blk._insert_op(
                idx, type="fake_quantize_abs_max",
                inputs={"X": [name]},
                outputs={"Out": [q], "OutScale": [scale]},
                attrs={"bit_length": self.wbits})
        return q

    # ----------------------------------------------------------------------
    def apply(self, program):
        blk = program.global_block()
        done = {}          # original name -> quantized name
        i = 0
        while i < len(blk.ops):
            op = blk.ops[i]
            if op.type in self.op_types and op.type in _OP_SLOTS:
                a_slot, w_slot, ch = _OP_SLOTS[op.type]
                if op.input(a_slot) and op.input(w_slot):
                    a = op.input(a_slot)[0]
                    w = op.input(w_slot)[0]
                    if a.endswith(".quantized") or \
                            w.endswith(".quantized"):
                        i += 1
                        continue
                    if a not in done:
                        done[a] = self._insert_act_quant(blk, i, a)
                        i += 1
                    if w not in done:
                        done[w] = self._insert_weight_quant(blk, i, w,
                                                            ch)
                        i += 1
                    op.inputs[a_slot] = [done[a]]
                    op.inputs[w_slot] = [done[w]]
            i += 1
        return program


_FAKE_QUANT_TYPES = (
    "fake_quantize_abs_max", "fake_quantize_range_abs_max",
    "fake_quantize_moving_average_abs_max",
    "fake_channel_wise_quantize_abs_max", "moving_average_abs_max_scale")


class QuantizationFreezePass:
    """Convert a QAT-trained program into the deployable int8 form
    (reference quantization_pass.py QuantizationFreezePass): drop the
    fake-quant ops, bake the streamed activation scales and the final
    per-channel weight scales into `quantized_*` op attrs, store int8
    weights in the scope."""

    def __init__(self, scope=None, weight_bits=8,
                 weight_quantize_type="channel_wise_abs_max"):
        from ..fluid.executor import global_scope

        self.scope = scope or global_scope()
        self.wbits = int(weight_bits)
        self.weight_qtype = weight_quantize_type

    def apply(self, program):
        blk = program.global_block()
        # map quantized-var name -> source name
        strip = lambda n: n[:-len(".quantized")] \
            if n.endswith(".quantized") else n          # noqa: E731
        new_ops = []
        done_w = {}   # weight name -> (scales, ch_axis): quantize ONCE
        # (a shared weight re-read as int8 would yield abs-max ~127 and
        # bake garbage scales into its second consumer; also makes the
        # pass idempotent)
        for op in blk.ops:
            if op.type in _FAKE_QUANT_TYPES:
                continue  # dropped; scales live in the scope
            if op.type in self.op_types_map():
                a_slot, w_slot, ch = _OP_SLOTS[op.type]
                a_q = op.input(a_slot)[0] if op.input(a_slot) else ""
                w_q = op.input(w_slot)[0] if op.input(w_slot) else ""
                if a_q.endswith(".quantized") or \
                        w_q.endswith(".quantized"):
                    a, w = strip(a_q), strip(w_q)
                    op.inputs[a_slot] = [a]
                    op.inputs[w_slot] = [w]
                    s_act = self.scope.get_value(f"{a}.quant_scale")
                    s_in = max(float(np.asarray(s_act).reshape(-1)[0]),
                               1e-8) / 127.0 if s_act is not None \
                        else 1.0 / 127.0
                    if w in done_w:
                        scales, ch_axis = done_w[w]
                        op.type = "quantized_" + op.type
                        op.attrs["in_scale"] = float(s_in)
                        op.attrs["weight_scales"] = scales
                        op.attrs["weight_channel_axis"] = ch_axis
                        new_ops.append(op)
                        continue
                    wv = np.asarray(self.scope.get_value(w), np.float32)
                    if np.asarray(self.scope.get_value(w)).dtype == \
                            np.int8:
                        raise RuntimeError(
                            f"QuantizationFreezePass: weight {w!r} is "
                            "already int8 — the pass ran twice on this "
                            "program/scope")
                    if self.weight_qtype == "channel_wise_abs_max":
                        red = tuple(i for i in range(wv.ndim)
                                    if i != ch)
                        s_w = np.maximum(np.abs(wv).max(axis=red),
                                         1e-8) / 127.0
                        shape = [1] * wv.ndim
                        shape[ch] = -1
                        w_q8 = np.clip(np.round(wv / s_w.reshape(shape)),
                                       -127, 127).astype(np.int8)
                        scales = [float(x) for x in np.atleast_1d(s_w)]
                        ch_axis = ch
                    else:
                        s = max(float(np.abs(wv).max()), 1e-8) / 127.0
                        w_q8 = np.clip(np.round(wv / s),
                                       -127, 127).astype(np.int8)
                        scales, ch_axis = [s], -1
                    self.scope.set_value(w, w_q8)
                    done_w[w] = (scales, ch_axis)
                    if blk.has_var(w):
                        blk.var(w).dtype = np.dtype(np.int8)
                    op.type = "quantized_" + op.type
                    op.attrs["in_scale"] = float(s_in)
                    op.attrs["weight_scales"] = scales
                    op.attrs["weight_channel_axis"] = ch_axis
            new_ops.append(op)
        blk.ops[:] = new_ops
        return program

    @staticmethod
    def op_types_map():
        return _OP_SLOTS
