"""paddle.slim: quantization (PTQ + QAT).

Reference parity: fluid/contrib/slim/quantization —
post_training_quantization.py (PostTrainingQuantization),
quantization_pass.py:211 (QuantizationTransformPass) and imperative QAT
(imperative/qat.py ImperativeQuantAware). TPU-native design: PTQ is a
program-IR pass whose output runs REAL int8 matmuls on the MXU
(lax.dot_general with int8 operands accumulating in int32), not a
simulated pass; QAT wraps layers with straight-through fake-quant so the
trained model exports to the same artifact family.
"""
from .quant import (ImperativeQuantAware, PostTrainingQuantization,
                    quant_post_static)

__all__ = ["PostTrainingQuantization", "quant_post_static",
           "ImperativeQuantAware"]
