"""Sequence decoding: greedy + beam search, TPU-native.

Reference parity: beam_search_op.cc / beam_search_decode_op.cc and the
machine-translation book's decoder. The reference threads LoD beams
through an op-by-op interpreter; here the WHOLE decode loop is one
`lax.scan` over steps with a fixed beam width — static shapes, one XLA
computation, jit/vmap-able, runs on device end to end.

step_fn(tokens [N] int32, state pytree with leading N) ->
    (logits [N, V], new_state) — one model step for N rows (the
    decoder's token + cache shape; N = batch*beam inside beam_search).
"""
from __future__ import annotations

NEG = -1e9


def _jnp():
    import jax.numpy as jnp

    return jnp


def greedy_search(step_fn, init_state, batch_size, bos_id, eos_id,
                  max_len, init_logits=None):
    """Argmax decoding. Returns (tokens [B, max_len], lengths [B]).

    init_logits ([B, V], optional): next-token logits already computed
    for the sequence prefix — the decode engine's PREFILL output. When
    given, the FIRST of the max_len tokens is argmax(init_logits) and
    the scan runs max_len - 1 steps; step_fn is then only ever called
    with tokens the cache has not seen (single-token decode steps)."""
    import jax

    jnp = _jnp()

    def step(carry, _):
        tok, state, done, length = carry
        logits, state = step_fn(tok, state)
        nxt = logits.argmax(-1).astype(jnp.int32)
        nxt = jnp.where(done, eos_id, nxt)
        done_new = done | (nxt == eos_id)
        length = length + (~done).astype(jnp.int32)
        return (nxt, state, done_new, length), nxt

    if init_logits is None:
        tok0 = jnp.full((batch_size,), bos_id, jnp.int32)
        done0 = jnp.zeros((batch_size,), bool)
        len0 = jnp.zeros((batch_size,), jnp.int32)
        scan_len = max_len
    else:
        tok0 = init_logits.argmax(-1).astype(jnp.int32)
        done0 = tok0 == eos_id
        len0 = jnp.ones((batch_size,), jnp.int32)
        scan_len = max_len - 1
    (_, _, _, lengths), toks = jax.lax.scan(
        step, (tok0, init_state, done0, len0), None, length=scan_len)
    toks = jnp.moveaxis(toks, 0, 1)
    if init_logits is not None:
        toks = jnp.concatenate([tok0[:, None], toks], axis=1)
    return toks, lengths


def beam_search(step_fn, init_state, batch_size, bos_id, eos_id,
                beam_size, max_len, length_penalty=0.0,
                return_state=False, init_logits=None):
    """Beam search. Returns (tokens [B, K, max_len] best-first,
    scores [B, K], lengths [B, K]) — plus each beam's final state
    (best-first, leading dim B*K) when return_state=True.

    States must have leading dim batch_size; they are tiled to
    batch*beam internally and re-gathered as beams reshuffle.

    init_logits ([B, V], optional, V >= K): prefix logits from the
    decode engine's prefill — the first expansion is top_k over THEM
    (equivalent to the classic first step, where only beam 0 is live)
    and the scan runs max_len - 1 steps on cache-backed decode tokens.
    """
    import jax

    jnp = _jnp()
    B, K = batch_size, beam_size

    def tile(t):
        return jnp.repeat(t, K, axis=0)  # [B*K, ...] beam-major rows

    state0 = jax.tree_util.tree_map(tile, init_state)
    if init_logits is None:
        # beam 0 starts live, others dead so the first expansion is
        # unique. f32 explicitly: under jax_enable_x64 a bare float
        # list is f64, which would promote the whole scoring scan to
        # emulated f64 on TPU
        logp0 = jnp.tile(jnp.asarray([0.0] + [NEG] * (K - 1),
                                     jnp.float32), (B, 1))
        tok0 = jnp.full((B, K), bos_id, jnp.int32)
        fin0 = jnp.zeros((B, K), bool)
        len0 = jnp.zeros((B, K), jnp.int32)
        scan_len = max_len
    else:
        lp_init = jax.nn.log_softmax(init_logits.astype(jnp.float32), -1)
        logp0, top_ix = jax.lax.top_k(lp_init, K)        # [B, K]
        tok0 = top_ix.astype(jnp.int32)
        fin0 = tok0 == eos_id
        len0 = jnp.ones((B, K), jnp.int32)
        scan_len = max_len - 1

    def step(carry, _):
        tok, logp, fin, lens, state = carry
        logits, state = step_fn(tok.reshape(B * K), state)
        V = logits.shape[-1]
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        lp = lp.reshape(B, K, V)
        # finished beams: only EOS continues, at no additional cost
        fin_mask = jnp.full((V,), NEG, jnp.float32).at[eos_id].set(0.0)
        lp = jnp.where(fin[:, :, None], fin_mask[None, None, :], lp)
        total = logp[:, :, None] + lp                  # [B, K, V]
        flat = total.reshape(B, K * V)
        top_lp, top_ix = jax.lax.top_k(flat, K)        # [B, K]
        src_beam = (top_ix // V).astype(jnp.int32)
        nxt_tok = (top_ix % V).astype(jnp.int32)

        def regather(t):
            tb = t.reshape((B, K) + t.shape[1:])
            out = jnp.take_along_axis(
                tb, src_beam.reshape((B, K) + (1,) * (t.ndim - 1)),
                axis=1)
            return out.reshape((B * K,) + t.shape[1:])

        state = jax.tree_util.tree_map(regather, state)
        fin = jnp.take_along_axis(fin, src_beam, axis=1)
        lens = jnp.take_along_axis(lens, src_beam, axis=1)
        lens = lens + (~fin).astype(jnp.int32)
        fin = fin | (nxt_tok == eos_id)
        return (nxt_tok, top_lp, fin, lens, state), (nxt_tok, src_beam)

    (tokT, logpT, finT, lensT, stateT), (toks, srcs) = jax.lax.scan(
        step, (tok0, logp0, fin0, len0, state0), None, length=scan_len)

    # backtrace beam ancestry so each final beam reads its OWN history
    def bwd(beam_ix, t):
        tok_t = jnp.take_along_axis(toks[t], beam_ix, axis=1)
        prev = jnp.take_along_axis(srcs[t], beam_ix, axis=1)
        return prev, tok_t

    init_ix = jnp.tile(jnp.arange(K, dtype=jnp.int32), (B, 1))
    first_ix, rev = jax.lax.scan(bwd, init_ix,
                                 jnp.arange(scan_len - 1, -1, -1))
    seqs = jnp.flip(jnp.moveaxis(rev, 0, 2), axis=2)  # [B, K, L]
    if init_logits is not None:
        # the ancestry bottoms out in the init expansion: prepend each
        # final beam's OWN first token
        first = jnp.take_along_axis(tok0, first_ix, axis=1)
        seqs = jnp.concatenate([first[:, :, None], seqs], axis=2)

    # length-penalized scores, best-first
    denom = jnp.maximum(lensT, 1).astype(jnp.float32) ** length_penalty
    scores = logpT / denom
    order = jnp.argsort(-scores, axis=1)
    seqs = jnp.take_along_axis(seqs, order[:, :, None], axis=1)
    scores = jnp.take_along_axis(scores, order, axis=1)
    lens = jnp.take_along_axis(lensT, order, axis=1)
    if return_state:
        def reorder(t):
            tb = t.reshape((B, K) + t.shape[1:])
            out = jnp.take_along_axis(
                tb, order.reshape((B, K) + (1,) * (t.ndim - 1)), axis=1)
            return out.reshape((B * K,) + t.shape[1:])

        stateF = jax.tree_util.tree_map(reorder, stateT)
        return seqs, scores, lens, stateF
    return seqs, scores, lens
