"""Sequence decoding: greedy + beam search, TPU-native.

Reference parity: beam_search_op.cc / beam_search_decode_op.cc and the
machine-translation book's decoder. The reference threads LoD beams
through an op-by-op interpreter; here the WHOLE decode loop is one
`lax.scan` over steps with a fixed beam width — static shapes, one XLA
computation, jit/vmap-able, runs on device end to end.

step_fn(tokens [N] int32, state pytree with leading N) ->
    (logits [N, V], new_state) — one model step for N rows (the
    decoder's token + cache shape; N = batch*beam inside beam_search).
"""
from __future__ import annotations

NEG = -1e9


def _jnp():
    import jax.numpy as jnp

    return jnp


def greedy_search(step_fn, init_state, batch_size, bos_id, eos_id,
                  max_len, init_logits=None):
    """Argmax decoding. Returns (tokens [B, max_len], lengths [B]).

    init_logits ([B, V], optional): next-token logits already computed
    for the sequence prefix — the decode engine's PREFILL output. When
    given, the FIRST of the max_len tokens is argmax(init_logits) and
    the scan runs max_len - 1 steps; step_fn is then only ever called
    with tokens the cache has not seen (single-token decode steps)."""
    import jax

    jnp = _jnp()

    def step(carry, _):
        tok, state, done, length = carry
        logits, state = step_fn(tok, state)
        nxt = logits.argmax(-1).astype(jnp.int32)
        nxt = jnp.where(done, eos_id, nxt)
        done_new = done | (nxt == eos_id)
        length = length + (~done).astype(jnp.int32)
        return (nxt, state, done_new, length), nxt

    if init_logits is None:
        tok0 = jnp.full((batch_size,), bos_id, jnp.int32)
        done0 = jnp.zeros((batch_size,), bool)
        len0 = jnp.zeros((batch_size,), jnp.int32)
        scan_len = max_len
    else:
        tok0 = init_logits.argmax(-1).astype(jnp.int32)
        done0 = tok0 == eos_id
        len0 = jnp.ones((batch_size,), jnp.int32)
        scan_len = max_len - 1
    (_, _, _, lengths), toks = jax.lax.scan(
        step, (tok0, init_state, done0, len0), None, length=scan_len)
    toks = jnp.moveaxis(toks, 0, 1)
    if init_logits is not None:
        toks = jnp.concatenate([tok0[:, None], toks], axis=1)
    return toks, lengths


def greedy_accept(drafts, preds):
    """Greedy draft-verify acceptance. drafts [B, K-1] are the proposed
    continuation tokens; preds [B, K] = argmax of the verify logits for
    the fed block [pending, d_1, ..., d_{K-1}] — preds[:, i] is the
    oracle token FOLLOWING fed position i. Draft i is accepted only
    while every earlier draft matched its oracle (the classic greedy
    speculative-decoding rule, which keeps the output bit-identical to
    plain greedy for any draft source).

    Returns (n_match [B] in [0, K-1], emit [B, K]): emit[:, i] is the
    i-th newly emitted token — the accepted drafts, then the correction
    token preds[:, n_match] at position n_match (positions past n_match
    repeat the correction token; callers emit only n_match + 1)."""
    jnp = _jnp()
    K = preds.shape[1]
    match = (drafts == preds[:, :-1]).astype(jnp.int32)
    # explicit int32: under jax_enable_x64 integer reductions promote
    # to int64, which would poison the while-loop carry dtypes
    n_match = jnp.cumprod(match, axis=1).sum(
        axis=1).astype(jnp.int32)                             # [B]
    corr = jnp.take_along_axis(preds, n_match[:, None], axis=1)
    ii = jnp.arange(K, dtype=jnp.int32)[None, :]
    dpad = jnp.concatenate([drafts, corr], axis=1)            # [B, K]
    emit = jnp.where(ii < n_match[:, None], dpad, corr)
    return n_match, emit.astype(jnp.int32)


def spec_greedy_search(verify_fn, draft_fn, rollback_fn, init_state,
                       batch_size, eos_id, max_len, k, init_logits,
                       return_stats=False):
    """Speculative greedy decoding: the draft-verify counterpart of
    `greedy_search`. Each round proposes k - 1 draft tokens, runs ONE
    k-token verify step (the pending token plus the drafts, written
    into the cache at each row's current offset), accepts the longest
    matching prefix with `greedy_accept`, and rolls the cache back to
    the accepted length. Greedy acceptance keeps the output
    BIT-IDENTICAL to plain greedy decoding for ANY draft source; the
    whole generation is one fixed-k `lax.while_loop` whose carry holds
    the accepted-count arithmetic, so variable accept-lengths never
    change a shape and never retrace.

    verify_fn(tokens [B, k], state) -> (logits [B, k, V], state) — one
        k-token model step; must write the fed tokens at each row's
        current cache offset (`ops.attention.kv_verify_scope`).
    draft_fn(pending [B], emitted [B], state) -> (drafts [B, k-1],
        state) — any deterministic proposal source: n-gram
        self-speculation, a small draft model stepping its own cache.
    rollback_fn(state, n_match [B], active [B]) -> state — set every
        cache write index back to (pre-verify index + 1 + n_match) on
        active rows, and pin inactive rows' indices (verify advanced
        them all by k).

    init_logits [B, V]: prefill logits; the first emitted token is
    their argmax, exactly as in `greedy_search(init_logits=...)`.

    Returns (tokens [B, max_len], lengths [B]); with return_stats=True
    also a {"rounds", "proposed", "accepted"} dict of traced scalars —
    accepted counts draft tokens that were emitted, so the wasted-draft
    telemetry is exact."""
    import jax

    jnp = _jnp()
    B, K = batch_size, k
    tok0 = init_logits.argmax(-1).astype(jnp.int32)
    done0 = tok0 == eos_id
    # the buffer is k wider than max_len so a round's fixed-k block
    # write never clips; initialized to eos so capped tails match the
    # plain greedy convention (tokens after eos are eos)
    buf0 = jnp.full((B, max_len + K), eos_id, jnp.int32)
    buf0 = buf0.at[:, 0].set(tok0)
    cnt0 = jnp.ones((B,), jnp.int32)
    z = jnp.int32(0)
    carry0 = (tok0, init_state, done0, cnt0, buf0, z, z, z)

    def cond(carry):
        return ~jnp.all(carry[2])

    def body(carry):
        pending, state, done, cnt, buf, rounds, prop, acc = carry
        active = ~done
        drafts, state = draft_fn(pending, cnt, state)
        fed = jnp.concatenate([pending[:, None], drafts], axis=1)
        logits, state = verify_fn(fed, state)
        preds = logits.argmax(-1).astype(jnp.int32)
        n_match, emit = greedy_accept(drafts, preds)
        # emission caps: stop at the first emitted eos (inclusive) and
        # never past the max_len budget; done rows emit nothing
        ii = jnp.arange(K, dtype=jnp.int32)[None, :]
        eos_pos = jnp.min(jnp.where(emit == eos_id, ii, K), axis=1)
        n_emit = jnp.minimum(n_match + 1, eos_pos + 1)
        n_emit = jnp.minimum(n_emit, jnp.int32(max_len) - cnt)
        n_emit = jnp.where(active, n_emit, 0)
        blk = jnp.where(ii < n_emit[:, None], emit, eos_id)

        def wr(row, blk_row, at):
            return jax.lax.dynamic_update_slice(row, blk_row, (at,))

        buf = jax.vmap(wr)(buf, blk, cnt)
        state = rollback_fn(state, n_match, active)
        cnt = (cnt + n_emit).astype(jnp.int32)
        done = done | (eos_pos < n_emit) | (cnt >= jnp.int32(max_len))
        corr = jnp.take_along_axis(preds, n_match[:, None],
                                   axis=1)[:, 0]
        pending = jnp.where(active, corr, pending)
        n_act = active.astype(jnp.int32).sum().astype(jnp.int32)
        rounds = (rounds + jnp.minimum(n_act, 1)).astype(jnp.int32)
        prop = (prop + n_act * jnp.int32(K - 1)).astype(jnp.int32)
        acc = (acc + jnp.where(active, jnp.minimum(n_match, n_emit),
                               0).sum()).astype(jnp.int32)
        return pending, state, done, cnt, buf, rounds, prop, acc

    (_, _, _, cnt, buf, rounds, prop, acc) = jax.lax.while_loop(
        cond, body, carry0)
    toks = buf[:, :max_len]
    if return_stats:
        return toks, cnt, {"rounds": rounds, "proposed": prop,
                           "accepted": acc}
    return toks, cnt


def beam_search(step_fn, init_state, batch_size, bos_id, eos_id,
                beam_size, max_len, length_penalty=0.0,
                return_state=False, init_logits=None):
    """Beam search. Returns (tokens [B, K, max_len] best-first,
    scores [B, K], lengths [B, K]) — plus each beam's final state
    (best-first, leading dim B*K) when return_state=True.

    States must have leading dim batch_size; they are tiled to
    batch*beam internally and re-gathered as beams reshuffle.

    init_logits ([B, V], optional, V >= K): prefix logits from the
    decode engine's prefill — the first expansion is top_k over THEM
    (equivalent to the classic first step, where only beam 0 is live)
    and the scan runs max_len - 1 steps on cache-backed decode tokens.
    """
    import jax

    jnp = _jnp()
    B, K = batch_size, beam_size

    def tile(t):
        return jnp.repeat(t, K, axis=0)  # [B*K, ...] beam-major rows

    state0 = jax.tree_util.tree_map(tile, init_state)
    if init_logits is None:
        # beam 0 starts live, others dead so the first expansion is
        # unique. f32 explicitly: under jax_enable_x64 a bare float
        # list is f64, which would promote the whole scoring scan to
        # emulated f64 on TPU
        logp0 = jnp.tile(jnp.asarray([0.0] + [NEG] * (K - 1),
                                     jnp.float32), (B, 1))
        tok0 = jnp.full((B, K), bos_id, jnp.int32)
        fin0 = jnp.zeros((B, K), bool)
        len0 = jnp.zeros((B, K), jnp.int32)
        scan_len = max_len
    else:
        lp_init = jax.nn.log_softmax(init_logits.astype(jnp.float32), -1)
        logp0, top_ix = jax.lax.top_k(lp_init, K)        # [B, K]
        tok0 = top_ix.astype(jnp.int32)
        fin0 = tok0 == eos_id
        len0 = jnp.ones((B, K), jnp.int32)
        scan_len = max_len - 1

    def step(carry, _):
        tok, logp, fin, lens, state = carry
        logits, state = step_fn(tok.reshape(B * K), state)
        V = logits.shape[-1]
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        lp = lp.reshape(B, K, V)
        # finished beams: only EOS continues, at no additional cost
        fin_mask = jnp.full((V,), NEG, jnp.float32).at[eos_id].set(0.0)
        lp = jnp.where(fin[:, :, None], fin_mask[None, None, :], lp)
        total = logp[:, :, None] + lp                  # [B, K, V]
        flat = total.reshape(B, K * V)
        top_lp, top_ix = jax.lax.top_k(flat, K)        # [B, K]
        src_beam = (top_ix // V).astype(jnp.int32)
        nxt_tok = (top_ix % V).astype(jnp.int32)

        def regather(t):
            tb = t.reshape((B, K) + t.shape[1:])
            out = jnp.take_along_axis(
                tb, src_beam.reshape((B, K) + (1,) * (t.ndim - 1)),
                axis=1)
            return out.reshape((B * K,) + t.shape[1:])

        state = jax.tree_util.tree_map(regather, state)
        fin = jnp.take_along_axis(fin, src_beam, axis=1)
        lens = jnp.take_along_axis(lens, src_beam, axis=1)
        lens = lens + (~fin).astype(jnp.int32)
        fin = fin | (nxt_tok == eos_id)
        return (nxt_tok, top_lp, fin, lens, state), (nxt_tok, src_beam)

    (tokT, logpT, finT, lensT, stateT), (toks, srcs) = jax.lax.scan(
        step, (tok0, logp0, fin0, len0, state0), None, length=scan_len)

    # backtrace beam ancestry so each final beam reads its OWN history
    def bwd(beam_ix, t):
        tok_t = jnp.take_along_axis(toks[t], beam_ix, axis=1)
        prev = jnp.take_along_axis(srcs[t], beam_ix, axis=1)
        return prev, tok_t

    init_ix = jnp.tile(jnp.arange(K, dtype=jnp.int32), (B, 1))
    first_ix, rev = jax.lax.scan(bwd, init_ix,
                                 jnp.arange(scan_len - 1, -1, -1))
    seqs = jnp.flip(jnp.moveaxis(rev, 0, 2), axis=2)  # [B, K, L]
    if init_logits is not None:
        # the ancestry bottoms out in the init expansion: prepend each
        # final beam's OWN first token
        first = jnp.take_along_axis(tok0, first_ix, axis=1)
        seqs = jnp.concatenate([first[:, :, None], seqs], axis=2)

    # length-penalized scores, best-first
    denom = jnp.maximum(lensT, 1).astype(jnp.float32) ** length_penalty
    scores = logpT / denom
    order = jnp.argsort(-scores, axis=1)
    seqs = jnp.take_along_axis(seqs, order[:, :, None], axis=1)
    scores = jnp.take_along_axis(scores, order, axis=1)
    lens = jnp.take_along_axis(lensT, order, axis=1)
    if return_state:
        def reorder(t):
            tb = t.reshape((B, K) + t.shape[1:])
            out = jnp.take_along_axis(
                tb, order.reshape((B, K) + (1,) * (t.ndim - 1)), axis=1)
            return out.reshape((B * K,) + t.shape[1:])

        stateF = jax.tree_util.tree_map(reorder, stateT)
        return seqs, scores, lens, stateF
    return seqs, scores, lens
