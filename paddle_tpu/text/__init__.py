"""paddle.text parity (reference python/paddle/text/datasets: Imdb, Imikolov,
Movielens, Conll05st, UCIHousing, WMT14/16). No network egress: constructors
take local files; FakeTextDataset gives synthetic sequences for tests."""
from __future__ import annotations

import numpy as np

from ..io import Dataset
from . import datasets  # noqa: F401
from . import decode  # noqa: F401
from . import generation  # noqa: F401
from . import speculative  # noqa: F401
from . import viterbi  # noqa: F401


class FakeTextDataset(Dataset):
    """Synthetic token-sequence dataset (cls-style: ids, label)."""

    def __init__(self, size=1000, seq_len=128, vocab_size=30000,
                 num_classes=2, seed=0):
        self.size = size
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self.num_classes = num_classes

    def __getitem__(self, idx):
        rng = np.random.RandomState(idx % 65536)
        ids = rng.randint(1, self.vocab_size,
                          size=(self.seq_len,)).astype(np.int64)
        label = np.asarray(idx % self.num_classes, dtype=np.int64)
        return ids, label

    def __len__(self):
        return self.size


from .datasets import (Conll05st, Imdb, Imikolov,  # noqa: F401,E402
                       Movielens, MovieReviews, UCIHousing, WMT14, WMT16)
from . import models  # noqa: F401,E402
from .models import (ErnieConfig, ErnieForPretraining,  # noqa: F401,E402
                     ErnieForSequenceClassification, ErnieModel, ernie_base,
                     ernie_tiny)
