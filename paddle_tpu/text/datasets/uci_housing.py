"""UCI Housing regression dataset (text/datasets/uci_housing.py parity).

Format: whitespace-separated floats, 14 per row; features normalized by
(x - mean) / (max - min); 80/20 train/test split.
"""
from __future__ import annotations

import numpy as np

from ...io import Dataset
from ...dataset.common import _check_exists_and_download

URL = "https://archive.ics.uci.edu/ml/machine-learning-databases/housing/housing.data"
MD5 = "d4accdce7a25600298819f8e28e8d593"


class UCIHousing(Dataset):
    def __init__(self, data_file=None, mode="train", download=True):
        assert mode.lower() in ("train", "test"), mode
        self.mode = mode.lower()
        self.data_file = _check_exists_and_download(
            data_file, URL, MD5, "uci_housing", download)
        self._load_data()

    def _load_data(self, feature_num=14, ratio=0.8):
        data = np.fromfile(self.data_file, sep=" ")
        data = data.reshape(data.shape[0] // feature_num, feature_num)
        maximums = data.max(axis=0)
        minimums = data.min(axis=0)
        avgs = data.sum(axis=0) / data.shape[0]
        for i in range(feature_num - 1):
            data[:, i] = (data[:, i] - avgs[i]) / \
                (maximums[i] - minimums[i])
        offset = int(data.shape[0] * ratio)
        self.data = data[:offset] if self.mode == "train" else data[offset:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return (np.array(row[:-1]).astype("float32"),
                np.array(row[-1:]).astype("float32"))

    def __len__(self):
        return len(self.data)
