"""PTB language-model dataset (text/datasets/imikolov.py parity).

Format: simple-examples tar with ./simple-examples/data/ptb.{train,valid}
.txt; word dict from train+valid with min frequency, '<s>'/'<e>' counted
per line, '<unk>' last; samples are NGRAMs (window_size) or full SEQs.
"""
from __future__ import annotations

import collections
import tarfile

import numpy as np

from ...io import Dataset
from ...dataset.common import _check_exists_and_download

URL = "https://dataset.bj.bcebos.com/imikolov%2Fsimple-examples.tgz"
MD5 = "30177ea32e27c525793142b6bf2c8e2d"


class Imikolov(Dataset):
    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=True):
        assert data_type.upper() in ("NGRAM", "SEQ"), data_type
        self.data_type = data_type.upper()
        assert mode.lower() in ("train", "test"), mode
        self.mode = mode.lower()
        self.window_size = window_size
        self.min_word_freq = min_word_freq
        self.data_file = _check_exists_and_download(
            data_file, URL, MD5, "imikolov", download)
        self.word_idx = self._build_word_dict(min_word_freq)
        self._load_anno()

    @staticmethod
    def _word_count(f, word_freq=None):
        if word_freq is None:
            word_freq = collections.defaultdict(int)
        for line in f:
            for w in line.strip().split():
                word_freq[w] += 1
            word_freq["<s>"] += 1
            word_freq["<e>"] += 1
        return word_freq

    def _build_word_dict(self, cutoff):
        train_fn = "./simple-examples/data/ptb.train.txt"
        valid_fn = "./simple-examples/data/ptb.valid.txt"
        with tarfile.open(self.data_file) as tf:
            freq = self._word_count(
                _text(tf.extractfile(valid_fn)),
                self._word_count(_text(tf.extractfile(train_fn))))
            freq.pop("<unk>", None)
            freq = [x for x in freq.items() if x[1] > cutoff]
            dictionary = sorted(freq, key=lambda x: (-x[1], x[0]))
            words, _ = list(zip(*dictionary)) if dictionary else ((), ())
            word_idx = dict(zip(words, range(len(words))))
            word_idx["<unk>"] = len(words)
        return word_idx

    def _load_anno(self):
        fn = "./simple-examples/data/ptb.{}.txt".format(
            "train" if self.mode == "train" else "valid")
        self.data = []
        with tarfile.open(self.data_file) as tf:
            unk = self.word_idx["<unk>"]
            for line in _text(tf.extractfile(fn)):
                if self.data_type == "NGRAM":
                    assert self.window_size > -1, \
                        "NGRAM mode needs window_size > 0"
                    words = ["<s>"] + line.strip().split() + ["<e>"]
                    if len(words) < self.window_size:
                        continue
                    ids = [self.word_idx.get(w, unk) for w in words]
                    for i in range(self.window_size, len(ids) + 1):
                        self.data.append(
                            tuple(ids[i - self.window_size:i]))
                else:
                    words = ["<s>"] + line.strip().split() + ["<e>"]
                    ids = [self.word_idx.get(w, unk) for w in words]
                    self.data.append((ids[:-1], ids[1:]))

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


def _text(f):
    for line in f:
        yield line.decode("utf-8") if isinstance(line, bytes) else line
