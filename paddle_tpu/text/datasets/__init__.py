"""NLP datasets (paddle.text.datasets parity).

Reference parity: python/paddle/text/datasets/ (Imdb, Imikolov,
Movielens, Conll05st, UCIHousing, WMT14, WMT16, MovieReviews). This
environment has no network egress, so constructors accept local archive
files in the SAME formats the reference downloads (aclImdb tar, PTB
simple-examples tar, ml-1m zip, conll05st tar, wmt tars) and raise a
clear error when a download would be required.
"""
from .conll05 import Conll05st  # noqa: F401
from .imdb import Imdb  # noqa: F401
from .imikolov import Imikolov  # noqa: F401
from .movie_reviews import MovieReviews  # noqa: F401
from .movielens import Movielens  # noqa: F401
from .uci_housing import UCIHousing  # noqa: F401
from .wmt import WMT14, WMT16  # noqa: F401

__all__ = ["Imdb", "Imikolov", "Movielens", "Conll05st", "UCIHousing",
           "WMT14", "WMT16", "MovieReviews"]
