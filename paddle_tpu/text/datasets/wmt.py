"""WMT14/WMT16 machine-translation datasets (wmt14.py / wmt16.py parity).

WMT14 format: tar with {train,test,gen}/{train,test,gen} tab-separated
src\ttrg lines plus *src.dict / *trg.dict vocabulary members (first
dict_size lines).
WMT16 format: tar with wmt16/{train,val,test} tab-separated en\tde
lines; dictionaries BUILT from the train corpus by frequency with
<s>/<e>/<unk> reserved.
"""
from __future__ import annotations

import collections
import os
import tarfile

import numpy as np

from ...io import Dataset
from ...dataset import common
from ...dataset.common import _check_exists_and_download

WMT14_URL = ("http://paddlemodels.bj.bcebos.com/wmt/wmt14.tgz")
WMT14_MD5 = "0791583d57d5beb693b9414c5b36798c"
WMT16_URL = ("http://paddlemodels.bj.bcebos.com/wmt/wmt16.tar.gz")
WMT16_MD5 = "0c38be43600334966403524a40dcd81e"

START = "<s>"
END = "<e>"
UNK = "<unk>"
UNK_IDX = 2


class WMT14(Dataset):
    def __init__(self, data_file=None, mode="train", dict_size=-1,
                 download=True):
        assert mode.lower() in ("train", "test", "gen"), mode
        self.mode = mode.lower()
        self.data_file = _check_exists_and_download(
            data_file, WMT14_URL, WMT14_MD5, "wmt14", download)
        self.dict_size = dict_size if dict_size > 0 else 2 ** 31 - 1
        self._load_data()

    def _load_data(self):
        def to_dict(fd, size):
            out = {}
            for i, line in enumerate(fd):
                if i >= size:
                    break
                out[line.strip().decode("utf-8")] = i
            return out

        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self.data_file) as f:
            src_names = [m.name for m in f if m.name.endswith("src.dict")]
            trg_names = [m.name for m in f if m.name.endswith("trg.dict")]
            assert len(src_names) == 1 and len(trg_names) == 1
            self.src_dict = to_dict(f.extractfile(src_names[0]),
                                    self.dict_size)
            self.trg_dict = to_dict(f.extractfile(trg_names[0]),
                                    self.dict_size)
            fname = f"{self.mode}/{self.mode}"
            for name in [m.name for m in f if m.name.endswith(fname)]:
                for line in f.extractfile(name):
                    parts = line.decode("utf-8").strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src_words = parts[0].split()
                    src_ids = [self.src_dict.get(w, UNK_IDX)
                               for w in [START] + src_words + [END]]
                    trg_words = parts[1].split()
                    trg_ids = [self.trg_dict.get(w, UNK_IDX)
                               for w in trg_words]
                    if len(src_ids) > 80 or len(trg_ids) > 80:
                        continue
                    self.src_ids.append(src_ids)
                    self.trg_ids.append([self.trg_dict[START]] + trg_ids)
                    self.trg_ids_next.append(trg_ids +
                                             [self.trg_dict[END]])

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)

    def get_dict(self, reverse=False):
        if reverse:
            return ({v: k for k, v in self.src_dict.items()},
                    {v: k for k, v in self.trg_dict.items()})
        return self.src_dict, self.trg_dict


class WMT16(Dataset):
    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", download=True):
        assert mode.lower() in ("train", "test", "val"), mode
        assert lang in ("en", "de"), lang
        self.mode = mode.lower()
        self.lang = lang
        self.data_file = _check_exists_and_download(
            data_file, WMT16_URL, WMT16_MD5, "wmt16", download)
        self.src_dict_size = self._bounded(src_dict_size)
        self.trg_dict_size = self._bounded(trg_dict_size)
        self.src_dict = self._load_dict(lang, self.src_dict_size)
        self.trg_dict = self._load_dict(
            "de" if lang == "en" else "en", self.trg_dict_size)
        self._load_data()

    @staticmethod
    def _bounded(n):
        return n if n > 0 else 2 ** 31 - 1

    def _dict_path(self, lang, size):
        import hashlib

        base = os.path.join(
            os.path.expanduser(os.environ.get(
                "PADDLE_TPU_DATA_HOME", common.DATA_HOME)), "wmt16")
        os.makedirs(base, exist_ok=True)
        # key the cache on the CORPUS identity too: two different tars
        # must never share a vocabulary file
        st = os.stat(self.data_file)
        tag = hashlib.md5(
            f"{os.path.abspath(self.data_file)}:{st.st_size}:"
            f"{int(st.st_mtime)}".encode()).hexdigest()[:10]
        return os.path.join(base, f"{lang}_dict_{size}_{tag}.txt")

    def _load_dict(self, lang, size):
        path = self._dict_path(lang, size)
        if not os.path.exists(path):
            self._build_dict(path, size, lang)
        d = {}
        with open(path, "r", encoding="utf-8") as f:
            for i, line in enumerate(f):
                d[line.strip()] = i
        return d

    def _build_dict(self, path, size, lang):
        freq = collections.defaultdict(int)
        col = 0 if lang == "en" else 1
        with tarfile.open(self.data_file) as f:
            for line in f.extractfile("wmt16/train"):
                parts = line.decode("utf-8").strip().split("\t")
                if len(parts) != 2:
                    continue
                for w in parts[col].split():
                    freq[w] += 1
        with open(path, "w", encoding="utf-8") as out:
            out.write(f"{START}\n{END}\n{UNK}\n")
            for i, (word, _) in enumerate(sorted(
                    freq.items(), key=lambda x: (-x[1], x[0]))):
                if i + 3 >= size:
                    break
                out.write(word + "\n")

    def _load_data(self):
        start_id = self.src_dict[START]
        end_id = self.src_dict[END]
        unk_id = self.src_dict[UNK]
        src_col = 0 if self.lang == "en" else 1
        trg_col = 1 - src_col
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self.data_file) as f:
            for line in f.extractfile(f"wmt16/{self.mode}"):
                parts = line.decode("utf-8").strip().split("\t")
                if len(parts) != 2:
                    continue
                src_ids = [start_id] + [
                    self.src_dict.get(w, unk_id)
                    for w in parts[src_col].split()] + [end_id]
                trg_words = parts[trg_col].split()
                trg_ids = [self.trg_dict.get(w, unk_id)
                           for w in trg_words]
                self.src_ids.append(src_ids)
                self.trg_ids.append([start_id] + trg_ids)
                self.trg_ids_next.append(trg_ids + [end_id])

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)

    def get_dict(self, lang, reverse=False):
        d = self.src_dict if lang == self.lang else self.trg_dict
        return {v: k for k, v in d.items()} if reverse else d
