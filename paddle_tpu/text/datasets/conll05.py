"""CoNLL-2005 SRL dataset (text/datasets/conll05.py parity).

Format: conll05st-release tar with test.wsj words/props gzip members;
label sequences reconstructed from the bracketed proposition format to
B-/I-/O tags; per-sample features are the 9-slot SRL layout (word,
predicate context windows, region mark, predicate, labels).
"""
from __future__ import annotations

import gzip
import tarfile

import numpy as np

from ...io import Dataset
from ...dataset.common import _check_exists_and_download

DATA_URL = "https://dataset.bj.bcebos.com/conll05st%2Fconll05st-tests.tar.gz"
DATA_MD5 = "387719152ae52d60422c016e92a742fc"
WORDDICT_URL = "https://dataset.bj.bcebos.com/conll05st%2FwordDict.txt"
WORDDICT_MD5 = "ea7fb7d4c75cc6254716f0177a506baa"
VERBDICT_URL = "https://dataset.bj.bcebos.com/conll05st%2FverbDict.txt"
VERBDICT_MD5 = "0d2977293bbb6cbefab5b0f97db1e77c"
TRGDICT_URL = "https://dataset.bj.bcebos.com/conll05st%2FtargetDict.txt"
TRGDICT_MD5 = "d8c7f03ceb5fc2e5a0fa7503a4353751"
EMB_URL = "https://dataset.bj.bcebos.com/conll05st%2Femb"
EMB_MD5 = "bf436eb0faa1f6f9103017f8be57cdb7"

UNK_IDX = 0


class Conll05st(Dataset):
    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, emb_file=None,
                 download=True):
        self.data_file = _check_exists_and_download(
            data_file, DATA_URL, DATA_MD5, "conll05st", download)
        self.word_dict_file = _check_exists_and_download(
            word_dict_file, WORDDICT_URL, WORDDICT_MD5, "conll05st",
            download)
        self.verb_dict_file = _check_exists_and_download(
            verb_dict_file, VERBDICT_URL, VERBDICT_MD5, "conll05st",
            download)
        self.target_dict_file = _check_exists_and_download(
            target_dict_file, TRGDICT_URL, TRGDICT_MD5, "conll05st",
            download)
        self.word_dict = self._load_dict(self.word_dict_file)
        self.predicate_dict = self._load_dict(self.verb_dict_file)
        self.label_dict = self._load_label_dict(self.target_dict_file)
        self._load_anno()

    @staticmethod
    def _load_dict(path):
        d = {}
        with open(path, "r") as f:
            for i, line in enumerate(f):
                d[line.strip()] = i
        return d

    @staticmethod
    def _load_label_dict(path):
        d = {}
        tag_dict = set()
        with open(path, "r") as f:
            for line in f:
                line = line.strip()
                if line.startswith("B-"):
                    tag_dict.add(line[2:])
                elif line.startswith("I-"):
                    tag_dict.add(line[2:])
        index = 0
        for tag in sorted(tag_dict):
            d["B-" + tag] = index
            index += 1
            d["I-" + tag] = index
            index += 1
        d["O"] = index
        return d

    def _parse_labels(self, labels):
        """Bracketed proposition columns -> per-predicate B/I/O tag seqs."""
        outs = []
        verb_list = [x for x in labels[0] if x != "-"]
        for i, lbl in enumerate(labels[1:]):
            cur_tag, in_bracket = "O", False
            seq = []
            for tok in lbl:
                if tok == "*" and not in_bracket:
                    seq.append("O")
                elif tok == "*" and in_bracket:
                    seq.append("I-" + cur_tag)
                elif tok == "*)":
                    seq.append("I-" + cur_tag)
                    in_bracket = False
                elif "(" in tok and ")" in tok:
                    cur_tag = tok[1:tok.find("*")]
                    seq.append("B-" + cur_tag)
                    in_bracket = False
                elif "(" in tok and ")" not in tok:
                    cur_tag = tok[1:tok.find("*")]
                    seq.append("B-" + cur_tag)
                    in_bracket = True
            outs.append((verb_list[i] if i < len(verb_list) else "-", seq))
        return outs

    def _load_anno(self):
        self.sentences = []
        self.predicates = []
        self.labels = []
        with tarfile.open(self.data_file) as tf:
            wf = tf.extractfile(
                "conll05st-release/test.wsj/words/test.wsj.words.gz")
            pf = tf.extractfile(
                "conll05st-release/test.wsj/props/test.wsj.props.gz")
            with gzip.GzipFile(fileobj=wf) as words_file, \
                    gzip.GzipFile(fileobj=pf) as props_file:
                sentences = []
                one_seg = []
                for word, label in zip(words_file, props_file):
                    word = word.strip().decode("utf-8")
                    label = label.strip().decode("utf-8").split()
                    if len(label) == 0:  # sentence end
                        labels = []
                        for i in range(len(one_seg[0]) if one_seg else 0):
                            labels.append([x[i] for x in one_seg])
                        if len(labels) >= 1:
                            for verb, seq in self._parse_labels(labels):
                                if len(seq) != len(sentences):
                                    continue
                                self.sentences.append(list(sentences))
                                self.predicates.append(verb)
                                self.labels.append(seq)
                        sentences = []
                        one_seg = []
                    else:
                        sentences.append(word)
                        one_seg.append(label)

    def __getitem__(self, idx):
        """The 9-slot SRL feature layout (dataset/conll05.py reader_creator
        parity): word ids, 5 predicate-context windows, region mark,
        predicate id, label ids."""
        sen = self.sentences[idx]
        pred = self.predicates[idx]
        seq = self.labels[idx]
        word_ids = [self.word_dict.get(w, UNK_IDX) for w in sen]
        # predicate context window of 5 around the first B-V
        try:
            verb_index = seq.index("B-V")
        except ValueError:
            verb_index = 0
        ctx = []
        for off in (-2, -1, 0, 1, 2):
            j = min(max(verb_index + off, 0), len(sen) - 1)
            ctx.append(self.word_dict.get(sen[j], UNK_IDX))
        mark = [1 if v == "B-V" or v == "I-V" else 0 for v in seq]
        pred_id = self.predicate_dict.get(pred, UNK_IDX)
        label_ids = [self.label_dict.get(t, self.label_dict["O"])
                     for t in seq]
        return (np.array(word_ids), np.array([ctx[0]] * len(sen)),
                np.array([ctx[1]] * len(sen)),
                np.array([ctx[2]] * len(sen)),
                np.array([ctx[3]] * len(sen)),
                np.array([ctx[4]] * len(sen)), np.array(mark),
                np.array([pred_id]), np.array(label_ids))

    def __len__(self):
        return len(self.sentences)

    def get_dict(self):
        return self.word_dict, self.predicate_dict, self.label_dict

    def get_embedding(self, emb_file=None):
        emb_file = _check_exists_and_download(
            emb_file, EMB_URL, EMB_MD5, "conll05st", emb_file is None)
        return np.loadtxt(emb_file)
