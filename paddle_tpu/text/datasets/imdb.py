"""IMDB sentiment dataset (text/datasets/imdb.py parity).

Format: aclImdb_v1.tar.gz with aclImdb/{train,test}/{pos,neg}/*.txt;
word dictionary built from the TRAIN split with a frequency cutoff,
'<unk>' appended last; labels: pos=0, neg=1.
"""
from __future__ import annotations

import collections
import re
import string
import tarfile

import numpy as np

from ...io import Dataset
from ...dataset.common import _check_exists_and_download

URL = "https://dataset.bj.bcebos.com/imdb%2FaclImdb_v1.tar.gz"
MD5 = "7c2ac02c03563afcf9b574c7e56c153a"


class Imdb(Dataset):
    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        assert mode.lower() in ("train", "test"), mode
        self.mode = mode.lower()
        self.data_file = _check_exists_and_download(
            data_file, URL, MD5, "imdb", download)
        self.word_idx = self._build_word_dict(cutoff)
        self._load_anno()

    def _tokenize(self, pattern):
        data = []
        trans = str.maketrans("", "", string.punctuation)
        with tarfile.open(self.data_file) as tarf:
            tf = tarf.next()
            while tf is not None:
                if bool(pattern.match(tf.name)):
                    data.append(
                        tarf.extractfile(tf).read().decode(
                            "latin-1").lower().translate(trans).split())
                tf = tarf.next()
        return data

    def _build_word_dict(self, cutoff):
        pattern = re.compile(r"aclImdb/train/.*\.txt$")
        word_freq = collections.defaultdict(int)
        for doc in self._tokenize(pattern):
            for word in doc:
                word_freq[word] += 1
        word_freq = [(k, v) for k, v in word_freq.items() if v > cutoff]
        dictionary = sorted(word_freq, key=lambda x: (-x[1], x[0]))
        words, _ = list(zip(*dictionary)) if dictionary else ((), ())
        word_idx = dict(zip(words, range(len(words))))
        word_idx["<unk>"] = len(words)
        return word_idx

    def _load_anno(self):
        pos = re.compile(rf"aclImdb/{self.mode}/pos/.*\.txt$")
        neg = re.compile(rf"aclImdb/{self.mode}/neg/.*\.txt$")
        unk = self.word_idx["<unk>"]
        self.docs, self.labels = [], []
        for doc in self._tokenize(pos):
            self.docs.append([self.word_idx.get(w, unk) for w in doc])
            self.labels.append(0)
        for doc in self._tokenize(neg):
            self.docs.append([self.word_idx.get(w, unk) for w in doc])
            self.labels.append(1)

    def __getitem__(self, idx):
        return (np.array(self.docs[idx]), np.array([self.labels[idx]]))

    def __len__(self):
        return len(self.docs)
