"""MovieLens-1M rating dataset (text/datasets/movielens.py parity).

Format: ml-1m.zip with ml-1m/{movies,users,ratings}.dat ('::'-separated,
latin-1). Samples: user fields + movie fields + [rating*2-5].
"""
from __future__ import annotations

import re
import zipfile

import numpy as np

from ...io import Dataset
from ...dataset.common import _check_exists_and_download

URL = "https://dataset.bj.bcebos.com/movielens%2Fml-1m.zip"
MD5 = "c4d9eecfca2ab87c1945afe126590906"

age_table = [1, 18, 25, 35, 45, 50, 56]


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, categories_dict, movie_title_dict):
        return [
            [self.index],
            [categories_dict[c] for c in self.categories],
            [movie_title_dict[w.lower()] for w in self.title.split()],
        ]

    def __repr__(self):
        return (f"<MovieInfo id({self.index}), title({self.title}), "
                f"categories({self.categories})>")


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [[self.index], [0 if self.is_male else 1], [self.age],
                [self.job_id]]

    def __repr__(self):
        return (f"<UserInfo id({self.index}), gender({self.is_male}), "
                f"age({self.age}), job({self.job_id})>")


class Movielens(Dataset):
    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        assert mode.lower() in ("train", "test"), mode
        self.mode = mode.lower()
        self.data_file = _check_exists_and_download(
            data_file, URL, MD5, "sentiment", download)
        self.test_ratio = test_ratio
        np.random.seed(rand_seed)
        self._load_meta_info()
        self._load_data()

    def _load_meta_info(self):
        pattern = re.compile(r"^(.*)\((\d+)\)$")
        self.movie_info = {}
        self.movie_title_dict = {}
        self.categories_dict = {}
        self.user_info = {}
        with zipfile.ZipFile(self.data_file) as package:
            title_word_set = set()
            categories_set = set()
            with package.open("ml-1m/movies.dat") as movie_file:
                for line in movie_file:
                    line = line.decode("latin-1")
                    movie_id, title, categories = line.strip().split("::")
                    categories = categories.split("|")
                    categories_set.update(categories)
                    m = pattern.match(title)
                    title = m.group(1) if m else title
                    self.movie_info[int(movie_id)] = MovieInfo(
                        index=movie_id, categories=categories, title=title)
                    for w in title.split():
                        title_word_set.add(w.lower())
            for i, w in enumerate(sorted(title_word_set)):
                self.movie_title_dict[w] = i
            for i, c in enumerate(sorted(categories_set)):
                self.categories_dict[c] = i
            with package.open("ml-1m/users.dat") as user_file:
                for line in user_file:
                    line = line.decode("latin-1")
                    uid, gender, age, job, _ = line.strip().split("::")
                    self.user_info[int(uid)] = UserInfo(
                        index=uid, gender=gender, age=age, job_id=job)

    def _load_data(self):
        self.data = []
        is_test = self.mode == "test"
        with zipfile.ZipFile(self.data_file) as package:
            with package.open("ml-1m/ratings.dat") as rating:
                for line in rating:
                    line = line.decode("latin-1")
                    if (np.random.random() < self.test_ratio) == is_test:
                        uid, mov_id, r, _ = line.strip().split("::")
                        mov = self.movie_info[int(mov_id)]
                        usr = self.user_info[int(uid)]
                        self.data.append(
                            usr.value() +
                            mov.value(self.categories_dict,
                                      self.movie_title_dict) +
                            [[float(r) * 2 - 5.0]])

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)
