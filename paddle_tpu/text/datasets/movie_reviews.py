"""NLTK movie_reviews sentiment dataset (movie_reviews.py parity).

The reference pulls the corpus through nltk; here the corpus zip (or an
extracted directory with pos/ and neg/ subdirs of .txt files) is passed
locally — zero-egress environment.
"""
from __future__ import annotations

import collections
import os
import zipfile

import numpy as np

from ...io import Dataset


class MovieReviews(Dataset):
    def __init__(self, data_file=None, mode="train"):
        assert mode.lower() in ("train", "test"), mode
        self.mode = mode.lower()
        if data_file is None:
            raise RuntimeError(
                "MovieReviews needs data_file (the nltk movie_reviews "
                "corpus zip or an extracted pos/neg directory); this "
                "environment cannot download it")
        self.data_file = data_file
        docs = self._read_docs()
        word_freq = collections.defaultdict(int)
        for words, _ in docs:
            for w in words:
                word_freq[w] += 1
        items = sorted(word_freq.items(), key=lambda x: (-x[1], x[0]))
        self.word_idx = {w: i for i, (w, _) in enumerate(items)}
        # reference: 10% test split interleaved
        data = [([self.word_idx[w] for w in words], label)
                for words, label in docs]
        self.data = [d for i, d in enumerate(data)
                     if (i % 10 == 0) == (self.mode == "test")]

    def _read_docs(self):
        docs = []
        if os.path.isdir(self.data_file):
            for label, sub in ((0, "pos"), (1, "neg")):
                base = os.path.join(self.data_file, sub)
                for fn in sorted(os.listdir(base)):
                    with open(os.path.join(base, fn), "r",
                              errors="ignore") as f:
                        docs.append((f.read().lower().split(), label))
            return docs
        with zipfile.ZipFile(self.data_file) as z:
            for name in sorted(z.namelist()):
                low = name.lower()
                if not low.endswith(".txt"):
                    continue
                label = 0 if "/pos/" in low else (
                    1 if "/neg/" in low else None)
                if label is None:
                    continue
                docs.append(
                    (z.read(name).decode("latin-1").lower().split(),
                     label))
        return docs

    def __getitem__(self, idx):
        ids, label = self.data[idx]
        return np.array(ids), np.array([label])

    def __len__(self):
        return len(self.data)
