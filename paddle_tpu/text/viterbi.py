"""Viterbi decode (reference: operators/crf_decoding_op.h) — lax.scan based."""
from __future__ import annotations

from ..core.tensor import Tensor


def viterbi_decode(potentials, transition, lengths=None,
                   include_bos_eos_tag=True):
    import jax
    import jax.numpy as jnp

    pot = potentials._data if isinstance(potentials, Tensor) else potentials
    trans = transition._data if isinstance(transition, Tensor) else transition

    def decode_one(emissions):
        def step(carry, emit):
            score = carry
            broadcast = score[:, None] + trans
            best = broadcast.max(axis=0)
            idx = broadcast.argmax(axis=0)
            return best + emit, idx

        init = emissions[0]
        final, idxs = jax.lax.scan(step, init, emissions[1:])
        last = final.argmax()

        def back(carry, idx_row):
            tag = idx_row[carry]
            return tag, tag

        _, path_rev = jax.lax.scan(back, last, idxs[::-1])
        return jnp.concatenate([path_rev[::-1], last[None]]), final.max()

    paths, scores = jax.vmap(decode_one)(pot)
    return Tensor._wrap(scores), Tensor._wrap(paths)
