"""Speculative decoding: pluggable draft sources for the fused scan.

Draft-verify generation (the classic speculative-sampling /
Medusa-style self-drafting recipe, greedy-acceptance variant): instead
of one bandwidth-bound decode dispatch per token, a cheap DRAFT source
proposes k - 1 continuation tokens, ONE k-token VERIFY step runs them
through the target model against the live KV cache (the pending token
plus the drafts, written at each row's own offset —
`ops.attention.verify_attention`), and the longest draft prefix whose
tokens match the verify argmaxes is accepted together with one free
correction token (`text.decode.greedy_accept`). Greedy acceptance
makes the output BIT-IDENTICAL to plain greedy decoding for ANY draft
source, so the repo's fused-vs-eager identity tests extend directly.

Draft sources here:

  * `ngram_propose` — zero-cost self-speculation: suffix n-gram
    matching over the row's OWN prompt + generated history (a token
    mirror of the KV cache, same absolute-slot layout and the same
    index arithmetic for rollback). Pure jnp, fixed shapes, traced
    into the same program as the verify step. Strong on repetitive
    suffixes (code, templated text, copy-through), harmless elsewhere
    (unaccepted drafts cost only the verify lane they rode in).
  * `DraftModel` — a small draft model with its OWN StaticKVCache,
    prefilled alongside the target and stepped k times per round so
    both caches stay in lockstep; acceptance rolls both back with the
    same per-row write-index arithmetic.

Cache rollback needs NO copy: the verify step writes all k fed tokens,
acceptance just sets the per-row write index back to (pre-verify +
1 + n_match); rejected positions hold garbage that the next round's
fixed-k write covers before any query can see it (key positions >= the
write index are masked everywhere).
"""
from __future__ import annotations


def _jnp():
    import jax.numpy as jnp

    return jnp


def ngram_propose(hist, pending, lengths, Pb, n_new, gen_len, ngram=2):
    """Suffix n-gram self-speculation: propose `n_new` tokens for each
    row by finding the most recent position in the row's own history
    whose trailing `ngram`-gram matches the current context, and
    reading the tokens that followed it.

    hist [B, L]: token mirror of the KV cache — prompt at [0, Pb) with
    its pad hole, generated tokens from Pb in absolute-slot layout.
    pending [B]: the last emitted token (not yet written — the cache's
    pending-token convention). lengths [B]: true prompt lengths (the
    hole [lengths, Pb) is skipped by matching in LOGICAL coordinates).
    gen_len [B]: count of valid generated tokens in hist (= emitted -
    1). Rows with no match repeat the pending token — any proposal is
    output-safe under greedy acceptance, a wrong one just wastes its
    verify lane. Pure jnp, fixed shapes, fully traced."""
    jnp = _jnp()
    B, L = hist.shape
    lens = jnp.asarray(lengths, jnp.int32).reshape(-1, 1)     # [B, 1]
    # Pb may be a python int (DecodeEngine: one bucket per program) or
    # a per-row [B] array (the serving pool: slots joined at different
    # prompt buckets co-reside)
    Pbv = jnp.asarray(Pb, jnp.int32).reshape(-1, 1)
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    # logical view: real prompt tokens then generated tokens, the pad
    # hole [len, Pb) spliced out
    phys = jnp.where(pos < lens, pos, pos + (Pbv - lens))
    hl = jnp.take_along_axis(hist, jnp.clip(phys, 0, L - 1), axis=1)
    ell = lens[:, 0] + jnp.asarray(gen_len, jnp.int32)        # [B]
    # match at q: hl[q] == pending and hl[q - j] == (j-th token back
    # from the current end) for j = 1..ngram-1
    ok = hl == pending[:, None]
    for j in range(1, int(ngram)):
        cj = jnp.take_along_axis(
            hl, jnp.clip(ell - j, 0, L - 1)[:, None], axis=1)
        hl_back = jnp.take_along_axis(hl, jnp.clip(pos - j, 0, L - 1),
                                      axis=1)
        ok = ok & (hl_back == cj) & (pos >= j)
    ok = ok & (pos < ell[:, None])
    q = jnp.max(jnp.where(ok, pos, -1), axis=1)               # [B]
    # the match distance IS the detected period: position ell + 1 + j
    # (the j-th proposal) reads q + 1 + (j mod p), wrapping so periodic
    # continuations of ANY period are proposed in full; the wrap
    # position that lands on ell itself is the pending token
    p = jnp.maximum(ell - q, 1)[:, None]                      # [B, 1]
    jj = jnp.arange(n_new, dtype=jnp.int32)[None, :]
    off = q[:, None] + 1 + jj % p
    gath = jnp.take_along_axis(hl, jnp.clip(off, 0, L - 1), axis=1)
    oob = (q[:, None] < 0) | (off >= ell[:, None])
    return jnp.where(oob, pending[:, None], gath).astype(jnp.int32)


def write_hist(hist, fed, index):
    """Mirror a verify round's fed block [B, k] into the history buffer
    at each row's cache write offset (the SAME per-row vmapped
    dynamic_update_slice the cache write uses — one source of truth for
    the slot layout, and rollback is implicit: validity is derived from
    the rolled-back write index)."""
    import jax
    import jax.numpy as jnp

    def wr(row, blk, at):
        return jax.lax.dynamic_update_slice(row, blk.astype(row.dtype),
                                            (at,))

    return jax.vmap(wr)(hist, fed, jnp.asarray(index, jnp.int32))


def rollback_index(index, k, n_match, active):
    """The acceptance-time write-index arithmetic shared by every
    cache: verify advanced `index` by k; keep the pending token plus
    the accepted drafts on active rows, pin inactive rows."""
    jnp = _jnp()
    keep = jnp.where(active, 1 + jnp.asarray(n_match, jnp.int32), 0)
    return (jnp.asarray(index, jnp.int32) - jnp.int32(k) +
            keep).astype(jnp.int32)


class DraftModel:
    """A small draft model with its OWN StaticKVCache. Wraps a
    (decoder, embed, project) triple that shares the target's
    vocabulary and cross-attention memory; the spec engine prefills it
    alongside the target, steps it k times per round (the pending
    token, then each draft — the last step is write-only so the draft
    cache covers the same k positions the verify writes), and rolls
    its write indices back with the target's own acceptance
    arithmetic."""

    def __init__(self, decoder, embed, project):
        from ..parallel.functional import functionalize
        from .generation import _StepNet

        self.decoder = decoder
        self._net = _StepNet(decoder, embed, project)
        self._fm = functionalize(self._net)

    def params(self):
        return self._fm.params()

    def buffers(self):
        return self._fm.buffers()
