"""Flagship transformer models (ERNIE/BERT-family encoders).

Reference parity: the reference framework itself ships no ERNIE model code
(it lives in PaddleNLP), but ERNIE-base is the reference's headline NLP
benchmark workload (BASELINE.md config 3) and the fused attention kernels
(operators/fused/multihead_matmul_op.cc, math/bert_encoder_functor.cu) exist
to serve it. Here the model is a first-class citizen built on paddle_tpu.nn,
bf16-friendly, with parameter names matching parallel.sharding.COMMON_TP_RULES
so tp/sp sharding is declarative.
"""
from __future__ import annotations

from .. import nn


class ErnieConfig:
    def __init__(self, vocab_size=18000, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=3072, max_position=513,
                 type_vocab_size=2, hidden_dropout=0.1, attn_dropout=0.1,
                 num_classes=2, moe_experts=0, moe_capacity_factor=1.25):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size
        self.max_position = max_position
        self.type_vocab_size = type_vocab_size
        self.hidden_dropout = hidden_dropout
        self.attn_dropout = attn_dropout
        self.num_classes = num_classes
        # moe_experts > 0 replaces every encoder FFN with a top-1
        # routed MoELayer (nn/layer/moe.py) whose expert axis shards
        # over the mesh's `ep` axis
        self.moe_experts = moe_experts
        self.moe_capacity_factor = moe_capacity_factor

    @classmethod
    def base(cls, **kw):
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        d = dict(vocab_size=1024, hidden_size=64, num_layers=2, num_heads=4,
                 intermediate_size=128, max_position=128)
        d.update(kw)
        return cls(**d)


class ErnieEmbeddings(nn.Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = nn.Embedding(cfg.max_position,
                                                cfg.hidden_size)
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size,
                                                  cfg.hidden_size)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size)
        self.dropout = nn.Dropout(cfg.hidden_dropout)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        from ..tensor import ops as T

        seq_len = input_ids.shape[1]
        if position_ids is None:
            position_ids = T.arange(0, seq_len, dtype="int64")
            position_ids = T.unsqueeze(position_ids, 0)
        if token_type_ids is None:
            token_type_ids = T.zeros_like(input_ids)
        emb = (self.word_embeddings(input_ids)
               + self.position_embeddings(position_ids)
               + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(emb))


class ErnieModel(nn.Layer):
    """BERT/ERNIE encoder. attention_mask: (B, S) 1/0 valid-token mask.

    Packed varlen feeds (LoD-native fine-tuning): pass the outputs of
    core/lod.pack_padded instead of a padded batch — `input_ids` =
    packed.data, `position_ids` = packed.positions, `attn_segment_ids`
    = packed.segment_ids, and `cls_flat_index` = packed.cls_flat_index()
    to pool each SEQUENCE's first token (several sequences share a
    row, so `seq_out[:, 0]` would miss all but the first). No dense
    attention_mask is needed: pads form their own segment, and the
    attention dispatcher routes segment ids to the segment-masked
    packed flash kernel on TPU."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = ErnieEmbeddings(cfg)
        enc_layer = nn.TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_heads, cfg.intermediate_size,
            dropout=cfg.hidden_dropout, activation="gelu",
            attn_dropout=cfg.attn_dropout,
            moe_experts=getattr(cfg, "moe_experts", 0),
            moe_capacity_factor=getattr(cfg, "moe_capacity_factor", 1.25))
        self.encoder = nn.TransformerEncoder(enc_layer, cfg.num_layers)
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.pooler_act = nn.Tanh()

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, attn_segment_ids=None,
                cls_flat_index=None):
        from ..tensor import ops as T

        if attention_mask is not None:
            # (B, S) -> additive (B, 1, 1, S) broadcast over heads/queries
            m = T.unsqueeze(attention_mask, [1, 2])
            mask = (1.0 - m.astype("float32")) * -1e4
        else:
            mask = None
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        seq_out = self.encoder(x, mask, segment_ids=attn_segment_ids)
        if cls_flat_index is not None:
            b, s, hdim = seq_out.shape
            flat = seq_out.reshape([b * s, hdim])
            cls_tok = T.index_select(flat, cls_flat_index, axis=0)
        else:
            cls_tok = seq_out[:, 0]
        pooled = self.pooler_act(self.pooler(cls_tok))
        return seq_out, pooled


class ErnieForSequenceClassification(nn.Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.dropout = nn.Dropout(cfg.hidden_dropout)
        self.classifier = nn.Linear(cfg.hidden_size, cfg.num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                position_ids=None, attn_segment_ids=None,
                cls_flat_index=None):
        _, pooled = self.ernie(input_ids, token_type_ids,
                               position_ids=position_ids,
                               attention_mask=attention_mask,
                               attn_segment_ids=attn_segment_ids,
                               cls_flat_index=cls_flat_index)
        return self.classifier(self.dropout(pooled))


class ErnieForPretraining(nn.Layer):
    """MLM head (tied to word embeddings) + NSP head."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.mlm_transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.mlm_norm = nn.LayerNorm(cfg.hidden_size)
        self.nsp = nn.Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        from .. import nn as _nn
        from ..tensor import ops as T

        seq_out, pooled = self.ernie(input_ids, token_type_ids,
                                     attention_mask=attention_mask)
        h = self.mlm_norm(_nn.functional.gelu(self.mlm_transform(seq_out)))
        # tied decoder: logits = h @ word_emb.T
        w = self.ernie.embeddings.word_embeddings.weight
        mlm_logits = T.matmul(h, w, transpose_y=True)
        nsp_logits = self.nsp(pooled)
        return mlm_logits, nsp_logits


def ernie_base(**kw):
    return ErnieModel(ErnieConfig.base(**kw))


def ernie_tiny(**kw):
    return ErnieModel(ErnieConfig.tiny(**kw))
