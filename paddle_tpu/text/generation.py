"""Static-shape KV-cache decode engine: fused autoregressive generation.

Reference role: the machine-translation book's decoder loop and
beam_search_op.cc, re-designed serving-first. The reference (and the
eager fallback kept in `generate_eager`) grows the cache with a concat
per token — every step changes the cache shape, so every step retraces
and reallocates, and the cache can never be a `lax.scan` carry. Here the
cache is a preallocated `MultiHeadAttention.StaticKVCache` ([B, H,
max_len, D] buffers + an int32 write index, see nn/layer/transformer.py):

  * PREFILL: the whole (padded) prompt runs ONCE through the regular
    flash-capable attention path and lands in the cache in one
    `dynamic_update_slice`;
  * DECODE: `text.decode.greedy_search` / `beam_search` run the entire
    generation as ONE jitted `lax.scan` with the caches as carry — beam
    ancestry regather tree-maps over the state, so StaticKVCache rides
    it for free; each step's attention is the split-K flash-decode
    kernel on TPU (ops/attention.py) and the XLA reference elsewhere.

Shape-bucket policy: prompt length and batch pad to the next power of
two, so the jit cache stays O(log n) over serving traffic instead of
O(distinct shapes). The `max_length` preallocation contract: the cache
is built with max_length = bucket(prompt_len) + max_new_tokens; rows
whose prompt is shorter than the bucket keep a -1e30 key bias over the
pad hole [len_i, bucket) for the whole generation, and generated tokens
occupy positions bucket, bucket+1, ... (absolute slot indices — the
same convention the eager right-padded reference uses, which is what
makes the two paths bit-comparable).
"""
from __future__ import annotations

import inspect

from ..nn.layer.layers import Layer
from ..nn.layer.transformer import MultiHeadAttention
from ..core.bucketing import bucket_size, pad_rows as _pad_rows  # noqa: F401
from ..core.tensor import Tensor
from ..parallel.functional import functionalize
from ..profiler import trace as _trace
from .decode import beam_search, greedy_search

NEG = -1e30


def _jnp():
    import jax.numpy as jnp

    return jnp


def _raw(x):
    import jax.numpy as jnp

    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _takes_positions(fn):
    """Does the embed callable accept (tokens, positions)? Layers are
    inspected on .forward; plain callables directly."""
    try:
        target = fn.forward if isinstance(fn, Layer) else fn
        params = [p for p in inspect.signature(target).parameters.values()
                  if p.kind in (p.POSITIONAL_ONLY,
                                p.POSITIONAL_OR_KEYWORD)]
        return len(params) >= 2
    except (TypeError, ValueError):
        return False


class _StepNet(Layer):
    """decoder + embed + project as ONE functionalized unit, so a single
    param/buffer pytree feeds both prefill and every scan step."""

    def __init__(self, decoder, embed, project):
        super().__init__()
        self.decoder = decoder
        self.embed = embed
        self.project = project
        self._embed_pos = _takes_positions(embed)

    def _embed(self, tokens, positions):
        if self._embed_pos:
            return self.embed(tokens, positions)
        return self.embed(tokens)

    def forward(self, tokens, positions, memory, tgt_mask=None,
                memory_mask=None, inc=None, static_kv=None,
                prefill=False):
        if prefill:
            static_kv = [
                tuple(_raw(t) for t in layer.cross_attn.gen_cache(
                    memory, type=MultiHeadAttention.StaticCache))
                for layer in self.decoder.layers]
        x = self._embed(tokens, positions)
        cache = [(inc[i],
                  MultiHeadAttention.StaticCache(Tensor._wrap(sk),
                                                 Tensor._wrap(sv)))
                 for i, (sk, sv) in enumerate(static_kv)]
        out, new_caches = self.decoder(x, memory, tgt_mask, memory_mask,
                                       cache)
        logits = self.project(out)
        new_inc = [c[0] for c in new_caches]
        if prefill:
            return logits, new_inc, static_kv
        return logits, new_inc


class DecodeEngine:
    """One engine per (decoder, embed, project) triple. `generate()`
    buckets the call shape, then runs a jitted (prefill + scan) program
    compiled ONCE per bucket — `trace_counts` records per-bucket trace
    counts so serving code (and the compile-count test) can verify the
    compile cache stays bounded."""

    def __init__(self, decoder, embed, project):
        self.embed_ref = embed
        self.project_ref = project
        self._net = _StepNet(decoder, embed, project)
        self._fm = functionalize(self._net)
        # observable jit cache + trace counter: the compile observer /
        # retrace sentinel (profiler.trace) see every compile
        self._compiled = _trace.JitCache(self)
        self.trace_counts = _trace.ObservedCounter(owner="DecodeEngine")
        self._n_params = None      # cached for cost_hint
        self._draft = None         # DraftModel of the last spec call

    def cost_hint(self, key):
        """Analytic cost for one compiled (prefill + scan) program —
        profiler.costs' CPU-safe fallback when XLA's analysis is
        unavailable (or the program compiled before accounting armed).
        Key layout matches generate()'s cache key."""
        from ..profiler import costs as _costs

        if not (isinstance(key, tuple) and len(key) >= 8):
            return None
        Bb, Pb, max_new, K = (int(key[0]), int(key[1]), int(key[2]),
                              int(key[3]))
        mshape = key[7]
        M = int(mshape[0]) if mshape else 0
        if self._n_params is None:
            self._n_params = sum(
                int(getattr(v, "size", 0))
                for v in self._fm.params().values()) + sum(
                int(getattr(v, "size", 0))
                for v in self._fm.buffers().values())
        decoder = self._net.decoder
        h0 = decoder.layers[0].self_attn
        n_layers, heads, hd = len(decoder.layers), h0.num_heads, \
            h0.head_dim
        flops = _costs.transformer_prefill_flops(
            self._n_params, Bb, Pb, n_layers, heads, hd, mem_len=M)
        flops += max_new * _costs.transformer_decode_flops(
            self._n_params, Bb * K, Pb + max_new, n_layers, heads, hd,
            mem_len=M)
        pbytes = sum(
            int(getattr(v, "size", 0)) *
            int(getattr(getattr(v, "dtype", None), "itemsize", 4))
            for v in self._fm.params().values())
        return {"flops": flops,
                "bytes_accessed": float(pbytes) * (max_new + 1)}

    # ------------------------------------------------------------------
    def generate(self, memory, prompt=None, prompt_lengths=None, *,
                 bos_id=0, eos_id=1, max_new_tokens=32, beam_size=1,
                 length_penalty=0.0, memory_mask=None,
                 bucket_batch=True, spec_k=None, spec_ngram=2,
                 draft_model=None, return_spec_stats=False):
        """Generate max_new_tokens per row. Greedy (beam_size=1) returns
        (tokens [B, max_new_tokens], lengths [B]); beam returns
        (tokens [B, K, max_new_tokens] best-first, scores [B, K],
        lengths [B, K]). `prompt` [B, P] int (must start with bos;
        defaults to a bos column); ragged prompts pass prompt_lengths
        [B] and right-pad.

        spec_k >= 2 switches greedy generation to SPECULATIVE
        draft-verify (text/speculative.py): each round drafts spec_k -
        1 tokens — suffix n-gram self-speculation over the row's own
        history by default (`spec_ngram`), or a `DraftModel` with its
        own StaticKVCache — and one spec_k-token verify step accepts
        the matching prefix. Output is BIT-IDENTICAL to spec_k=None;
        only the dispatch count changes. `return_spec_stats=True`
        appends a {rounds, proposed, accepted} acceptance-telemetry
        dict. One compile per (bucket, spec_k): `spec_k` should come
        from a small fixed set (pow2: 2/4/8) so the jit cache stays
        bounded."""
        import jax.numpy as jnp
        import numpy as np

        if spec_k is not None:
            spec_k = int(spec_k)
            if spec_k < 2:
                raise ValueError("spec_k must be >= 2 (the pending "
                                 "token plus at least one draft)")
            if beam_size != 1:
                raise ValueError("speculative decoding is greedy-only "
                                 "(beam_size must be 1)")
        memory = _raw(memory)
        B0 = memory.shape[0]
        if prompt is None:
            prompt = jnp.full((B0, 1), bos_id, jnp.int32)
        prompt = _raw(prompt).astype(jnp.int32)
        P0 = prompt.shape[1]
        if prompt_lengths is None:
            lengths = jnp.full((B0,), P0, jnp.int32)
        else:
            lengths = _raw(prompt_lengths).astype(jnp.int32)
        Pb = bucket_size(P0)
        Bb = bucket_size(B0) if bucket_batch else B0
        pad_cols = jnp.full((B0, Pb - P0), eos_id, jnp.int32)
        prompt_b = _pad_rows(jnp.concatenate([prompt, pad_cols], 1), Bb)
        lengths_b = _pad_rows(lengths, Bb)
        memory_b = _pad_rows(memory, Bb)
        mm_b = None if memory_mask is None else \
            _pad_rows(_raw(memory_mask), Bb)
        self._draft = draft_model
        key = (Bb, Pb, int(max_new_tokens), int(beam_size),
               int(bos_id), int(eos_id), float(length_penalty),
               memory_b.shape[1:], str(memory_b.dtype),
               mm_b is not None, spec_k or 0, int(spec_ngram),
               0 if draft_model is None else id(draft_model))
        fn = self._compiled.get(key)
        if fn is None:
            fn = self._build(key)
            self._compiled[key] = fn
            fn = self._compiled[key]   # the observed wrapper
        args = [self._fm.params(), self._fm.buffers(), memory_b,
                prompt_b, lengths_b]
        if mm_b is not None:
            args.append(mm_b)
        if spec_k is not None and draft_model is not None:
            args += [draft_model.params(), draft_model.buffers()]
        out = fn(*args)
        if spec_k is not None:
            toks, lens, stats = out
            toks = np.asarray(toks)[:B0]
            lens = np.asarray(lens)[:B0]
            if return_spec_stats:
                return toks, lens, {k2: int(v)
                                    for k2, v in stats.items()}
            return toks, lens
        if beam_size == 1:
            toks, lens = out
            return np.asarray(toks)[:B0], np.asarray(lens)[:B0]
        toks, scores, lens = out
        return (np.asarray(toks)[:B0], np.asarray(scores)[:B0],
                np.asarray(lens)[:B0])

    # ------------------------------------------------------------------
    def _build(self, key):
        import jax
        import jax.numpy as jnp

        (Bb, Pb, max_new, K, bos_id, eos_id, lp, _mshape, _mdtype,
         has_mm) = key[:10]
        spec_k = int(key[10]) if len(key) > 10 else 0
        ngram = int(key[11]) if len(key) > 11 else 2
        has_draft = bool(key[12]) if len(key) > 12 else False
        draft = self._draft if has_draft else None
        fm = self._fm
        decoder = self._net.decoder
        # the max_length preallocation contract; speculative decoding
        # pads the cache by spec_k so a round's fixed-k verify write
        # never clips (the extra tail stays masked — bit-neutral)
        L = Pb + max_new + spec_k

        def gen_fn(params, buffers, memory, prompt, lengths,
                   *extra):
            self.trace_counts[key] += 1  # python side effect: one per
            #                              trace = one per compile
            i = 0
            mem_mask = None
            if has_mm:
                mem_mask, i = extra[0], 1
            if has_draft:
                dparams, dbuffers = extra[i], extra[i + 1]
            kpos = jnp.arange(L, dtype=jnp.int32)
            hole = (kpos[None, :] >= lengths[:, None]) & \
                (kpos[None, :] < jnp.int32(Pb))
            pad_bias = jnp.where(hole, jnp.float32(NEG),
                                 jnp.float32(0.0))        # [Bb, L]
            positions = jnp.broadcast_to(
                jnp.arange(Pb, dtype=jnp.int32)[None], (Bb, Pb))
            inc0 = [layer.self_attn.gen_cache(
                None, max_length=L, batch_size=Bb, dtype=memory.dtype)
                for layer in decoder.layers]
            (lg, inc1, static_kv), _ = fm.apply(
                params, buffers, None, prompt, positions, memory,
                training=False, tgt_mask=pad_bias[:, :Pb],
                memory_mask=mem_mask, inc=inc0, prefill=True)
            # the next token conditions on each row's LAST REAL prompt
            # position, not the pad tail
            last = jnp.take_along_axis(
                lg, (lengths - 1)[:, None, None], axis=1)[:, 0]
            if spec_k:
                from ..ops import attention as A
                from . import speculative as SP
                from .decode import spec_greedy_search

                iota_k = jnp.arange(spec_k, dtype=jnp.int32)
                hist0 = jnp.zeros((Bb, L), jnp.int32)
                hist0 = jax.lax.dynamic_update_slice(
                    hist0, prompt, (jnp.int32(0), jnp.int32(0)))
                state0 = {"inc": inc1, "hist": hist0}
                if has_draft:
                    dfm = draft._fm
                    ddec = draft._net.decoder
                    dinc0 = [ly.self_attn.gen_cache(
                        None, max_length=L, batch_size=Bb,
                        dtype=memory.dtype) for ly in ddec.layers]
                    (_, dinc1, dstatic), _ = dfm.apply(
                        dparams, dbuffers, None, prompt, positions,
                        memory, training=False,
                        tgt_mask=pad_bias[:, :Pb],
                        memory_mask=mem_mask, inc=dinc0, prefill=True)
                    state0["dinc"] = dinc1

                def verify_fn(fed, st):
                    posn = st["inc"][0].index[:, None] + iota_k[None, :]
                    with A.kv_verify_scope():
                        (lg2, inc2), _ = fm.apply(
                            params, buffers, None, fed, posn, memory,
                            training=False, tgt_mask=pad_bias,
                            memory_mask=mem_mask, inc=st["inc"],
                            static_kv=static_kv, prefill=False)
                    return lg2, dict(
                        st, inc=inc2,
                        hist=SP.write_hist(st["hist"], fed,
                                           st["inc"][0].index))

                if has_draft:
                    def draft_fn(pending, cnt, st):
                        dinc = st["dinc"]
                        t = pending
                        toks_d = []
                        # k-1 draft proposals, then one write-only step
                        # so the draft cache covers the verify's k slots
                        for _ in range(spec_k - 1):
                            posn = dinc[0].index[:, None]
                            (lgd, dinc), _ = dfm.apply(
                                dparams, dbuffers, None, t[:, None],
                                posn, memory, training=False,
                                tgt_mask=pad_bias, memory_mask=mem_mask,
                                inc=dinc, static_kv=dstatic,
                                prefill=False)
                            t = lgd[:, 0].argmax(-1).astype(jnp.int32)
                            toks_d.append(t)
                        posn = dinc[0].index[:, None]
                        (_, dinc), _ = dfm.apply(
                            dparams, dbuffers, None, t[:, None], posn,
                            memory, training=False, tgt_mask=pad_bias,
                            memory_mask=mem_mask, inc=dinc,
                            static_kv=dstatic, prefill=False)
                        return (jnp.stack(toks_d, axis=1),
                                dict(st, dinc=dinc))
                else:
                    def draft_fn(pending, cnt, st):
                        drafts = SP.ngram_propose(
                            st["hist"], pending, lengths, Pb,
                            spec_k - 1, cnt - 1, ngram)
                        return drafts, st

                def rollback_fn(st, n_match, active):
                    out = dict(st, inc=[
                        c._replace(index=SP.rollback_index(
                            c.index, spec_k, n_match, active))
                        for c in st["inc"]])
                    if has_draft:
                        out["dinc"] = [
                            c._replace(index=SP.rollback_index(
                                c.index, spec_k, n_match, active))
                            for c in st["dinc"]]
                    return out

                return spec_greedy_search(
                    verify_fn, draft_fn, rollback_fn, state0, Bb,
                    eos_id, max_new, spec_k, last, return_stats=True)
            rep = 1 if K == 1 else K

            def tile(t):
                return t if rep == 1 else jnp.repeat(t, rep, axis=0)

            mem_t = tile(memory)
            bias_t = tile(pad_bias)
            mm_t = None if mem_mask is None else tile(mem_mask)
            static_t = [(tile(sk), tile(sv)) for sk, sv in static_kv]

            def step_fn(tok, state):
                inc = state
                posn = inc[0].index[:, None]  # written count == the
                #                               incoming token's slot
                (lg2, inc2), _ = fm.apply(
                    params, buffers, None, tok[:, None], posn, mem_t,
                    training=False, tgt_mask=bias_t,
                    memory_mask=mm_t, inc=inc, static_kv=static_t,
                    prefill=False)
                return lg2[:, 0], inc2

            if K == 1:
                return greedy_search(step_fn, inc1, Bb, bos_id, eos_id,
                                     max_new, init_logits=last)
            toks, scores, lens = beam_search(
                step_fn, inc1, Bb, bos_id, eos_id, K, max_new,
                length_penalty=lp, init_logits=last)
            return toks, scores, lens

        return jax.jit(gen_fn)


# ----------------------------------------------------------------------
# eager concat-cache reference: the A side of the decode_throughput
# bench and the parity oracle for the fused path
# ----------------------------------------------------------------------

def generate_eager(decoder, embed, project, memory, prompt=None,
                   prompt_lengths=None, *, bos_id=0, eos_id=1,
                   max_new_tokens=32, beam_size=1, length_penalty=0.0,
                   memory_mask=None, pad_prompt_to=None):
    """Token-by-token generation on the reference concat-grown Cache
    path: every step T.concat-extends the cache (one reallocation + one
    retrace per token — the regime the static engine removes). Pads the
    prompt to `pad_prompt_to` (default bucket_size(P)) with the same
    masking/position conventions as the fused path, so outputs are
    directly comparable."""
    import jax
    import numpy as np

    jnp = _jnp()
    takes_pos = _takes_positions(embed)

    def run_embed(tokens, positions):
        t = Tensor._wrap(jnp.asarray(tokens, jnp.int32))
        if takes_pos:
            return embed(t, Tensor._wrap(jnp.asarray(positions,
                                                     jnp.int32)))
        return embed(t)

    was_training = decoder.training
    decoder.eval()
    try:
        memory_t = Tensor._wrap(_raw(memory))
        B = memory_t.shape[0]
        if prompt is None:
            prompt = jnp.full((B, 1), bos_id, jnp.int32)
        prompt = _raw(prompt).astype(jnp.int32)
        P0 = prompt.shape[1]
        Pb = pad_prompt_to or bucket_size(P0)
        lengths = (jnp.full((B,), P0, jnp.int32)
                   if prompt_lengths is None
                   else _raw(prompt_lengths).astype(jnp.int32))
        prompt = jnp.concatenate(
            [prompt, jnp.full((B, Pb - P0), eos_id, jnp.int32)], 1)
        L = Pb + max_new_tokens
        kpos = jnp.arange(L, dtype=jnp.int32)
        hole = (kpos[None, :] >= lengths[:, None]) & \
            (kpos[None, :] < jnp.int32(Pb))
        pad_bias = jnp.where(hole, jnp.float32(NEG), jnp.float32(0.0))
        mm = None if memory_mask is None else Tensor._wrap(
            _raw(memory_mask))

        def prefill(mem_t, bias):
            caches = decoder.gen_cache(mem_t)
            x = run_embed(prompt if bias.shape[0] == B else
                          jnp.repeat(prompt, beam_size, axis=0),
                          jnp.broadcast_to(
                              jnp.arange(Pb, dtype=jnp.int32)[None],
                              (bias.shape[0], Pb)))
            cmask = jnp.where(
                jnp.tril(jnp.ones((Pb, Pb), bool)), 0.0, NEG
            ).astype(jnp.float32)
            full = cmask[None, None] + bias[:, None, None, :Pb]
            out, caches = decoder(x, mem_t, Tensor._wrap(full), mm2(mm,
                                  bias.shape[0]), caches)
            return project(out), caches

        def mm2(m, n):
            if m is None:
                return None
            if m.shape[0] == n:
                return m
            return Tensor._wrap(jnp.repeat(_raw(m), beam_size, axis=0))

        def step(tok, pos, n_keys, mem_t, bias, caches):
            x = run_embed(tok[:, None], pos[:, None])
            out, caches = decoder(
                x, mem_t, Tensor._wrap(bias[:, None, None, :n_keys]),
                mm2(mm, bias.shape[0]), caches)
            return _raw(project(out))[:, 0], caches

        if beam_size == 1:
            logits, caches = prefill(memory_t, pad_bias)
            lg = _raw(logits)
            last = jnp.take_along_axis(
                lg, (lengths - 1)[:, None, None], axis=1)[:, 0]
            tok = last.argmax(-1).astype(jnp.int32)
            done = tok == eos_id
            lens = jnp.ones((B,), jnp.int32)
            toks = [tok]
            for t in range(1, max_new_tokens):
                lg2, caches = step(tok, jnp.full((B,), Pb + t - 1,
                                                 jnp.int32),
                                   Pb + t, memory_t, pad_bias, caches)
                nxt = lg2.argmax(-1).astype(jnp.int32)
                nxt = jnp.where(done, eos_id, nxt)
                lens = lens + (~done).astype(jnp.int32)
                done = done | (nxt == eos_id)
                tok = nxt
                toks.append(tok)
            return (np.stack([np.asarray(t) for t in toks], 1),
                    np.asarray(lens))

        # ---- beam: the exact decode.beam_search math, python-stepped
        # over concat caches regathered by ancestry ----
        K = beam_size
        mem_k = Tensor._wrap(jnp.repeat(_raw(memory_t), K, axis=0))
        bias_k = jnp.repeat(pad_bias, K, axis=0)
        logits, _ = prefill(memory_t, pad_bias)
        lg = _raw(logits)
        last = jnp.take_along_axis(
            lg, (lengths - 1)[:, None, None], axis=1)[:, 0]
        lp0 = jax.nn.log_softmax(last.astype(jnp.float32), -1)
        logp, top_ix = jax.lax.top_k(lp0, K)            # [B, K]
        tok = top_ix.astype(jnp.int32)
        fin = tok == eos_id
        lens = jnp.ones((B, K), jnp.int32)
        # the prefill cache is per-row; tile to beam-major B*K rows
        _, caches = prefill(mem_k, bias_k)
        histories = [[[int(tok[b, k])] for k in range(K)]
                     for b in range(B)]
        for t in range(1, max_new_tokens):
            lg2, caches = step(
                tok.reshape(B * K),
                jnp.full((B * K,), Pb + t - 1, jnp.int32),
                Pb + t, mem_k, bias_k, caches)
            V = lg2.shape[-1]
            lp = jax.nn.log_softmax(lg2.astype(jnp.float32), -1)
            lp = lp.reshape(B, K, V)
            # scoring mask uses decode.beam_search's own NEG so the two
            # paths rank identically even among dead-beam candidates
            from .decode import NEG as SCORE_NEG
            fin_mask = jnp.full((V,), SCORE_NEG,
                                jnp.float32).at[eos_id].set(0.0)
            lp = jnp.where(fin[:, :, None], fin_mask[None, None, :], lp)
            total = logp[:, :, None] + lp
            logp, top_ix = jax.lax.top_k(total.reshape(B, K * V), K)
            src = (top_ix // V).astype(jnp.int32)
            tok = (top_ix % V).astype(jnp.int32)

            def regather(arr):
                a = _raw(arr)
                a = a.reshape((B, K) + a.shape[1:])
                srcx = src.reshape((B, K) + (1,) * (a.ndim - 2))
                a = jnp.take_along_axis(a, srcx, axis=1)
                return Tensor._wrap(a.reshape((B * K,) + a.shape[2:]))

            caches = jax.tree_util.tree_map(
                regather, caches,
                is_leaf=lambda x: isinstance(x, Tensor))
            fin = jnp.take_along_axis(fin, src, axis=1)
            lens = jnp.take_along_axis(lens, src, axis=1)
            lens = lens + (~fin).astype(jnp.int32)
            fin = fin | (tok == eos_id)
            histories = [[histories[b][int(src[b, k])] +
                          [int(tok[b, k])] for k in range(K)]
                         for b in range(B)]
        denom = jnp.maximum(lens, 1).astype(jnp.float32) ** \
            length_penalty
        scores = logp / denom
        order = np.asarray(jnp.argsort(-scores, axis=1))
        seqs = np.asarray([[histories[b][order[b, k]]
                            for k in range(K)] for b in range(B)],
                          dtype=np.int32)
        scores = np.take_along_axis(np.asarray(scores), order, axis=1)
        lens = np.take_along_axis(np.asarray(lens), order, axis=1)
        return seqs, scores, lens
    finally:
        decoder.train() if was_training else decoder.eval()
