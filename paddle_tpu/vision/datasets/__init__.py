"""Vision datasets.

Reference parity: python/paddle/vision/datasets/ (MNIST, Cifar10/100,
FashionMNIST, Flowers). This environment has no network egress, so
constructors accept local files (same formats as the reference loaders) and
raise a clear error when download would be required; `FakeData` provides a
drop-in synthetic dataset for tests/benchmarks.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ...io import Dataset


class FakeData(Dataset):
    """Synthetic images dataset (deterministic; torchvision-FakeData-like)."""

    def __init__(self, size=1000, image_shape=(3, 224, 224), num_classes=10,
                 transform=None, dtype="float32"):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.dtype = dtype

    def __getitem__(self, idx):
        rng = np.random.RandomState(idx % 65536)
        img = rng.standard_normal(self.image_shape).astype(self.dtype)
        label = np.asarray(idx % self.num_classes, dtype=np.int64)
        if self.transform:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.size


def _require(path, name):
    if path is None or not os.path.exists(path):
        raise RuntimeError(
            f"{name}: no network egress in this environment — pass the "
            f"local data file path explicitly (got {path!r}), or use "
            f"paddle_tpu.vision.datasets.FakeData for synthetic data")


class MNIST(Dataset):
    """idx-ubyte MNIST reader (reference vision/datasets/mnist.py format)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        _require(image_path, "MNIST")
        _require(label_path, "MNIST")
        with gzip.open(image_path, "rb") if image_path.endswith(".gz") \
                else open(image_path, "rb") as f:
            magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
            self.images = np.frombuffer(f.read(), dtype=np.uint8).reshape(
                num, 1, rows, cols).astype(np.float32) / 255.0
        with gzip.open(label_path, "rb") if label_path.endswith(".gz") \
                else open(label_path, "rb") as f:
            struct.unpack(">II", f.read(8))
            self.labels = np.frombuffer(f.read(), dtype=np.uint8).astype(
                np.int64)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


FashionMNIST = MNIST


class Cifar10(Dataset):
    """python-pickle CIFAR reader (reference vision/datasets/cifar.py)."""

    MODE_MAP = {"train": [f"data_batch_{i}" for i in range(1, 6)],
                "test": ["test_batch"]}

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        _require(data_file, "Cifar10")
        images, labels = [], []
        with tarfile.open(data_file) as tf:
            for member in tf.getmembers():
                base = os.path.basename(member.name)
                if base in self.MODE_MAP[mode]:
                    d = pickle.load(tf.extractfile(member),
                                    encoding="bytes")
                    images.append(d[b"data"])
                    labels.extend(d.get(b"labels", d.get(b"fine_labels")))
        self.images = np.concatenate(images).reshape(
            -1, 3, 32, 32).astype(np.float32) / 255.0
        self.labels = np.asarray(labels, dtype=np.int64)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    MODE_MAP = {"train": ["train"], "test": ["test"]}


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        _require(root, "DatasetFolder")
        self.root = root
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fn in sorted(os.listdir(cdir)):
                self.samples.append((os.path.join(cdir, fn),
                                     self.class_to_idx[c]))
        self.transform = transform
        self.loader = loader or _default_loader

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


ImageFolder = DatasetFolder


def _default_loader(path):
    if path.endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image

        return np.asarray(Image.open(path).convert("RGB"),
                          dtype=np.float32).transpose(2, 0, 1) / 255.0
    except ImportError:
        raise RuntimeError("PIL unavailable; use .npy image files")
