"""paddle.vision.ops parity: detection operators over eager Tensors.

Reference: python/paddle/vision/ops.py (yolo_box, roi_align, roi_pool,
nms, prior_box, box_coder...). Thin Tensor wrappers over
paddle_tpu.ops.detection kernels (static-shape TPU design: NMS results
are -1-padded fixed buffers + counts).
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..ops import detection as D


def _raw(v):
    return v._data if isinstance(v, Tensor) else np.asarray(v)


def nms(boxes, iou_threshold=0.3, scores=None, score_threshold=None,
        category_idxs=None, categories=None, top_k=None):
    import jax.numpy as jnp

    b = _raw(boxes)
    s = _raw(scores) if scores is not None else jnp.ones((b.shape[0],))
    if category_idxs is not None:
        # per-category NMS: offset each category onto a disjoint canvas so
        # cross-category boxes never overlap (batched-NMS trick)
        cats = jnp.asarray(_raw(category_idxs)).astype(jnp.float32)
        span = jnp.abs(jnp.asarray(b)).max() + 1.0
        b = jnp.asarray(b) + (cats * 2.0 * span)[:, None]
    keep, cnt = D.nms(b, s, iou_threshold, score_threshold,
                      top_k or b.shape[0])
    n = int(cnt)
    return Tensor._wrap(keep[:n])


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=64,
                   keep_top_k=100, nms_threshold=0.3,
                   background_label=0):
    out, num = D.multiclass_nms(_raw(bboxes), _raw(scores),
                                score_threshold, nms_top_k, keep_top_k,
                                nms_threshold,
                                background_label=background_label)
    return Tensor._wrap(out), Tensor._wrap(num)


def roi_align(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    import jax.numpy as jnp

    xr, br = _raw(x), _raw(boxes)
    if boxes_num is None:
        batch_ids = jnp.zeros((br.shape[0],), jnp.int32)
    else:
        bn = np.asarray(_raw(boxes_num)).reshape(-1)
        batch_ids = jnp.asarray(np.repeat(np.arange(len(bn)), bn)
                                .astype(np.int32))
    out = D.roi_align(xr, br, batch_ids, output_size, spatial_scale,
                      sampling_ratio, aligned)
    return Tensor._wrap(out)


def roi_pool(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0):
    import jax.numpy as jnp

    xr, br = _raw(x), _raw(boxes)
    if boxes_num is None:
        batch_ids = jnp.zeros((br.shape[0],), jnp.int32)
    else:
        bn = np.asarray(_raw(boxes_num)).reshape(-1)
        batch_ids = jnp.asarray(np.repeat(np.arange(len(bn)), bn)
                                .astype(np.int32))
    return Tensor._wrap(D.roi_pool(xr, br, batch_ids, output_size,
                                   spatial_scale))


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, name=None,
             scale_x_y=1.0):
    boxes, scores = D.yolo_box(_raw(x), _raw(img_size), anchors,
                               class_num, conf_thresh, downsample_ratio,
                               clip_bbox, scale_x_y)
    return Tensor._wrap(boxes), Tensor._wrap(scores)


def prior_box(input, image, min_sizes, max_sizes=None,
              aspect_ratios=(1.0,), variance=(0.1, 0.1, 0.2, 0.2),
              flip=False, clip=False, steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    xr, im = _raw(input), _raw(image)
    # reference steps order is [step_w, step_h]; the kernel takes (h, w)
    boxes, var = D.prior_box(
        (xr.shape[2], xr.shape[3]), (im.shape[2], im.shape[3]),
        list(min_sizes), list(max_sizes) if max_sizes else None,
        tuple(aspect_ratios), tuple(variance), flip, clip,
        (steps[1] if len(steps) > 1 else steps[0], steps[0]),
        offset, min_max_aspect_ratios_order)
    return Tensor._wrap(boxes), Tensor._wrap(var)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None, axis=0):
    pv = None if prior_box_var is None else _raw(prior_box_var)
    return Tensor._wrap(D.box_coder(_raw(prior_box), pv,
                                    _raw(target_box), code_type,
                                    box_normalized))


def iou_similarity(x, y, box_normalized=True):
    return Tensor._wrap(D.iou_matrix(_raw(x), _raw(y), box_normalized))


def bipartite_match(dist_matrix):
    idx, d = D.bipartite_match(_raw(dist_matrix))
    return Tensor._wrap(idx), Tensor._wrap(d)


def box_clip(input, im_info, name=None):
    return Tensor._wrap(D.box_clip(_raw(input), _raw(im_info)))
