"""paddle.vision.transforms parity (numpy CHW images)."""
from __future__ import annotations

import numbers
import random

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, dtype=np.float32)
        if arr.ndim == 3 and arr.shape[-1] in (1, 3, 4) and \
                self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        if arr.max() > 1.5:
            arr = arr / 255.0
        return arr


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img, dtype=np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        return (img - self.mean.reshape(shape)) / self.std.reshape(shape)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, numbers.Number) \
            else tuple(size)

    def _apply_image(self, img):
        img = np.asarray(img, dtype=np.float32)
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        if chw:
            c, h, w = img.shape
        else:
            h, w = img.shape[:2]
        oh, ow = self.size
        yi = np.clip((np.arange(oh) + 0.5) * h / oh - 0.5, 0,
                     h - 1).astype(np.int64)
        xi = np.clip((np.arange(ow) + 0.5) * w / ow - 0.5, 0,
                     w - 1).astype(np.int64)
        if chw:
            return img[:, yi][:, :, xi]
        return img[yi][:, xi]


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, numbers.Number) \
            else tuple(size)

    def _apply_image(self, img):
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        h, w = (img.shape[1:] if chw else img.shape[:2])
        th, tw = self.size
        i = max(0, (h - th) // 2)
        j = max(0, (w - tw) // 2)
        return img[:, i:i + th, j:j + tw] if chw else \
            img[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None):
        self.size = (size, size) if isinstance(size, numbers.Number) \
            else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        if self.padding:
            p = self.padding
            pads = [(0, 0), (p, p), (p, p)] if chw else \
                [(p, p), (p, p)] + ([(0, 0)] if img.ndim == 3 else [])
            img = np.pad(img, pads)
        h, w = (img.shape[1:] if chw else img.shape[:2])
        th, tw = self.size
        i = random.randint(0, max(0, h - th))
        j = random.randint(0, max(0, w - tw))
        return img[:, i:i + th, j:j + tw] if chw else \
            img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
            return img[:, :, ::-1].copy() if chw else img[:, ::-1].copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
            return img[:, ::-1].copy() if chw else img[::-1].copy()
        return img


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3)):
        self.size = (size, size) if isinstance(size, numbers.Number) \
            else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self._resize = Resize(self.size)

    def _apply_image(self, img):
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        h, w = (img.shape[1:] if chw else img.shape[:2])
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            tw = int(round((target * ar) ** 0.5))
            th = int(round((target / ar) ** 0.5))
            if 0 < tw <= w and 0 < th <= h:
                i = random.randint(0, h - th)
                j = random.randint(0, w - tw)
                crop = img[:, i:i + th, j:j + tw] if chw else \
                    img[i:i + th, j:j + tw]
                return self._resize._apply_image(crop)
        return self._resize._apply_image(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        return np.asarray(img).transpose(self.order)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)
