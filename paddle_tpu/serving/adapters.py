"""Multi-tenant adapter serving: batched LoRA banks over one shared base.

Millions of users means thousands of fine-tuned variants, not one model
per pool. This module lets ONE serving engine carry many tenants:

  * every target Linear of the decoder (self-attention QKV/out-proj and
    the FFN pair, per layer) gets a row in stacked device banks
    ``A [capacity, d_in, r]`` / ``B [capacity, r, d_out]`` — row 0 is
    the base model and stays all-zero, so base requests ride the same
    compiled step with an exactly-zero delta;
  * per-slot adapter ids ship into the decode/prefill programs as
    traced int32 inputs (the page-table trick), and the delta is ONE
    gathered batched matmul (`ops.quant.lora_delta`) fused into the
    existing single-dispatch step — joining a new tenant, switching
    adapters, and hot-load/evict NEVER retrace;
  * `AdapterPool` is the host-side bookkeeping, riding the
    PageAllocator pattern: a free list + refcounts over bank rows,
    LRU reuse of zero-reference rows (a released adapter stays hot
    until its row is needed — the adapter cache), and `OutOfAdapters`
    backpressure when every row is pinned by a live slot (the engine
    defers the queue head via `Scheduler.push_front`, exactly like
    OutOfPages);
  * `quantize_net` applies the int8 weight path to the whole serving
    stack (`nn.Linear/Embedding.quantize_int8`), shrinking the base
    weights the tenants share — the HBM the ledger frees is what pays
    for more slots and more adapters at equal memory.

Host-side only: banks are plain jax arrays handed to the engine per
dispatch; loading an adapter is a functional ``.at[row].set`` per
target (a partial load can never be observed — the fault point fires
before any write). Single-threaded by the engine contract, like the
PageAllocator.
"""
from __future__ import annotations

import collections

import numpy as np

from ..testing import faults

__all__ = ["OutOfAdapters", "AdapterPool", "quantize_net",
           "decoder_lora_targets"]

#: fault point on the adapter hot-load (the device bank write): chaos
#: cells prove a transient load retries and a persistent one isolates
#: only that tenant's requests
_PT_ADAPTER_LOAD = faults.point("serving.adapter_load")


class OutOfAdapters(RuntimeError):
    """Every adapter bank row is pinned by a live slot: backpressure —
    the engine defers the queue head until a tenant's last slot
    drains and frees a row."""


def decoder_lora_targets(decoder):
    """The per-layer large dense matmuls adapters attach to: self-attn
    Q/K/V/out projections + the FFN pair, in layer order. Cross-attn
    and norms stay base-only (the prefix-attach path runs cross-attn
    K/V alone, so a shared-prefix join needs no banks)."""
    out = []
    for layer in decoder.layers:
        sa = layer.self_attn
        out.extend((sa.q_proj, sa.k_proj, sa.v_proj, sa.out_proj))
        out.extend((layer.linear1, layer.linear2))
    return out


def quantize_net(decoder, embed=None, project=None):
    """int8-quantize every large dense weight of a serving stack: the
    decoder's self/cross-attention projections and FFN pairs, the
    token embedding's vocab table, and the logits projection.
    Symmetric per-output-channel scales, fp32 compute preserved
    (ops.quant); biases and norms stay fp32. In-place and one-way —
    the engine owns the model once it serves it."""
    n = 0
    for layer in decoder.layers:
        for attn in (layer.self_attn, layer.cross_attn):
            for lin in (attn.q_proj, attn.k_proj, attn.v_proj,
                        attn.out_proj):
                lin.quantize_int8()
                n += 1
        layer.linear1.quantize_int8()
        layer.linear2.quantize_int8()
        n += 2
    if embed is not None and hasattr(embed, "quantize_int8"):
        embed.quantize_int8()
        n += 1
    if project is not None and hasattr(project, "quantize_int8"):
        project.quantize_int8()
        n += 1
    return n


class AdapterPool:
    """Refcounted hot-load/evict of LoRA adapter banks for one serving
    engine. ``capacity`` counts bank rows INCLUDING the reserved base
    row 0; ``rank`` is the shared low-rank r; ``alpha`` the LoRA
    scaling (B is stored pre-scaled by alpha/r, so the serving delta
    and the merged-weight oracle share one convention). Tenants
    `register()` host-side weights once; `acquire()` pins a bank row
    for a slot (loading over the LRU zero-reference row on a miss)
    and `release()` unpins it — a zero-reference adapter stays HOT
    until its row is reused, which is the adapter cache the hit-rate
    gauge measures."""

    def __init__(self, decoder, *, capacity=4, rank=8, alpha=None):
        if capacity < 2:
            raise ValueError("capacity must be >= 2 (the reserved "
                             "base row plus at least one adapter)")
        if rank < 1:
            raise ValueError("rank must be >= 1")
        import jax.numpy as jnp

        self.decoder = decoder
        self.capacity = int(capacity)
        self.rank = int(rank)
        self.alpha = float(alpha) if alpha is not None else float(rank)
        self.targets = decoder_lora_targets(decoder)
        self._dims = []
        for i, lin in enumerate(self.targets):
            lin._lora_idx = i
            self._dims.append((int(lin.in_features),
                               int(lin.out_features)))
        self._A = [jnp.zeros((self.capacity, din, self.rank),
                             jnp.float32) for din, _ in self._dims]
        self._B = [jnp.zeros((self.capacity, self.rank, dout),
                             jnp.float32) for _, dout in self._dims]
        self._registry = {}            # name -> [(A, B_scaled) numpy]
        self._gen = {}                 # name -> registration count:
        #                                per-tenant prefix-cache keys
        #                                carry it, so re-registered
        #                                weights can never serve a
        #                                stale cached prefix
        self._rows = {}                # name -> hot bank row
        self._row_name = {}            # row -> name
        self.refcount = np.zeros(self.capacity, np.int64)
        self._free = list(range(self.capacity - 1, 0, -1))
        self._lru = collections.OrderedDict()   # zero-ref hot rows
        #: bumped per load so placements (sharded device_put) can
        #: cache the placed banks between loads
        self.version = 0
        self.loads = 0
        self.evictions = 0
        self.hits = 0
        self.misses = 0
        self._metrics = None
        self._invalidate_cbs = []      # see on_invalidate()

    # ---- engine wiring ----
    def bind_metrics(self, metrics):
        """The engine mirrors pool events into its ServingMetrics
        tenancy section."""
        self._metrics = metrics

    # ---- tenant registry (host-side cold storage) ----
    def register(self, name, weights):
        """Register a tenant's adapter: `weights` is a list aligned
        with `targets` of (A [d_in, r], B [r, d_out]) arrays (or None
        for targets the adapter leaves at base). B is stored pre-
        scaled by alpha/r."""
        if name is None or name == "base":
            raise ValueError("adapter name None/'base' is reserved "
                             "for the base model")
        if len(weights) != len(self.targets):
            raise ValueError(
                f"adapter {name!r} has {len(weights)} target entries, "
                f"pool targets {len(self.targets)}")
        s = self.alpha / self.rank
        stored = []
        for i, wpair in enumerate(weights):
            din, dout = self._dims[i]
            if wpair is None:
                stored.append((np.zeros((din, self.rank), np.float32),
                               np.zeros((self.rank, dout), np.float32)))
                continue
            wa, wb = wpair
            wa = np.asarray(wa, np.float32)
            wb = np.asarray(wb, np.float32) * s
            if wa.shape != (din, self.rank) or \
                    wb.shape != (self.rank, dout):
                raise ValueError(
                    f"adapter {name!r} target {i}: shapes "
                    f"{wa.shape}/{wb.shape} != "
                    f"({din}, {self.rank})/({self.rank}, {dout})")
            stored.append((wa, wb))
        # re-registration swaps the tenant's weights: refuse under
        # live traffic (a slot mid-decode on the old weights), drop a
        # zero-reference hot row so the next acquire reloads, and
        # bump the generation the per-tenant prefix keys carry (a
        # cached prefix prefilled under the OLD weights must miss)
        row = self._rows.get(name)
        if row is not None:
            if self.refcount[row] > 0:
                raise ValueError(
                    f"adapter {name!r} is pinned by a live slot; "
                    f"drain it before re-registering new weights")
            self._lru.pop(row, None)
            del self._rows[name]
            del self._row_name[row]
            self._free.append(row)
        self._registry[name] = stored
        self._gen[name] = self._gen.get(name, 0) + 1
        for cb in self._invalidate_cbs:
            cb(name, self._gen[name])
        return self

    def generation(self, name):
        """Registration generation for `name` (0 = unregistered) —
        folded into the paged engine's per-tenant prefix keys."""
        return self._gen.get(name, 0)

    def on_invalidate(self, cb):
        """Subscribe `cb(name, new_generation)` to re-registrations:
        the paged engine's radix prefix cache drops the tenant's
        subtree EAGERLY (releasing its page references now) instead of
        waiting for the generation key to orphan it lazily. Callbacks
        must hold only weak references to long-lived owners."""
        self._invalidate_cbs.append(cb)
        return cb

    def register_random(self, name, seed=0, scale=0.1):
        """Convenience for tests/benches: a deterministic random
        adapter across every target."""
        rs = np.random.RandomState(seed)
        ws = [(rs.randn(din, self.rank).astype(np.float32) * scale,
               rs.randn(self.rank, dout).astype(np.float32) * scale)
              for din, dout in self._dims]
        return self.register(name, ws)

    def registered(self, name):
        return name in self._registry

    def tenants(self):
        return sorted(self._registry)

    def merged_weights(self, name):
        """[(target_index, merged W' = W + A @ B_scaled)] for a
        registered tenant — the oracle the acceptance tests serve a
        solo engine with. Requires the targets to still hold fp32
        weights (merge before quantize_net)."""
        from ..ops.quant import merge_lora_weight

        out = []
        for i, (wa, wb) in enumerate(self._registry[name]):
            lin = self.targets[i]
            if lin.weight is None:
                raise ValueError("merged_weights needs fp32 target "
                                 "weights (quantized in place)")
            out.append((i, merge_lora_weight(lin.weight._data, wa, wb)))
        return out

    # ---- hot rows: acquire / release / load ----
    def can_acquire(self, name):
        """Admission headroom: True when `name` is already hot or a
        bank row is free/evictable RIGHT NOW. The engine's admission
        gate consults this and defers (push_front) on False instead
        of letting the join raise."""
        return (name in self._rows or bool(self._free)
                or bool(self._lru))

    def acquire(self, name):
        """Pin a bank row for one slot serving `name` and return the
        row id. Hot adapters hit the cache; a miss loads into a free
        row (or evicts the LRU zero-reference adapter for its row).
        Raises KeyError for unregistered names and OutOfAdapters when
        every row is pinned."""
        if name is None:
            return 0
        if name not in self._registry:
            raise KeyError(f"adapter {name!r} is not registered "
                           f"(tenants: {self.tenants()})")
        row = self._rows.get(name)
        if row is not None:
            self.hits += 1
            if self._metrics is not None:
                self._metrics.record_adapter_acquire(True)
            self._lru.pop(row, None)
            self.refcount[row] += 1
            return row
        self.misses += 1
        if self._metrics is not None:
            self._metrics.record_adapter_acquire(False)
        if self._free:
            row = self._free.pop()
        elif self._lru:
            row, _ = self._lru.popitem(last=False)
            old = self._row_name.pop(row)
            del self._rows[old]
            self.evictions += 1
            if self._metrics is not None:
                self._metrics.record_adapter_eviction()
        else:
            raise OutOfAdapters(
                f"every adapter row is pinned by a live slot "
                f"({self.capacity - 1} rows, base excluded)")
        try:
            self._load(row, name)
        except Exception:
            # the row never became visible: back to the free list
            self._free.append(row)
            raise
        self._rows[name] = row
        self._row_name[row] = name
        self.refcount[row] = 1
        return row

    def _load(self, row, name):
        """The device write: every target's bank row set from the
        registry. The fault point fires FIRST, so an injected failure
        leaves the banks untouched (functional updates commit only on
        full success)."""
        _PT_ADAPTER_LOAD()
        newA, newB = [], []
        for i, (wa, wb) in enumerate(self._registry[name]):
            newA.append(self._A[i].at[row].set(wa))
            newB.append(self._B[i].at[row].set(wb))
        self._A, self._B = newA, newB
        self.loads += 1
        self.version += 1
        if self._metrics is not None:
            self._metrics.record_adapter_load()

    def release(self, row):
        """Unpin one slot's reference. A row reaching zero references
        stays hot (LRU-evictable) — the next acquire of the same
        tenant is a free cache hit."""
        row = int(row)
        if row == 0:
            return
        if self.refcount[row] <= 0:
            raise RuntimeError(f"release on unpinned adapter row {row}")
        self.refcount[row] -= 1
        if self.refcount[row] == 0:
            self._lru[row] = True

    # ---- the device-side banks the programs take ----
    def banks(self):
        """[(A, B)] per target — the traced inputs every adapter-
        carrying program receives. A fresh list each call (the arrays
        are immutables; hot-loads swap them)."""
        return list(zip(self._A, self._B))

    def bytes(self):
        """Logical device bytes of the stacked banks (the HBM ledger's
        adapter component): capacity * (d_in + d_out) * r * 4 summed
        over targets — exactly the analytic footprint."""
        return sum(int(a.size) * 4 + int(b.size) * 4
                   for a, b in zip(self._A, self._B))

    def name_of(self, row):
        """Tenant name for a bank row (None = base)."""
        return self._row_name.get(int(row))

    @property
    def hit_rate(self):
        n = self.hits + self.misses
        return (self.hits / n) if n else 0.0

    def check(self):
        """Invariants (the leak checks pivot on this, like
        PageAllocator.check): free rows, hot zero-ref rows, and
        pinned rows partition 1..capacity-1 exactly; refcounts are
        never negative; every hot name maps a consistent row."""
        free = set(self._free)
        if len(free) != len(self._free):
            raise AssertionError("duplicate adapter rows on the free "
                                 "list")
        hot = set(self._rows.values())
        if free & hot:
            raise AssertionError(f"adapter rows both free and hot: "
                                 f"{sorted(free & hot)}")
        if free | hot != set(range(1, self.capacity)):
            raise AssertionError(
                "leaked adapter rows: "
                f"{sorted(set(range(1, self.capacity)) - free - hot)}")
        if (self.refcount < 0).any():
            raise AssertionError("negative adapter refcount")
        for row in free:
            if self.refcount[row] != 0:
                raise AssertionError(f"free adapter row {row} holds "
                                     f"references")
        for name, row in self._rows.items():
            if self._row_name.get(row) != name:
                raise AssertionError(f"row map out of sync at {row}")
            if self.refcount[row] == 0 and row not in self._lru:
                raise AssertionError(f"zero-ref hot row {row} not "
                                     f"LRU-evictable")
        return True
