"""Thread-based serving frontend: submit(prompt) -> future, streaming.

The engine is single-threaded by design (all device work happens on one
thread); the server wraps it in an always-on loop thread and exposes a
thread-safe `submit` to any number of caller threads. Tokens stream per
iteration through the request's `stream_cb`; the final result is a
`concurrent.futures`-style future on the returned `Request`.

Lifecycle: `shutdown(drain=True)` closes admission and lets everything
already accepted run to completion (graceful drain); `drain=False`
aborts in-flight work at the next iteration boundary, delivering
partial tokens with finish_reason "shutdown". Works under
JAX_PLATFORMS=cpu — nothing here assumes an accelerator."""
from __future__ import annotations

import threading
import time

from ..profiler import trace as _trace
from .scheduler import Request, Scheduler

__all__ = ["ServingServer", "ServerCrashed"]


class ServerCrashed(RuntimeError):
    """The serving loop died (or refused to stop in time). Every
    outstanding future has been failed with this as the cause; further
    `submit()` calls raise it immediately."""


class ServingServer:
    """Always-on generation frontend over a serving engine.

        server = ServingServer(engine, max_queue=64)
        req = server.submit(prompt, memory=mem, max_new_tokens=32,
                            timeout=2.0, stream_cb=on_token)
        result = req.result()          # RequestResult(tokens, ...)
        server.shutdown(drain=True)

    `submit` raises `QueueFull` past the queue's high-water mark
    (backpressure) and ValueError for requests the pool can never
    serve (admission pre-check)."""

    def __init__(self, engine, *, max_queue=64, clock=None,
                 idle_wait_s=0.005, start=True, scheduler=None):
        self.engine = engine
        if clock is None:
            clock = engine.clock
        self.clock = clock
        # a caller-built scheduler (e.g. ShapingScheduler with SLO
        # classes / tenant weights) rides the same loop; default FIFO
        self.scheduler = scheduler if scheduler is not None else \
            Scheduler(max_queue=max_queue, clock=clock)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._drained = threading.Event()
        self._dead = False
        self._crash_cause = None
        self._idle_wait_s = float(idle_wait_s)
        self._thread = threading.Thread(
            target=self._loop, name="paddle-tpu-serving", daemon=True)
        self._started = False
        if start:
            self.start()

    # ------------------------------------------------------------------
    def start(self):
        if not self._started:
            self._started = True
            self._thread.start()
        return self

    def submit(self, prompt, memory=None, *, max_new_tokens=32,
               eos_id=1, deadline=None, timeout=None, stream_cb=None,
               spec=True, adapter=None, slo=None):
        """Enqueue one generation request; returns the `Request` whose
        `.result()` blocks for a RequestResult and whose `.cancel()`
        withdraws it. `timeout` (seconds from now) is sugar for an
        absolute `deadline` on the engine clock. `adapter` names the
        registered tenant adapter to decode under (None = base model;
        needs an engine with an AdapterPool). `slo` is the request's
        SLO class (an `SLOClass` or a class name a `ShapingScheduler`
        resolves at submit; ignored by the FIFO scheduler). Raises
        QueueFull under backpressure, RuntimeError after shutdown/drain
        began, and ValueError for unservable requests."""
        if self._dead:
            raise ServerCrashed(
                f"server is dead ({self._crash_cause!r}); restart it")
        if timeout is not None:
            deadline = self.clock() + float(timeout)
        r = Request(prompt, memory, max_new_tokens=max_new_tokens,
                    eos_id=eos_id, deadline=deadline,
                    stream_cb=stream_cb, spec=spec, adapter=adapter,
                    slo=slo)
        self.engine.admit_check(r)   # fail fast, before queueing
        try:
            self.scheduler.submit(r)
        except Exception as e:
            self.engine.metrics.record_reject()
            self.engine._cbs.emit("on_reject", r, type(e).__name__)
            raise
        self.engine.metrics.record_submit()
        self.engine._cbs.emit("on_submit", r)
        self._wake.set()
        return r

    def metrics_snapshot(self):
        return self.engine.metrics.snapshot()

    # ------------------------------------------------------------------
    def _idle(self):
        return (self.scheduler.depth() == 0 and
                self.engine.occupancy() == 0)

    def _loop(self):
        try:
            while True:
                if self._stop.is_set():
                    break
                progress = self.engine.run_iteration(self.scheduler)
                if self.scheduler.draining and self._idle():
                    break   # graceful drain complete
                if not progress:
                    self._wake.wait(self._idle_wait_s)
                    self._wake.clear()
        except BaseException as e:
            # the engine isolates per-request failures; anything that
            # still escapes is a loop-level crash — fail every future
            # rather than hanging their callers
            self._declare_dead(e)
        finally:
            self._drained.set()

    def _declare_dead(self, cause):
        """Mark the server dead: close admission, fail every queued and
        in-flight future with a ServerCrashed cause, make subsequent
        submit() raise immediately. Engine state is left untouched — a
        hung loop thread may still own it."""
        self._dead = True
        self._crash_cause = cause
        self._stop.set()
        self.scheduler.drain()
        self.engine.metrics.record_error("server_crash", cause)
        if _trace._SESSION is not None:
            _trace._SESSION.instant(
                "server_crash", cat="engine",
                attrs={"cause": type(cause).__name__})
        exc = ServerCrashed(f"serving loop crashed: {cause!r}")
        exc.__cause__ = cause if isinstance(cause, BaseException) \
            else None
        now = self.clock()
        doomed = self.scheduler.pop_all() + \
            [r for r in self.engine.slots if r is not None]
        for r in doomed:
            r.fail(exc, now)   # idempotent vs a racing finish()
            self.engine.metrics.record_finish("error", len(r.tokens))
            self.engine._cbs.emit("on_finish", r)

    # ------------------------------------------------------------------
    def shutdown(self, drain=True, timeout=None):
        """Stop serving. drain=True: close admission, run accepted work
        to completion, then stop (graceful). drain=False: stop at the
        next iteration boundary, finalizing queued AND in-flight
        requests with finish_reason "shutdown" (partial tokens
        delivered)."""
        if not self._started or self._dead:
            return
        if drain:
            self.scheduler.drain()
        else:
            self._stop.set()
        self._wake.set()
        self._thread.join(timeout)
        if self._thread.is_alive():
            # the loop is wedged: declare the server dead so no future
            # ever hangs — queued + in-flight futures fail with a
            # ServerCrashed cause and submit() rejects from now on
            self._declare_dead(
                TimeoutError(f"serving loop did not stop within "
                             f"{timeout}s"))
            raise TimeoutError(
                "serving loop did not stop in time; server marked "
                "dead, outstanding futures failed with ServerCrashed")
        if not drain:
            now = self.clock()
            self.scheduler.drain()
            for r in self.scheduler.abort_queued("shutdown", now):
                self.engine.metrics.record_finish(r.finish_reason,
                                                  len(r.tokens))
                self.engine._cbs.emit("on_finish", r)
            self.engine.abort_active("shutdown", now)
        self._started = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown(drain=exc == (None, None, None))
        return False
