"""Traffic shaping for the serving runtime: SLO classes, weighted fair
queueing over tenants, and fairness-aware preemption.

The bounded FIFO (`scheduler.Scheduler`) admits in arrival order — fine
for one traffic class, pathological for mixed traffic: one batch tenant
with long prompts starves every interactive request behind it, and the
PR-15 tenancy metrics can only WATCH the unfairness happen. The
`ShapingScheduler` is the control plane over the primitives the serving
stack already has:

  * **SLO classes** — every request carries an `SLOClass` (interactive
    vs batch by default) with TTFT/TPOT targets. Admission is ordered
    by (class rank, TTFT deadline): an interactive request never waits
    behind queued batch work, and within a class the request closest
    to missing its target goes first.
  * **weighted fair queueing** — across tenants (adapter identities),
    a classic virtual-time WFQ: each pop charges the tenant
    `cost / weight` of virtual time (cost = prompt + max_new tokens,
    the slot-time the request will occupy), and the tenant whose
    backlog has the smallest finish tag is served next. Per-tenant
    lag (finish tag − pool virtual time) is published into the
    ServingMetrics "slo" section every iteration — the enforcement
    counterpart of the `tenancy.fairness` Jain gauge.
  * **preemption** — when the pool is full and the queue head outranks
    a running preemptible slot, `pick_preempt_victim` names the victim;
    the engine evicts it TO THE PREFIX CACHE (pages + prefix keys
    survive), so resume is a cheap whole-hit attach, not a re-prefill.
    `max_preemptions` bounds per-request churn.
  * **admission gating** — batch-class admission closes while the HBM
    ledger sits above its watermark (`metrics.watermark_exceeded()`)
    or goodput degrades below `min_goodput`: under memory pressure the
    pool finishes what it has instead of thrashing preemptions.

The engine discovers the shaping hooks by duck typing
(`pick_preempt_victim` / `requeue_preempted` / `wfq_lag_by_tenant`);
the plain `Scheduler` has none of them, so a FIFO-driven engine runs
exactly the pre-shaping code path — the degenerate single-class
config. Chunked prefill (the `prefill_chunk` engine knob) is
independent of the scheduler choice; together they bound both halves
of interactive latency: chunking bounds decode-step inter-arrival,
shaping bounds time-to-slot."""
from __future__ import annotations

import collections
import threading
import time

from ..profiler import trace as _trace
from . import tracing as _rt
from .scheduler import QueueFull, _PT_ADMIT

__all__ = ["SLOClass", "INTERACTIVE", "BATCH", "ShapingScheduler"]


class SLOClass:
    """A traffic class and its latency contract. `rank` orders classes
    (lower = more latency-critical; admission and preemption both
    honor it); `preemptible` marks classes whose running slots may be
    evicted to the prefix cache for higher-ranked work."""

    __slots__ = ("name", "ttft_target_s", "tpot_target_s",
                 "preemptible", "rank")

    def __init__(self, name, *, ttft_target_s, tpot_target_s,
                 preemptible=False, rank=0):
        self.name = str(name)
        self.ttft_target_s = float(ttft_target_s)
        self.tpot_target_s = float(tpot_target_s)
        self.preemptible = bool(preemptible)
        self.rank = int(rank)

    def __repr__(self):
        return (f"SLOClass({self.name!r}, ttft={self.ttft_target_s}s, "
                f"tpot={self.tpot_target_s}s, rank={self.rank}, "
                f"preemptible={self.preemptible})")


#: the default two-class config: latency-bound chat traffic vs
#: throughput-bound batch jobs (summaries, evals, backfills)
INTERACTIVE = SLOClass("interactive", ttft_target_s=0.5,
                       tpot_target_s=0.1, rank=0)
BATCH = SLOClass("batch", ttft_target_s=30.0, tpot_target_s=1.0,
                 preemptible=True, rank=1)


def _tenant(r):
    """Fairness key: the adapter identity (matches the engine's
    tenancy accounting — base-model traffic is one tenant)."""
    return r.adapter if r.adapter is not None else "base"


def _cost(r):
    """WFQ service cost: the slot-time the request will occupy, in
    token units (prompt prefill + decode budget)."""
    return float(int(r.prompt.shape[0]) + r.max_new_tokens)


class ShapingScheduler:
    """Drop-in replacement for `Scheduler` (same surface: submit /
    pop_ready / push_front / depth / drain / pop_all / abort_queued)
    plus the shaping hooks the engine duck-types. Thread-safe."""

    def __init__(self, max_queue=64, clock=time.monotonic, *,
                 tenant_weights=None, default_weight=1.0,
                 classes=None, default_class=BATCH,
                 max_preemptions=2, min_goodput=0.0, metrics=None):
        self.max_queue = int(max_queue)
        self.clock = clock
        if classes is None:
            classes = (INTERACTIVE, BATCH)
        self.classes = {c.name: c for c in classes}
        self.default_class = (self.classes[default_class]
                              if isinstance(default_class, str)
                              else default_class)
        self.tenant_weights = dict(tenant_weights or {})
        self.default_weight = float(default_weight)
        self.max_preemptions = int(max_preemptions)
        self.min_goodput = float(min_goodput)
        self.metrics = metrics
        self._lock = threading.Lock()
        self._draining = False
        self._seq = 0
        # per-tenant backlogs, each kept sorted by the within-tenant
        # priority key; _front is the engine's return lane (page
        # backpressure deferrals) — served first, never re-charged
        self._q = {}                       # tenant -> [request, ...]
        self._front = collections.deque()
        # WFQ virtual-time state: pool virtual time advances to each
        # served request's start tag; a tenant's finish tag trails it
        # by exactly the service its backlog has been charged
        self._vt = 0.0
        self._ft = {}                      # tenant -> finish tag

    # ---- class / priority plumbing ----
    def _resolve_class(self, r):
        slo = r.slo
        if slo is None:
            return self.default_class
        if isinstance(slo, str):
            try:
                return self.classes[slo]
            except KeyError:
                raise ValueError(
                    f"unknown SLO class {slo!r}; registered: "
                    f"{sorted(self.classes)}") from None
        return slo

    def _prio(self, r):
        """Within-tenant order: class rank, then the TTFT deadline
        (submit + target — the request closest to missing its target
        first), then arrival."""
        return (r.slo.rank, r.submitted_at + r.slo.ttft_target_s,
                r._shape_seq)

    def _weight(self, tenant):
        return float(self.tenant_weights.get(tenant,
                                             self.default_weight))

    def _goodput_ratio(self):
        m = self.metrics
        if m is None:
            return 1.0
        wasted_drafts = m.drafts_proposed - m.drafts_accepted
        denom = (m.useful_tokens + m.wasted_tokens + m.warmup_tokens +
                 m.retry_tokens + wasted_drafts)
        return m.useful_tokens / denom if denom else 1.0

    def _gated(self, cls):
        """Admission gate for low-priority classes: while the HBM
        ledger is above its watermark or goodput has degraded, batch
        admission closes (interactive traffic keeps flowing — it is
        what preemption protects)."""
        if cls.rank == 0 or self.metrics is None:
            return False
        if self.metrics.watermark_exceeded():
            return True
        return (self.min_goodput > 0.0 and
                self._goodput_ratio() < self.min_goodput)

    # ---- Scheduler surface ----
    def submit(self, request):
        """Enqueue under the request's SLO class (resolving string
        names), or raise QueueFull — at the high-water mark like the
        FIFO, and additionally for gated batch-class admission."""
        now = self.clock()
        _PT_ADMIT()   # fault point: an injected raise = admission lost
        cls = self._resolve_class(request)
        with self._lock:
            if self._draining:
                raise RuntimeError("scheduler is draining: admission "
                                   "closed")
            if self._depth_locked() >= self.max_queue:
                raise QueueFull(
                    f"request queue at high-water mark "
                    f"({self.max_queue}); shed load or retry")
            if self._gated(cls):
                raise QueueFull(
                    f"admission gated for class {cls.name!r}: pool "
                    f"under memory/goodput pressure; retry later")
            request.slo = cls
            request.submitted_at = now
            request._shape_seq = self._seq
            self._seq += 1
            self._insert(request)
        if _trace._SESSION is not None:
            _rt.on_submit(request)
        return request

    # caller (submit) holds the lock
    def _insert(self, r):       # analysis: single-threaded
        q = self._q.setdefault(_tenant(r), [])
        key = self._prio(r)
        lo, hi = 0, len(q)
        while lo < hi:            # insertion sort: queues are short
            mid = (lo + hi) // 2
            if self._prio(q[mid]) <= key:
                lo = mid + 1
            else:
                hi = mid
        q.insert(lo, r)

    def _select_tenant(self):
        """The tenant to serve next: strict class priority first (the
        best head rank present), then the smallest WFQ finish tag the
        head would be charged, then the earlier deadline."""
        best, best_key = None, None
        for t, q in self._q.items():
            if not q:
                continue
            h = q[0]
            tag = (max(self._vt, self._ft.get(t, 0.0)) +
                   _cost(h) / self._weight(t))
            key = (h.slo.rank, tag,
                   h.submitted_at + h.slo.ttft_target_s, h._shape_seq)
            if best_key is None or key < best_key:
                best, best_key = t, key
        return best

    # callers (pop_ready / drain) hold the lock — the _locked suffix
    # is the contract
    def _pop_locked(self):      # analysis: single-threaded
        """Next request under the lock: the return lane first (no WFQ
        charge — it was charged on its first pop), then the WFQ pick,
        charging its tenant's virtual time."""
        if self._front:
            return self._front.popleft()
        t = self._select_tenant()
        if t is None:
            return None
        r = self._q[t].pop(0)
        if not self._q[t]:
            del self._q[t]
        start = max(self._vt, self._ft.get(t, 0.0))
        self._ft[t] = start + _cost(r) / self._weight(t)
        self._vt = start
        return r

    def pop_ready(self, now=None, on_dead=None):
        """Next admissible request by shaping order, finalizing queued
        requests that died on the way (cancel/deadline — the FIFO's
        screening contract). Returns None when idle."""
        if now is None:
            now = self.clock()
        while True:
            with self._lock:
                r = self._pop_locked()
            if r is None:
                return None
            if r.cancelled or r.expired(now):
                r.finish("cancelled" if r.cancelled else "timeout", now)
                if on_dead is not None:
                    on_dead(r)
                continue
            if r._trace is not None:
                _rt.on_queue_exit(r)
            return r

    def push_front(self, request):
        """Return an admitted request to the head (resource
        backpressure deferral): served before any queued work, no
        second WFQ charge. Bypasses the high-water mark on purpose."""
        if request._trace is not None:
            _rt.on_requeue(request)
        with self._lock:
            self._front.appendleft(request)

    def depth(self):
        with self._lock:
            return self._depth_locked()

    def _depth_locked(self):
        return len(self._front) + sum(len(q) for q in self._q.values())

    # ---- drain / teardown (FIFO contract) ----
    def drain(self):
        with self._lock:
            self._draining = True

    @property
    def draining(self):
        return self._draining

    def pop_all(self):
        with self._lock:
            out = list(self._front)
            for t in sorted(self._q):
                out.extend(self._q[t])
            self._front.clear()
            self._q.clear()
        return out

    def abort_queued(self, reason, now=None):
        if now is None:
            now = self.clock()
        out = []
        while True:
            with self._lock:
                r = self._pop_locked()
            if r is None:
                return out
            r.finish(reason if not r.cancelled else "cancelled", now)
            out.append(r)

    # ---- shaping hooks (the engine duck-types these) ----
    def _peek(self):
        with self._lock:
            if self._front:
                return self._front[0]
            t = self._select_tenant()
            return None if t is None else self._q[t][0]

    def pick_preempt_victim(self, engine, now):
        """The pool is full and the engine asks whom to evict. A slot
        qualifies when the waiting head STRICTLY outranks it, its class
        is preemptible, it has churn budget left, and the engine can
        checkpoint it (`can_preempt`: paged pool + prefix cache + at
        least one delivered token). Among candidates, the one with the
        fewest delivered tokens loses — the cheapest replay."""
        head = self._peek()
        if head is None or head.slo is None:
            return None
        best, best_key = None, None
        for s, r in enumerate(engine.slots):
            if r is None:
                continue
            slo = getattr(r, "slo", None)
            if (slo is None or not slo.preemptible or
                    head.slo.rank >= slo.rank or
                    r._preemptions >= self.max_preemptions or
                    not engine.can_preempt(s)):
                continue
            key = (-slo.rank, len(r.tokens), r.id)
            if best_key is None or key < best_key:
                best, best_key = s, key
        return best

    def requeue_preempted(self, r):
        """A preempted request re-enters the backlog at its class
        priority (behind the interactive work it yielded to). The next
        pop charges its tenant again — re-admission occupies slot time
        twice, so WFQ accounts it twice."""
        with self._lock:
            self._insert(r)

    def wfq_lag_by_tenant(self):
        """Per-tenant virtual-time lag (finish tag − pool virtual
        time) for tenants with backlog or unspent charge: 0 means the
        tenant is keeping pace with its weight; a large lag means its
        demand exceeds its share. Published into the metrics "slo"
        section each iteration."""
        with self._lock:
            out = {}
            for t, ft in self._ft.items():
                lag = ft - self._vt
                if lag > 1e-9 or t in self._q:
                    out[t] = max(0.0, lag)
            return out
