"""Continuous-batching generation engines: a fixed slot pool over the
static KV cache.

`DecodeEngine` (text/generation.py) made whole-batch generation one
compiled program, but a batch is an all-or-nothing unit: a straggler
request pins every finished row and new arrivals wait for a full
drain. The serving engines here do Orca/vLLM-style *iteration-level*
batching instead — the scheduling unit is ONE decode step:

  * the pool owns S cache slots: per-layer `StaticKVCache` buffers of
    shape [S, H, max_len, D] with PER-ROW write indices, plus pooled
    cross-attention K/V, pad-bias rows, and memory rows;
  * the decode step is ONE jitted call of static shape [S, ...] with a
    per-slot active mask — compiled once per pool config, regardless of
    which requests occupy which slots (`trace_counts` proves it);
  * a finished/evicted slot is refilled by prefilling the new prompt
    (batch-1, prompt bucketed to a power of two) through the regular
    flash-capable path and SPLICING its K/V rows + write index into the
    live pool with `dynamic_update_slice` — the slot id and prompt
    length are traced scalars, so slot join never retraces either
    (one compile per prompt bucket).

Numerics contract: every slot reproduces `generate_eager` for its own
prompt bit-for-bit at the token level — all per-slot ops are row-wise,
so co-resident requests can never perturb each other's output; the
soak test in tests/test_serving.py holds this across joins, evictions,
and timeouts.

`ArtifactServingEngine` applies the same slot lifecycle to inference
Program artifacts (ids -> logits, no threadable cache): each iteration
re-runs every active slot's bucketed prefix, batched across slots —
the `Predictor.generate` serving mode behind
`Config.enable_serving_engine()`.
"""
from __future__ import annotations

import time

import numpy as np

from ..core.bucketing import bucket_size, pad_prompt_row, pad_token_rows
from ..profiler import costs as _costs
from ..profiler import trace as _trace
from ..testing import faults
from . import tracing as _rt
from .paging import (OutOfPages, PageAllocator, RadixPrefixCache,
                     pages_for)
from .metrics import CallbackList, ServingMetrics

__all__ = ["ServingEngine", "PagedServingEngine",
           "ArtifactServingEngine", "WatchdogTimeout"]

#: fault points instrumenting the slot lifecycle (armed only in tests /
#: chaos runs; a disarmed hit is one boolean read)
_PT_SLOT_JOIN = faults.point("serving.slot_join")
_PT_PREFILL = faults.point("serving.prefill")
_PT_PATTACH = faults.point("serving.pattach")
_PT_DECODE = faults.point("serving.decode_step")
_PT_CHUNK = faults.point("serving.prefill_chunk")
_PT_PREEMPT = faults.point("serving.preempt")


def _reject_sharded_params(params, engine_name):
    """Fail FAST (and loudly) when a single-chip engine is handed
    mesh-sharded weights. A weight committed across several devices
    would make the jitted join/step programs run SPMD over the whole
    mesh with a single-device pool layout — at best slow, at worst a
    silently different reduction order than the engine's bit-match
    contract. The sharded pool engine exists for exactly this."""
    for name, v in params.items():
        sh = getattr(v, "sharding", None)
        if sh is None:
            continue
        try:
            multi = len(sh.device_set) > 1
            replicated = bool(getattr(sh, "is_fully_replicated", False))
        except Exception:
            continue
        if multi and not replicated:
            raise ValueError(
                f"{engine_name} was handed mesh-sharded weights "
                f"(param {name!r} is laid out across "
                f"{len(sh.device_set)} devices: {sh}); the single-chip "
                f"slot pool cannot serve them. Use "
                f"paddle_tpu.serving.sharded.ShardedServingEngine, "
                f"which lays weights out tp/fsdp and shards the slot "
                f"pool data-parallel over the same mesh.")


def _tree_bytes(x):
    """Logical byte footprint of a pytree of arrays: every leaf counts
    at size x itemsize (aliased leaves count each — the ledger reports
    committed CAPACITY, which is what the two pool k/v views occupy
    once they diverge). Pure metadata walk: never syncs the device."""
    if x is None:
        return 0
    if isinstance(x, dict):
        return sum(_tree_bytes(v) for v in x.values())
    if isinstance(x, (list, tuple)):   # incl. NamedTuple caches
        return sum(_tree_bytes(v) for v in x)
    x = getattr(x, "_data", x)         # Tensor wrapper -> array
    size = getattr(x, "size", None)
    dt = getattr(x, "dtype", None)
    if size is None or dt is None:
        return 0
    itemsize = getattr(dt, "itemsize", None)
    if itemsize is None:
        itemsize = np.dtype(str(dt)).itemsize
    return int(size) * int(itemsize)


class WatchdogTimeout(TimeoutError):
    """An engine operation completed but blew its `watchdog_s` wall
    budget — treated as a failure (retried with backoff, then failed
    cleanly) so one slow/hung compile can't wedge the pool silently."""


class PoolCarryLost(RuntimeError):
    """The donated pool-state carry was consumed by a dispatch that
    died without assigning a replacement: no valid buffer survives to
    retry on. Raised instead of dispatching dead buffers; the caller
    escalates to the all-or-nothing recovery (_fail_active ->
    _reset_pool) so the pool rebuilds and keeps serving."""


class _CachedProgram:
    """A program deserialized from the persistent AOT cache, with a
    rebuild escape hatch: a stale-but-CRC-valid entry whose argument
    layout no longer matches the live pool raises TypeError at the
    AOT arg check — rebuild the jitted program in place (one compile,
    recorded as an `aot_cache` error) instead of crashing the serve.
    The happy path is one try frame around the raw executable call."""

    __slots__ = ("_engine", "_key", "_build", "compiled", "_fell_back")

    def __init__(self, engine, key, build, compiled):
        self._engine = engine
        self._key = key
        self._build = build
        self.compiled = compiled
        self._fell_back = False

    def __call__(self, *args):
        if not self._fell_back:
            try:
                return self.compiled(*args)
            except TypeError as e:
                self._fell_back = True
                self._engine.metrics.record_error("aot_cache", e)
                self.compiled = self._build()
        return self.compiled(*args)


class _EngineBase:
    """Slot lifecycle + per-iteration orchestration shared by the
    model-backed and artifact-backed engines. Subclasses implement
    `_join(slot, request) -> first_token | None`, `_decode_step(active)
    -> tokens [S]`, and optionally `_evict(slot)` / `admit_check`.

    One `run_iteration(scheduler)` is the continuous-batching unit:
    (1) fault harvest — cancelled / past-deadline requests leave their
    slots with partial output; (2) admission — up to
    `max_joins_per_iter` queued requests prefill into free slots (the
    prefill/decode interleave policy: bounding joins per iteration
    bounds the decode stall co-resident requests see); (3) one batched
    decode step over the active mask. NOT thread-safe — drive it from
    one thread (the `ServingServer` loop or a synchronous drain)."""

    #: the multi-tenant AdapterPool (serving/adapters.py); model-backed
    #: engines set it from the `adapters=` knob, the Artifact engine
    #: never does — base-class code guards on None
    _apool = None

    def __init__(self, num_slots, *, max_joins_per_iter=2, metrics=None,
                 callbacks=(), clock=time.monotonic, max_attempts=3,
                 backoff_base_s=0.01, backoff_cap_s=0.5,
                 watchdog_s=None, sleep=time.sleep,
                 hbm_budget_bytes=None, hbm_watermark=0.9):
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        self.num_slots = int(num_slots)
        self.max_joins_per_iter = int(max_joins_per_iter)
        self.clock = clock
        self.metrics = metrics if metrics is not None else \
            ServingMetrics(clock=clock)
        self._cbs = CallbackList(
            callbacks,
            on_error=lambda hook, e: self.metrics.record_error(
                f"callback.{hook}", e))
        self.slots = [None] * self.num_slots   # Request | None
        # slots whose request holds the slot but whose pool state is
        # not spliced yet (a disaggregated prefill still in flight on
        # the prefill mesh slice): occupied for admission, EXCLUDED
        # from the decode-step active mask until _poll_pending splices
        self._pending = set()
        self._last_step_done = None   # decode-step inter-arrival clock
        # trace_counts is observable: the retrace sentinel / tracer see
        # every increment (= one jax trace = one compile) as it happens
        self.trace_counts = _trace.ObservedCounter(
            owner=type(self).__name__)
        # failure-isolation knobs: every join/decode runs under a
        # capped-exponential retry loop and an optional wall watchdog
        self.max_attempts = max(1, int(max_attempts))
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.watchdog_s = watchdog_s
        self._sleep = sleep
        # HBM budget for the live memory ledger's watermark (warn
        # BEFORE OutOfPages/OOM); model-backed subclasses register
        # their ledger provider with the metrics sink
        self.hbm_budget_bytes = hbm_budget_bytes
        if hbm_budget_bytes is not None:
            self.metrics.budget_bytes = int(hbm_budget_bytes)
            self.metrics.watermark_frac = float(hbm_watermark)
        self._weights_bytes = None   # cached by memory_ledger()
        self._step_cost_cache = None  # (book, key, ProgramCost)
        # token-0 delivery policy: joins return the TRACED first-token
        # scalar and run_iteration resolves the whole admission
        # round's tokens after the last join dispatched — k joins pay
        # ~1 host sync instead of k blocking int(tok0) calls on the
        # submit path. sync_tok0=True restores the per-join block (the
        # bench's before/after host-time check flips it).
        self.sync_tok0 = False

    # ---- subclass surface ----
    def admit_check(self, request):
        """Raise ValueError for requests this pool can never serve."""

    def _join(self, slot, request):
        raise NotImplementedError

    def _decode_step(self, active):
        raise NotImplementedError

    def _evict(self, slot):
        """Host-side bookkeeping on slot release (device state needs
        none: the active mask hides the slot and the next join splices
        over it)."""

    def _join_fallback(self, request, exc):
        """Last-resort degradation after a join failed all attempts.
        Return True when the request was served another way (its future
        resolved); False to fail the future with `exc`."""
        return False

    def _admission_gate(self, request):
        """Resource headroom check beyond a free slot. Returning False
        pushes the request back to the queue HEAD (it stays admitted,
        just deferred) and ends this iteration's joins — the paged
        engine's OutOfPages backpressure path."""
        return True

    def _iteration_gauges(self):
        """Extra per-iteration gauges for metrics.record_iteration
        (the paged engine reports page occupancy here)."""
        return None

    def _reset_pool(self):
        """Rebuild device pool state after a decode-step failure (all
        in-flight requests have been evicted)."""

    def _poll_pending(self, now):
        """Advance asynchronous joins (the sharded engine's
        disaggregated prefill): splice any prefill whose arrays are
        ready into the pool and activate the slot. Returns True when
        any slot was activated. Default engines join synchronously —
        no-op."""
        return False

    def _choose_slot(self, free):
        """Pick the slot a new request joins into. The sharded engine
        overrides this to balance occupancy across the dp shards of
        the slot axis."""
        return free[0]

    def _advance_chunks(self, now):
        """Run ONE prefill chunk for every slot mid chunked-prefill.
        Called between admission and the decode step — a chunk-joined
        slot's first chunk must dispatch BEFORE any decode step, so the
        decode step's masked k/v writes (which land at the slot's pool
        index) can never clobber prompt positions the chunk family owns.
        Returns True when any chunk ran. Default: no chunking."""
        return False

    def preempt_slot(self, s, now):
        """Evict the RUNNING request in slot `s` to the prefix cache so
        a later re-admission resumes via a cheap attach instead of a
        re-prefill. Returns the preempted Request (re-queueable), or
        None when this engine has no preemption mechanism (dense pools:
        nothing to park the KV in). Default: no mechanism."""
        return None

    def can_preempt(self, s):
        """True when slot `s` currently holds a preemptible-in-principle
        request (engine-side mechanics only — class policy lives in the
        shaper)."""
        return False

    def _preempt_for(self, scheduler, now):
        """Admission found no free slot: ask the scheduler (duck-typed
        — only the ShapingScheduler implements the hook) for a victim
        slot, evict it to the prefix cache, and requeue the preempted
        request. Returns the freed slot index or None."""
        pick = getattr(scheduler, "pick_preempt_victim", None)
        if pick is None:
            return None
        s = pick(self, now)
        if s is None:
            return None
        try:
            r = self.preempt_slot(s, now)
        except Exception as e:
            # the preempt fault point fires BEFORE any mutation, so a
            # failed preemption leaves slot, pages, and queue intact —
            # record it and let this iteration's admission just stop
            self.metrics.record_error("preempt", e)
            return None
        if r is None:
            return None
        requeue = getattr(scheduler, "requeue_preempted",
                          scheduler.push_front)
        requeue(r)
        return s

    #: jit-cache key KINDS whose pool-state carry argument is donated
    #: into the compiled program (position of the state arg in the
    #: body signature). Donation lets XLA alias the KV pool in place
    #: instead of copying it every dispatch — on the decode hot path
    #: that copy is the whole cache, and on the JOIN family it is the
    #: whole-pool memcpy that masked the prefix cache's TTFT win (a
    #: mid-page radix hit paid it twice: cow + pattach). The whole
    #: program matrix donates now; per-request isolation survives via
    #: a generation-checked alias instead of a copy:
    #:
    #:  - every engine-injected fault point (_PT_SLOT_JOIN/_PT_PREFILL/
    #:    _PT_PATTACH/_PT_SPLICE) fires host-side BEFORE dispatch, so a
    #:    failed attempt's carry is the untouched pre-join buffer and
    #:    the guarded retry re-runs on it bit-identically;
    #:  - an attempt that EXECUTED before failing (watchdog overrun)
    #:    already reassigned self._state inside the op closure — join
    #:    programs write only their target slot, so the retry re-runs
    #:    slot-idempotently on the surviving carry and co-resident
    #:    slots stay bit-identical;
    #:  - the one remaining hazard — a carry consumed by donation with
    #:    no replacement assigned (a dispatch that died mid-execution)
    #:    — is detected by _carry_alive() before every attempt and in
    #:    the join/splice failure handlers, and escalates to the
    #:    existing all-or-nothing recovery (_fail_active -> _reset_pool)
    #:    instead of re-dispatching dead buffers.
    #:
    #: The static analyzer's donation audit (PTA102) reads this same
    #: declaration (one source of truth for the jit builders AND the
    #: audit); ANALYSIS_BASELINE.json carries no join-family waivers.
    _DONATED_KINDS = {"step": 2, "sstep": 2, "pstep": 2, "pverify": 2,
                      "join": 2, "pjoin": 2, "attach": 2, "cow": 0,
                      "pattach": 4, "splice": 0, "bsplice": 0,
                      "cjoin": 4, "pcjoin": 4}

    def _program(self, key, build):
        """Get-or-build a compiled program from the observed jit
        cache: a miss stores `build()`'s result and returns the
        observing wrapper, so every trace surfaces as a compile span."""
        fn = self._compiled.get(key)
        if fn is None:
            self._compiled[key] = build()
            fn = self._compiled[key]   # the observed wrapper
        return fn

    def _donate_argnums(self, key):
        """donate_argnums for the program at `key` (() = donate
        nothing). One declaration shared by the jit builders AND the
        static analyzer, so the audit can never drift from the code."""
        kind = key[0] if isinstance(key, tuple) and key else key
        pos = self._DONATED_KINDS.get(kind)
        return () if pos is None else (pos,)

    # ---- cost/memory accounting (profiler.costs) ----
    def _step_cost_key(self):
        """The jit-cache key of this engine's batched decode step (the
        identity the MFU gauges, the compile observer, and the retrace
        sentinel all share). None = no compiled step (Artifact pool)."""
        return None

    def cost_hint(self, key):
        """Analytic {flops, bytes_accessed, ...} for `key` — the
        CPU-safe fallback when XLA's cost analysis returns nothing (or
        the program compiled before accounting armed). None = no
        estimate for this key."""
        return None

    def _record_step_cost(self, dt_s):
        """Armed-only (caller guards on the costs session): one decode
        step's roofline position into the MFU/bandwidth gauges. The
        ProgramCost lookup is cached per (book, key) so a steady pool
        pays one dict hit + two reservoir adds per armed step."""
        key = self._step_cost_key()
        if key is None:
            return
        bk = _costs._BOOK
        if bk is None:
            return
        cached = self._step_cost_cache
        if cached is not None and cached[0] is bk and cached[1] == key:
            c = cached[2]
        else:
            c = _costs.cost_for(self, key)
            if c is None:
                return
            self._step_cost_cache = (bk, key, c)
        self.metrics.record_step_utilization(
            c.flops, c.bytes_accessed, dt_s, bk.spec, c.source)

    # ---- zero-warmup startup: AOT precompile + persistent cache ----
    def _startup_programs(self, prompt_buckets):
        """[(key, build, example_args)] for every compiled program
        this pool config serves with: the jit-cache key, a zero-arg
        builder returning the jitted program, and arguments shaped
        EXACTLY like the runtime calls (so an AOT lower().compile()
        yields the executable the hot path will invoke). Default: none
        (the Artifact engine's programs live in its Predictor)."""
        return []

    def _program_fingerprint(self):
        """Identity folded into every persistent-cache key so two
        engines with different models/pool configs can never collide
        in one cache directory."""
        return type(self).__name__

    def _program_cache_key(self, key):
        return f"{self._program_fingerprint()}|{key!r}"

    def _precompile_run(self, progs, cache, persist):
        """Ready every (key, build, args) program: deserialize from
        the persistent cache when possible, AOT lower+compile
        otherwise (and persist the result), and install the finished
        executable in the jit cache — the serving hot path then never
        traces. Returns the cold_start report."""
        from ..tuning.aot_cache import AotCompileCache

        t_start = time.perf_counter()
        if cache is not None and not isinstance(cache, AotCompileCache):
            cache = AotCompileCache(cache)
        err0 = (cache.stats["corrupt"] + cache.stats["stale"]) \
            if cache is not None else 0
        n_loaded = n_compiled = n_ready = n_failed = 0
        for key, build, args in progs:
            if key in self._compiled:
                n_ready += 1
                continue
            t0 = time.perf_counter()
            fn = None
            source = "cache"
            if cache is not None:
                loaded = cache.load(self._program_cache_key(key))
                if loaded is not None:
                    fn = _CachedProgram(self, key, build, loaded)
                    n_loaded += 1
            if fn is None:
                source = "compile"
                try:
                    fn = build().lower(*args).compile()
                except Exception as e:
                    # a program that cannot AOT-compile here still
                    # compiles lazily at first use — precompile must
                    # never take the pool down
                    self.metrics.record_error("precompile", e)
                    n_failed += 1
                    continue
                n_compiled += 1
                if cache is not None and persist:
                    cache.store(self._program_cache_key(key), fn)
            t1 = time.perf_counter()
            self._compiled[key] = fn
            n_ready += 1
            if _trace._SESSION is not None:
                _trace.record_precompile(self, key, t0, t1, source)
            if _costs._BOOK is not None:
                compiled = fn.compiled if isinstance(
                    fn, _CachedProgram) else fn
                _costs.capture_compiled(self, key, compiled,
                                        compile_s=t1 - t0)
        errs = ((cache.stats["corrupt"] + cache.stats["stale"])
                if cache is not None else 0) - err0
        report = {
            "time_to_ready_s": round(time.perf_counter() - t_start, 4),
            "programs": n_ready,
            "loaded_from_cache": n_loaded,
            "compiled": n_compiled,
            "cache_errors": errs,
            "build_failures": n_failed,
            "warm": int(n_compiled == 0 and n_failed == 0),
        }
        self.metrics.record_cold_start(report)
        return report

    # ---- watchdog + retry/backoff ----
    def _guarded(self, opname, fn, retry_tokens=0):
        """Run one engine op with up to `max_attempts` tries, capped
        exponential backoff between them, and a wall-clock watchdog: an
        op that returns but took > `watchdog_s` is treated as failed
        (a hung compile/dispatch that eventually unwedges must not be
        trusted to have left the iteration on schedule). The final
        failure propagates to the caller, which isolates it."""
        last = None
        for attempt in range(self.max_attempts):
            if attempt:
                self.metrics.record_retry(opname, retry_tokens)
                self._sleep(min(self.backoff_cap_s,
                                self.backoff_base_s * (2 ** (attempt - 1))))
            t0 = time.monotonic()
            try:
                out = fn()
            except Exception as e:
                last = e
                continue
            if self.watchdog_s is not None:
                dt = time.monotonic() - t0
                if dt > self.watchdog_s:
                    last = WatchdogTimeout(
                        f"{opname} took {dt:.3f}s > watchdog budget "
                        f"{self.watchdog_s}s")
                    continue
            return out
        raise last

    def _carry_alive(self):
        """True when every leaf of the device pool carry is still
        live. Donated join/step programs consume their input carry;
        normally the op closure reassigns self._state before anything
        can observe the dead buffer, but a dispatch that dies
        mid-execution leaves the consumed carry with no replacement —
        this sweep (a few hundred host-side is_deleted checks, no
        device work) is how the retry path refuses to re-dispatch
        dead buffers."""
        state = getattr(self, "_state", None)
        if state is None:
            return True
        import jax

        return not any(getattr(x, "is_deleted", lambda: False)()
                       for x in jax.tree_util.tree_leaves(state))

    def _join_attempt(self, s, r):
        _PT_SLOT_JOIN()
        if not self._carry_alive():
            raise PoolCarryLost(
                "pool carry consumed by a failed dispatch with no "
                "replacement state — refusing to retry the join on "
                "dead buffers")
        return self._join(s, r)

    def _decode_attempt(self, active):
        _PT_DECODE()
        return self._decode_step(active)

    def _fail_active(self, exc):
        """Decode-step failure that survived retries: every in-flight
        request is poisoned (the batched step is all-or-nothing), so
        evict them all with their partial tokens + the cause, rebuild
        the pool state, and keep serving — the pool itself survives."""
        now = self.clock()
        for s, r in enumerate(self.slots):
            if r is None:
                continue
            self.slots[s] = None
            self._evict(s)
            self.metrics.record_finish("error", len(r.tokens))
            self.metrics.record_eviction_on_error()
            r.finish("error", now, error=exc)
            self._cbs.emit("on_finish", r)
        self._reset_pool()

    # ---- slot lifecycle ----
    def occupancy(self):
        return sum(r is not None for r in self.slots)

    def _finish_slot(self, s, reason, now):
        r = self.slots[s]
        self.slots[s] = None
        self._evict(s)
        self.metrics.record_finish(reason, len(r.tokens))
        if reason in ("eos", "length"):
            # slo is an SLOClass once a ShapingScheduler admitted the
            # request; a string class name through the plain FIFO is
            # never resolved — no class semantics, nothing to record
            slo = getattr(r, "slo", None)
            if hasattr(slo, "ttft_target_s") \
                    and r.first_token_at is not None \
                    and r.submitted_at is not None:
                ttft = r.first_token_at - r.submitted_at
                n = len(r.tokens)
                tpot = ((now - r.first_token_at) / (n - 1)
                        if n > 1 else 0.0)
                self.metrics.record_slo_finish(
                    slo.name, ttft, tpot, slo.ttft_target_s,
                    slo.tpot_target_s)
        r.finish(reason, now)
        self._cbs.emit("on_finish", r)

    def _tenant_of(self, r):
        """Tenant label for per-tenant accounting (None = tenancy off:
        the engine carries no AdapterPool)."""
        if self._apool is None:
            return None
        return getattr(r, "adapter", None) or "base"

    def _deliver(self, r, tok, now):
        if r.state == "DONE":
            return
        rep = getattr(r, "_replay", 0)
        if rep > 0:
            # post-preemption replay: the resumed slot re-decodes
            # tokens the caller already holds (determinism makes the
            # replay bit-identical); absorb them silently — no append,
            # no stream callback, no TTFT/throughput double-count
            r._replay = rep - 1
            self.metrics.record_replay_token()
            return
        r.tokens.append(tok)
        self.metrics.record_token(self._tenant_of(r))
        if r.first_token_at is None:
            r.first_token_at = now
            if r._trace is not None:
                _rt.on_first_token(r)
            if r.submitted_at is not None:
                self.metrics.record_first_token(now - r.submitted_at)
        self._cbs.emit("on_token", r, tok)
        if r.stream_cb is not None:
            try:
                r.stream_cb(r, tok)
            except Exception as e:
                # a broken streaming callback must not stall the pool,
                # but the failure is recorded, never swallowed
                self.metrics.record_error("stream_cb", e)
        if r.eos_id is not None and tok == r.eos_id:
            self._finish_slot(r.slot, "eos", now)
        elif len(r.tokens) >= r.max_new_tokens:
            self._finish_slot(r.slot, "length", now)

    # ---- the continuous-batching iteration ----
    def run_iteration(self, scheduler):
        """One iteration: harvest faults, admit new work, decode one
        token for every active slot. Returns True when any work was
        done (False = idle: empty queue, empty pool)."""
        now = self.clock()
        progress = False
        # 1. fault harvest: cancellation + deadline eviction happen at
        # iteration boundaries — partial tokens are delivered
        for s, r in enumerate(self.slots):
            if r is None:
                continue
            if r.cancelled:
                self._finish_slot(s, "cancelled", now)
                progress = True
            elif r.expired(now):
                self._finish_slot(s, "timeout", now)
                progress = True
        # 1b. asynchronous joins: splice finished disaggregated
        # prefills into the pool (no-op for synchronous engines)
        if self._poll_pending(now):
            progress = True
        # 2. admission: refill free slots, bounded per iteration

        def _queue_death(req):   # cancelled/expired while QUEUED
            self.metrics.record_finish(req.finish_reason,
                                       len(req.tokens))
            self._cbs.emit("on_finish", req)

        joins = 0
        tok0s = []   # (request, traced token-0) resolved after the loop
        while joins < self.max_joins_per_iter:
            free = [i for i, r in enumerate(self.slots) if r is None]
            if not free:
                # fairness-aware preemption: a full pool defers to the
                # scheduler (duck-typed — only the ShapingScheduler
                # implements the hook) to evict a lower-class slot to
                # the prefix cache; resume later rides a cheap attach
                s = self._preempt_for(scheduler, now)
                if s is None:
                    break
                free = [s]
                progress = True
            r = scheduler.pop_ready(now, on_dead=_queue_death)
            if r is None:
                break
            try:
                self.admit_check(r)
            except Exception as e:
                # unservable request that bypassed the frontend check
                self.metrics.record_error("admit", e)
                r.fail(e, now)
                self.metrics.record_finish("error", len(r.tokens))
                self._cbs.emit("on_finish", r)
                continue
            if not self._admission_gate(r):
                # resource backpressure (paged: not enough free pages):
                # the request stays queued at the head, joins stop for
                # this iteration, decode keeps draining the pool
                scheduler.push_front(r)
                break
            s = self._choose_slot(free)
            r.state, r.slot = "RUNNING", s
            self.slots[s] = r
            if _trace._SESSION is not None:
                _rt.on_join_begin(r, s)
            try:
                tok = self._guarded("slot_join",
                                    lambda: self._join_attempt(s, r))
            except Exception as e:
                # per-request isolation: the failed join kills THIS
                # request's future (or degrades it to the eager path),
                # frees the slot, and the pool keeps serving
                self.slots[s] = None
                self._evict(s)
                r.slot = None
                if r._trace is not None:
                    _rt.on_join_end(r, ok=False, error=e)
                self.metrics.record_error("slot_join", e)
                if not self._join_fallback(r, e):
                    r.fail(e, self.clock())
                    self.metrics.record_finish("error", len(r.tokens))
                    self._cbs.emit("on_finish", r)
                progress = True
                if not self._carry_alive():
                    # the failed attempt consumed the donated carry
                    # without replacing it: no valid pool state
                    # survives for the co-resident slots — rebuild
                    # (all-or-nothing recovery, same as a dead step)
                    self._fail_active(e)
                    break
                continue
            joins += 1
            progress = True
            if r._trace is not None:
                _rt.on_join_end(r, pending=s in self._pending)
            if getattr(r, "_replay", 0) > 0:
                # a preempted request re-joining: its replay counter
                # was armed at preemption and survives to here
                self.metrics.record_resume()
            self.metrics.record_join()
            self._cbs.emit("on_join", r, s)
            if tok is not None:   # prefill already produced token 0
                if self.sync_tok0:
                    self._deliver(r, int(tok), self.clock())
                else:
                    tok0s.append((r, tok))
        # resolve the admission round's first tokens AFTER the last
        # join dispatched: the traced scalars sync here (one natural
        # host sync instead of a blocking int() per join). A request
        # finishing at token 0 frees its slot an iteration late — the
        # decode step's active mask already excludes DONE slots.
        for r, tok in tok0s:
            self._deliver(r, int(tok), self.clock())
        # 2b. chunked prefill: one chunk per mid-prefill slot, BEFORE
        # the decode step — a freshly chunk-joined slot's first chunk
        # must set the pool index past its pad hole before any masked
        # decode-step write can land inside the prompt region
        if self._advance_chunks(self.clock()):
            progress = True
        # 3. one batched decode step over the active mask (slots with a
        # disaggregated prefill still in flight stay masked out)
        active = np.asarray(
            [r is not None and s not in self._pending
             for s, r in enumerate(self.slots)], bool)
        if active.any():
            t0 = self.clock()
            _ts0 = (time.perf_counter()
                    if _trace._SESSION is not None else 0.0)
            try:
                toks = self._guarded(
                    "decode_step", lambda: self._decode_attempt(active),
                    retry_tokens=int(active.sum()))
            except Exception as e:
                self.metrics.record_error("decode_step", e)
                self._fail_active(e)
                progress = True
            else:
                now2 = self.clock()
                if _trace._SESSION is not None:
                    _rt.on_decode_step(self, _ts0, time.perf_counter(),
                                       active, scheduler)
                n = 0
                if isinstance(toks, tuple):
                    # speculative step: (emit [S, k], n_emit [S]) —
                    # up to k tokens per slot per iteration; delivery
                    # stops the moment the slot finishes (eos /
                    # max_new_tokens), dropping the over-speculated
                    # tail exactly like the eager oracle would
                    emit, n_emit = toks
                    for s, r in enumerate(list(self.slots)):
                        if r is None or not active[s]:
                            continue
                        for j in range(int(n_emit[s])):
                            if self.slots[s] is not r or \
                                    r.state == "DONE":
                                break
                            self._deliver(r, int(emit[s, j]), now2)
                            n += 1
                else:
                    for s, r in enumerate(list(self.slots)):
                        if r is not None and active[s]:
                            self._deliver(r, int(toks[s]), now2)
                            n += 1
                self.metrics.record_decode(n, now2 - t0)
                # roofline gauges: one global read disarmed; when a
                # costs session is armed, the step's flops/bytes (XLA
                # or analytic) land in the MFU/bandwidth reservoirs
                if _costs._BOOK is not None:
                    self._record_step_cost(now2 - t0)
                # decode-step inter-arrival: the latency co-resident
                # requests actually SEE between their tokens — inline
                # prefill inflates it, disaggregated prefill doesn't
                if self._last_step_done is not None:
                    self.metrics.record_step_gap(
                        now2 - self._last_step_done)
                self._last_step_done = now2
                progress = True
        else:
            self._last_step_done = None
        self.metrics.record_iteration(
            scheduler.depth(), self.occupancy() / self.num_slots,
            **(self._iteration_gauges() or {}))
        lag_fn = getattr(scheduler, "wfq_lag_by_tenant", None)
        if lag_fn is not None:
            self.metrics.set_wfq_lag(lag_fn())
        self._cbs.emit("on_iteration", {
            "queue_depth": scheduler.depth(),
            "occupancy": self.occupancy(), "joins": joins})
        return progress

    def serve_until_idle(self, scheduler, max_iterations=None):
        """Synchronous drive: iterate until queue and pool are empty.
        The offline path (Predictor.generate, benches, tests) — online
        serving wraps run_iteration in a ServingServer thread."""
        it = 0
        while scheduler.depth() > 0 or self.occupancy() > 0:
            self.run_iteration(scheduler)
            it += 1
            if max_iterations is not None and it >= max_iterations:
                raise RuntimeError(
                    f"serve_until_idle: no convergence after {it} "
                    f"iterations")

    def abort_active(self, reason, now=None):
        """Finalize every in-flight request (non-drain shutdown);
        partial tokens are delivered."""
        if now is None:
            now = self.clock()
        for s, r in enumerate(self.slots):
            if r is not None:
                self._finish_slot(
                    s, "cancelled" if r.cancelled else reason, now)


class ServingEngine(_EngineBase):
    """The always-on model-backed engine: (decoder, embed, project)
    triple — the same step net `DecodeEngine` compiles — over a pooled
    StaticKVCache of `num_slots` rows x `max_len` positions.

    Admission contract: a request needs `bucket(prompt_len) +
    max_new_tokens <= max_len` cache positions and a cross-attention
    `memory` of the pool's [M, D] shape (fixed by the first join).
    Token positions follow the DecodeEngine convention — prompt at
    [0, Pb), its pad hole key-masked forever, generated tokens at
    absolute slots Pb, Pb+1, ... — which is what makes every slot's
    output bit-comparable to a solo `generate_eager` run."""

    def __new__(cls, *args, **kw):
        # `paged=True` routes construction to the paged-pool engine so
        # callers opt into paging without a second entry point
        if cls is ServingEngine and kw.get("paged"):
            return object.__new__(PagedServingEngine)
        return object.__new__(cls)

    def __init__(self, decoder, embed, project, *, num_slots=8,
                 max_len=128, max_joins_per_iter=2, metrics=None,
                 callbacks=(), clock=time.monotonic,
                 eager_fallback=False, paged=False, spec_k=None,
                 spec_ngram=2, spec_adapt=True, spec_adapt_low=0.15,
                 spec_adapt_high=0.6, spec_adapt_patience=4,
                 spec_adapt_alpha=0.3, adapters=None, quantize=None,
                 prefill_chunk=None, **kw):
        super().__init__(num_slots, max_joins_per_iter=max_joins_per_iter,
                         metrics=metrics, callbacks=callbacks, clock=clock,
                         **kw)
        from ..parallel.functional import functionalize
        from ..text.generation import _StepNet
        from .layers import (DenseLayout, PagedLayout, PlainStepper,
                             SpecStepper)

        # int8 base weights: quantize="int8" rewrites every large
        # dense weight of the stack (decoder projections + FFN, the
        # embedding vocab table, the logits projection) to symmetric
        # per-output-channel int8 + f32 scales BEFORE functionalize
        # snapshots the state — the compiled programs then carry int8
        # weight buffers and the scaled-int8 matmul path
        # (ops/quant.py). In place and one-way: the engine owns the
        # model it serves. With quantize=None nothing is touched and
        # the fp32 path is bit-identical to every prior PR.
        if quantize is not None:
            if str(quantize) != "int8":
                raise ValueError(f"quantize={quantize!r}: only 'int8' "
                                 f"is supported")
            from .adapters import quantize_net

            quantize_net(decoder, embed, project)
        self.quantize = quantize
        # batched LoRA adapters: an AdapterPool turns every step/join
        # program into an adapter-carrying one — per-slot adapter ids
        # + stacked A/B banks ride in as traced inputs, so tenant
        # switches and hot-load/evict never retrace
        if adapters is not None and adapters.decoder is not decoder:
            raise ValueError("the AdapterPool was built for a "
                             "different decoder than this engine "
                             "serves")
        self._apool = adapters
        self._adapter_rows = np.zeros(int(num_slots), np.int64)
        if adapters is not None:
            adapters.bind_metrics(self.metrics)
        self.eager_fallback = bool(eager_fallback)
        self.max_len = int(max_len)
        # speculative decoding (text/speculative.py): spec_k >= 2 turns
        # the batched decode step into a draft + k-token-verify pair
        # delivering up to spec_k tokens per slot per iteration —
        # bit-identical tokens, fewer dispatches. The pool carries
        # spec_k extra cache positions so a round's fixed-k verify
        # write never clips (admission keeps the max_len contract).
        # Works on EVERY pool layout: the paged pool's verify rides
        # multi-token page writes + the block-table verify kernel.
        if spec_k is not None:
            spec_k = int(spec_k)
            if spec_k < 2:
                raise ValueError("spec_k must be >= 2 (the pending "
                                 "token plus at least one draft)")
        self.spec_k = spec_k
        self.spec_ngram = int(spec_ngram)
        # adaptive effective k: shrink/regrow the live draft depth
        # batch-wide on the acceptance-rate EMA with hysteresis (the
        # force-rejected tail rides the same fixed-k program, so a k
        # change NEVER retraces); see layers.SpecStepper
        self.spec_adapt = bool(spec_adapt)
        self.spec_adapt_low = float(spec_adapt_low)
        self.spec_adapt_high = float(spec_adapt_high)
        self.spec_adapt_patience = int(spec_adapt_patience)
        self.spec_adapt_alpha = float(spec_adapt_alpha)
        # chunked prefill (the mechanism; serving/shaping.py is the
        # policy): prompts longer than `prefill_chunk` positions
        # prefill in fixed-size chunks dispatched BETWEEN decode
        # steps — run_iteration runs ONE chunk per mid-prefill slot
        # per iteration — so the decode-step inter-arrival co-resident
        # requests see is bounded by one chunk at ANY prompt length.
        # Power of two so chunk buckets ride the compile-bucket grid
        # (one cjoin/pcjoin compile per chunk bucket, never per
        # prompt); the paged engine additionally requires a page
        # multiple so every chunk boundary is page-aligned.
        if prefill_chunk is not None:
            prefill_chunk = int(prefill_chunk)
            if prefill_chunk < 2 or prefill_chunk & (prefill_chunk - 1):
                raise ValueError(
                    f"prefill_chunk={prefill_chunk}: must be a power "
                    f"of two >= 2 (compile-bucket granularity)")
        self.prefill_chunk = prefill_chunk
        self._chunking = {}   # slot -> mid-chunked-prefill progress
        self._fm_cross = None   # lazy cross-K/V net (attach + chunks)
        self._pool_len = self.max_len + (spec_k or 0)
        # the composable pool layers (serving/layers.py): cache layout
        # x placement x stepper — every program body lives there, the
        # engine classes are configuration shims
        self.layout = (PagedLayout(self)
                       if isinstance(self, PagedServingEngine)
                       else DenseLayout(self))
        self.placement = self._make_placement()
        self.stepper = (SpecStepper(self) if self.spec_k
                        else PlainStepper(self))
        self._net = _StepNet(decoder, embed, project)
        self._fm = functionalize(self._net)
        if not getattr(self, "_accepts_sharded_params", False):
            _reject_sharded_params(
                self._fm.params(),
                f"{type(self).__name__}"
                f"{'(paged=True)' if paged else ''}")
        # jit cache whose entries the compile observer wraps: each
        # trace+compile surfaces as a "compile" span with its duration
        self._compiled = _trace.JitCache(self)
        self._state = None          # lazily built on first join
        self._mem_shape = None
        self._np_dtype = None
        self._pool_key = None
        self._n_params = None       # cached dense param count (hints)
        # the live HBM ledger: snapshot()["memory"] reports this
        # engine's weights + pool footprint (and the budget watermark
        # warns before the pool runs dry)
        self.metrics.set_memory_provider(self.memory_ledger)

    # ------------------------------------------------------------------
    def _make_placement(self):
        """The program-build strategy (layers.py): plain single-chip
        jit here; the sharded engine overrides with the mesh-annotated
        wrap."""
        from .layers import SinglePlacement

        return SinglePlacement(self)

    def _pool_variant(self):
        """Label for per-pool-variant metric splits (the speculation
        section's step-ms breakdown)."""
        base = "paged" if isinstance(self, PagedServingEngine) \
            else "dense"
        if getattr(self, "_accepts_sharded_params", False):
            return "sharded-" + base
        return base

    # ---- multi-tenant adapter plumbing (serving/adapters.py) ----
    def _adapter_pool_key(self):
        """Adapter-config component of the pool key: adapter-carrying
        programs have different signatures (ids + banks ride in), so
        the jit-cache/AOT identities must not collide with a
        base-only pool of the same shape."""
        if self._apool is None:
            return ()
        p = self._apool
        return (("lora", p.capacity, p.rank, len(p.targets)),)

    def _placed_banks(self):
        """The stacked A/B banks as the programs' traced inputs (the
        sharded engine overrides with a mesh-replicated copy, cached
        per pool version)."""
        return self._apool.banks()

    def _adapter_args(self):
        """(per-slot adapter ids [S] int32, banks) appended to every
        step-family dispatch — traced data, never part of a cache
        key, so adapter switches and hot-loads never retrace."""
        if self._apool is None:
            return ()
        import jax.numpy as jnp

        return (jnp.asarray(self._adapter_rows.astype(np.int32)),
                self._placed_banks())

    def _lora_ctx(self, ad):
        """The trace scope a program body opens around fm.apply: `ad`
        is the body's (ids-or-scalar, banks) tail (empty when the
        engine carries no pool — a zero-cost nullcontext)."""
        import contextlib

        if not ad:
            return contextlib.nullcontext()
        import jax.numpy as jnp

        from ..ops.quant import lora_scope

        ids, banks = ad
        return lora_scope(jnp.asarray(ids, jnp.int32).reshape(-1),
                          banks)

    def _acquire_adapter(self, r):
        """Pin the request's adapter bank row for its slot (0 = base).
        Runs inside the join attempt, so a transient load fault rides
        the join's retry loop; the caller releases on a later join
        failure."""
        if self._apool is None:
            return 0
        name = getattr(r, "adapter", None)
        if name is None:
            return 0
        return self._apool.acquire(name)

    def _release_adapter_row(self, row):
        if self._apool is not None and row:
            self._apool.release(row)

    def _adapter_gate(self, r):
        """Admission headroom for the request's adapter: False defers
        the queue head (push_front) until a bank row frees — the
        OutOfAdapters backpressure path, mirroring OutOfPages."""
        if self._apool is None:
            return True
        name = getattr(r, "adapter", None)
        if name is None or self._apool.can_acquire(name):
            return True
        self.metrics.record_adapter_wait()
        return False

    def _admission_gate(self, r):
        return self._adapter_gate(r)

    def _tenant_slot_counts(self):
        out = {}
        for s, req in enumerate(self.slots):
            if req is None:
                continue
            t = self._tenant_of(req)
            out[t] = out.get(t, 0) + 1
        return out

    def _iteration_gauges(self):
        if self._apool is None:
            return None
        return {"tenant_slots": self._tenant_slot_counts()}

    def _evict(self, s):
        self._chunking.pop(s, None)
        self._pending.discard(s)
        row = int(self._adapter_rows[s])
        if row:
            self._adapter_rows[s] = 0
            self._release_adapter_row(row)

    # ---- the cross-attention K/V net (attach + chunk families) ----
    def _ensure_cross(self):
        """Lazily build the functionalized 'memory -> per-layer cross
        K/V' net the prefix-attach and chunked-prefill program
        families run (they never run a self-attention prefill, but
        the joiner's own cross K/V is per-request compute)."""
        if self._fm_cross is None:
            self._fm_cross = _make_cross_kv_fm(self._net.decoder)

    def _cross_params(self):
        """Cross-attention K/V net params for the attach/chunk paths
        (the sharded engine overrides with its mesh-placed copy)."""
        return self._fm_cross.params()

    def _params(self):
        """Param pytree the compiled programs run over. The sharded
        engine overrides this with its mesh-placed copy."""
        return self._fm.params()

    def _buffers(self):
        return self._fm.buffers()

    def _max_len_detail(self):
        """Suffix for the max_len overflow message (the paged engine
        reports the page-granular limit here)."""
        return ""

    # ---- the live HBM ledger ----
    def weights_bytes(self):
        """Byte footprint of the params + buffers the compiled
        programs run over (the placed copy for sharded engines)."""
        if self._weights_bytes is None:
            self._weights_bytes = _tree_bytes(self._params()) + \
                _tree_bytes(self._buffers())
        return self._weights_bytes

    def pool_bytes(self):
        """Byte footprint of the slot-pool device state (0 before the
        first join builds it)."""
        return _tree_bytes(self._state)

    def pool_in_use_bytes(self):
        """The LIVE portion of the pool. The dense pool preallocates
        every row, so committed == live; the paged engine subtracts
        unmapped pages."""
        return self.pool_bytes()

    def adapter_bytes(self):
        """Byte footprint of the stacked LoRA banks (0 without an
        AdapterPool) — the ledger's adapter component, exactly the
        pool's analytic capacity * (d_in + d_out) * r * 4 sum."""
        return 0 if self._apool is None else self._apool.bytes()

    def memory_ledger(self):
        """The `memory` section's raw components — weights, pool,
        adapter banks, live bytes, and the compile temp high-water
        from the armed cost book (0 when accounting is off)."""
        w = self.weights_bytes()
        p = self.pool_bytes()
        a = self.adapter_bytes()
        return {"weights_bytes": w, "pool_bytes": p,
                "adapter_bytes": a,
                "in_use_bytes": w + a + self.pool_in_use_bytes(),
                "compile_temp_peak_bytes": _costs.temp_high_water()}

    # ---- analytic cost hints (profiler.costs fallback) ----
    def _model_dims(self):
        """(n_params, n_layers, heads, head_dim, mem_len) for the
        analytic flop formulas; None before the pool shape is known."""
        if self._n_params is None:
            self._n_params = sum(
                int(getattr(v, "size", 0))
                for v in self._params().values()) + sum(
                int(getattr(v, "size", 0))
                for v in self._buffers().values())
        decoder = self._net.decoder
        h0 = decoder.layers[0].self_attn
        M = self._mem_shape[0] if self._mem_shape else 0
        return (self._n_params, len(decoder.layers), h0.num_heads,
                h0.head_dim, M)

    def _step_cost_key(self):
        if self._pool_key is None:
            return None
        return self.layout.step_key() if not self.spec_k \
            else self.layout.spec_step_key()

    def cost_hint(self, key):
        kind = key[0] if isinstance(key, tuple) and key else key
        n_params, n_layers, heads, hd, M = self._model_dims()
        pool = self.pool_bytes()
        w = self.weights_bytes()
        if kind in ("step", "pstep", "sstep", "pverify"):
            # the compiled step computes ALL S rows over the full
            # (masked) max_len window, active or not; the k-token
            # verify step feeds spec_k query rows through the same net
            flops = _costs.transformer_decode_flops(
                n_params, self.num_slots, self.max_len, n_layers,
                heads, hd, mem_len=M)
            if kind in ("sstep", "pverify"):
                flops *= (self.spec_k or 1)
            return {"flops": flops, "bytes_accessed": w + pool,
                    "argument_bytes": w + pool}
        if kind == "draft":
            # pure gathers over the [S, L] token mirror — byte traffic
            return {"flops": 0.0, "bytes_accessed": pool,
                    "argument_bytes": pool}
        if kind in ("join", "pjoin", "prefill") and len(key) > 1:
            Pb = int(key[1])
            flops = _costs.transformer_prefill_flops(
                n_params, 1, Pb, n_layers, heads, hd, mem_len=M)
            return {"flops": flops, "bytes_accessed": w + pool,
                    "argument_bytes": w + pool}
        if kind == "pattach" and len(key) > 2:
            # tail-only prefill: Tb query rows through the net, each
            # attending over at most the (Mb + tail) page window
            Tb = int(key[2])
            flops = _costs.transformer_prefill_flops(
                n_params, 1, Tb, n_layers, heads, hd, mem_len=M)
            return {"flops": flops, "bytes_accessed": w + pool,
                    "argument_bytes": w + pool}
        if kind == "cjoin" and len(key) > 1:
            # one chunk: Cb query rows through the net
            Cb = int(key[1])
            flops = _costs.transformer_prefill_flops(
                n_params, 1, Cb, n_layers, heads, hd, mem_len=M)
            return {"flops": flops, "bytes_accessed": w + pool,
                    "argument_bytes": w + pool}
        if kind == "pcjoin" and len(key) > 2:
            Cb = int(key[2])
            flops = _costs.transformer_prefill_flops(
                n_params, 1, Cb, n_layers, heads, hd, mem_len=M)
            return {"flops": flops, "bytes_accessed": w + pool,
                    "argument_bytes": w + pool}
        if kind in ("attach", "cow", "splice"):
            # row splices / page copies: byte traffic, ~no matmul flops
            return {"flops": 0.0, "bytes_accessed": pool,
                    "argument_bytes": pool}
        return None

    def admit_check(self, r):
        name = getattr(r, "adapter", None)
        if name is not None:
            if self._apool is None:
                raise ValueError(
                    f"request names adapter {name!r} but this engine "
                    f"carries no AdapterPool (adapters=)")
            if not self._apool.registered(name):
                raise ValueError(
                    f"adapter {name!r} is not registered with the "
                    f"pool (tenants: {self._apool.tenants()})")
        P = max(1, int(r.prompt.shape[0]))
        Pb = bucket_size(P)
        if Pb + r.max_new_tokens > self.max_len:
            raise ValueError(
                f"request needs bucket({P})={Pb} prompt slots + "
                f"{r.max_new_tokens} decode slots > pool max_len "
                f"{self.max_len}{self._max_len_detail()}")
        if r.memory is None or r.memory.ndim != 2:
            raise ValueError("ServingEngine requests need a 2-D "
                             "cross-attention memory [M, D]")
        if self._mem_shape is not None and \
                tuple(r.memory.shape) != self._mem_shape:
            raise ValueError(
                f"memory shape {tuple(r.memory.shape)} != pool's "
                f"{self._mem_shape} (fixed by the first join)")

    def _ensure_state(self, memory):
        if self._state is not None:
            return
        from ..text.generation import NEG

        memory = np.asarray(memory)
        self._neg = float(NEG)
        self._state = self.layout.build_state(memory)
        self._mem_shape = tuple(memory.shape)
        self._np_dtype = np.dtype(str(self._state["mem"].dtype))
        self._pool_key = self.layout.pool_key(memory)
        self._post_state_build()

    def _post_state_build(self):
        if self.metrics.budget_bytes > 0:
            # the dense pool commits its whole footprint up front:
            # check the watermark the moment it exists
            self.metrics.check_memory_watermark(
                self.weights_bytes() + self.pool_bytes())

    # ------------------------------------------------------------------
    def _join_adapter_args(self, row):
        """The (adapter id, banks) tail a join/prefill program takes
        when the engine carries a pool (batch-1: one traced scalar
        id)."""
        if self._apool is None:
            return ()
        import jax.numpy as jnp

        return (jnp.int32(row), self._placed_banks())

    def _join(self, s, r):
        import jax.numpy as jnp

        _PT_PREFILL()
        self._ensure_state(r.memory)
        # idempotent under the retry loop: an attempt that executed
        # but blew the watchdog already pinned its adapter row —
        # release it before this attempt acquires, or the row's
        # refcount leaks one per retry
        prev = int(self._adapter_rows[s])
        if prev:
            self._adapter_rows[s] = 0
            self._release_adapter_row(prev)
        row = self._acquire_adapter(r)
        pad_id = int(r.eos_id) if r.eos_id is not None else 0
        prompt_b, P0, Pb = pad_prompt_row(r.prompt, pad_id)
        if r._trace is not None:
            _rt.on_join_attr(r, prompt_bucket=Pb)
        if self.prefill_chunk is not None and P0 > self.prefill_chunk:
            return self._chunk_begin(s, r, prompt_b, P0, Pb, row)
        fn = self._program(("join", Pb), lambda: self._build_join(Pb))
        try:
            self._state, tok0 = fn(
                self._params(), self._buffers(), self._state,
                jnp.int32(s), jnp.asarray(prompt_b),
                jnp.asarray([P0], jnp.int32),
                jnp.asarray(np.asarray(r.memory, self._np_dtype)[None]),
                *self._join_adapter_args(row))
        except Exception:
            self._release_adapter_row(row)
            raise
        self._adapter_rows[s] = row
        return tok0   # traced scalar: run_iteration resolves post-loop

    def _build_join(self, Pb):
        """Every program build is `placement.build(layout body)`: one
        source of truth for the math in layers.py, one trace_counts
        key whichever placement wraps it."""
        key = self.layout.join_key(Pb)
        return self.placement.build(key, self.layout.join_body(Pb),
                                    has_aux=True)

    # ---- chunked prefill (the cjoin/pcjoin program family) ----
    def _chunk_begin(self, s, r, prompt_b, P0, Pb, row):
        """Register the slot as mid-chunked-prefill: NO program runs
        at join time — run_iteration's _advance_chunks dispatches one
        chunk per iteration, interleaved with decode steps. The slot
        sits in `_pending` (occupied for admission, excluded from the
        decode-step active mask) until the final chunk delivers its
        token 0. `info["pos"]` is the next prompt position to write:
        it advances only AFTER a chunk dispatch succeeds, so the
        guarded retry loop re-runs the SAME chunk (the splice is
        position-idempotent)."""
        self._ensure_cross()
        self._adapter_rows[s] = row
        self._chunking[s] = {"r": r, "prompt_b": prompt_b, "P0": P0,
                             "Pb": Pb, "pos": 0}
        self._pending.add(s)
        self.metrics.record_chunked_join()
        return None   # token 0 arrives with the final chunk

    def _chunk_bucket(self, pos, P0):
        """(Cb, final?) for the chunk starting at `pos`: full
        `prefill_chunk` mid-prompt, the tail's power-of-two bucket
        (>= 2) for the final chunk. Never crosses Pb: the final
        bucket is <= prefill_chunk, which divides every prompt bucket
        this path serves (chunking requires P0 > prefill_chunk)."""
        chunk = self.prefill_chunk
        if pos + chunk < P0:
            return chunk, False
        return max(2, bucket_size(P0 - pos)), True

    def _advance_chunks(self, now):
        if not self._chunking:
            return False
        progress = False
        for s in sorted(self._chunking):
            info = self._chunking.get(s)
            r = info["r"] if info is not None else None
            if r is None or self.slots[s] is not r or \
                    r.state == "DONE":
                continue   # harvested between registration and now
            _ts0 = (time.perf_counter()
                    if _trace._SESSION is not None else 0.0)
            try:
                tok0 = self._guarded(
                    "prefill_chunk",
                    lambda s=s, info=info: self._chunk_attempt(s, info))
            except Exception as e:
                # per-request isolation, mirroring the join failure
                # path: the failed chunk kills THIS request's future
                # and frees the slot; the pool keeps serving
                self.slots[s] = None
                self._evict(s)
                r.slot = None
                self.metrics.record_error("prefill_chunk", e)
                r.fail(e, self.clock())
                self.metrics.record_finish("error", len(r.tokens))
                self._cbs.emit("on_finish", r)
                progress = True
                if not self._carry_alive():
                    self._fail_active(e)
                    break
                continue
            progress = True
            done = info["pos"] >= info["P0"]
            self.metrics.record_chunk()
            if r._trace is not None:
                _rt.on_chunk(r, _ts0, time.perf_counter(),
                             info["pos"], done)
            if done:
                self._chunking.pop(s, None)
                self._pending.discard(s)
                self._chunk_finalize(s, info)
                self._deliver(r, int(tok0), self.clock())
        return progress

    def _chunk_attempt(self, s, info):
        _PT_CHUNK()
        if not self._carry_alive():
            raise PoolCarryLost(
                "pool carry consumed by a failed dispatch with no "
                "replacement state — refusing to run a prefill chunk "
                "on dead buffers")
        return self._chunk_step(s, info)

    def _chunk_step(self, s, info):
        import jax.numpy as jnp

        r = info["r"]
        P0, Pb, pos = info["P0"], info["Pb"], info["pos"]
        Cb, _ = self._chunk_bucket(pos, P0)
        rows = info["prompt_b"][:, pos:pos + Cb]
        fn = self._program(("cjoin", Cb),
                           lambda: self._build_cjoin(Cb))
        self._state, tok0 = fn(
            self._params(), self._buffers(), self._cross_params(),
            self._fm_cross.buffers(), self._state, jnp.int32(s),
            jnp.asarray(rows), jnp.int32(pos),
            jnp.asarray([P0], jnp.int32), jnp.int32(Pb),
            jnp.asarray(np.asarray(r.memory, self._np_dtype)[None]),
            *self._attach_spec_rows(info["prompt_b"], Pb),
            *self._join_adapter_args(int(self._adapter_rows[s])))
        info["pos"] = pos + Cb
        return tok0

    def _chunk_finalize(self, s, info):
        """Host bookkeeping once the final chunk ran (the paged
        engine maps the tail pages into the radix trie and COWs a
        shared tail page here; the dense pool's splice already set
        the slot's write index to Pb)."""

    def _build_cjoin(self, Cb):
        return self.placement.build(
            ("cjoin", Cb), self.layout.cjoin_body(Cb), has_aux=True)

    def _attach_spec_rows(self, prompt_b, Pb):
        """Spec-pool splice rows for the attach/chunk families: the
        slot's draft history is the PROMPT (the n-gram draft proposes
        from it), padded to the pool row. () when spec is off."""
        if not self.spec_k:
            return ()
        import jax.numpy as jnp

        row = np.zeros((1, self._pool_len), np.int32)
        row[0, :Pb] = np.asarray(prompt_b[0], np.int32)
        return (jnp.asarray(row),)

    def _reset_pool(self):
        # dropped wholesale: the next join's _ensure_state rebuilds a
        # zeroed pool (all slots are empty by now); the compiled
        # join/step programs are pure and stay cached — no retrace
        self._state = None

    # ---- graceful degradation: solo eager serve ----
    def _join_fallback(self, r, exc):
        """`eager_fallback=True`: after a join fails every attempt
        (persistent compile/dispatch failure), serve the request solo
        on the eager concat-cache path — slower, but the caller gets
        its exact tokens instead of an exception."""
        if not self.eager_fallback:
            return False
        try:
            toks, n = self._run_eager(r)
        except Exception as e:
            self.metrics.record_error("eager_fallback", e)
            return False
        self.metrics.record_fallback()
        now = self.clock()
        for t in toks[:n]:
            r.tokens.append(int(t))
            self.metrics.record_token(self._tenant_of(r))
            if r.first_token_at is None:
                r.first_token_at = now
                if r.submitted_at is not None:
                    self.metrics.record_first_token(
                        now - r.submitted_at)
            self._cbs.emit("on_token", r, int(t))
            if r.stream_cb is not None:
                try:
                    r.stream_cb(r, int(t))
                except Exception as e:
                    self.metrics.record_error("stream_cb", e)
        reason = ("eos" if r.eos_id is not None and r.tokens and
                  r.tokens[-1] == r.eos_id else "length")
        self.metrics.record_finish(reason, len(r.tokens))
        r.finish(reason, now)
        self._cbs.emit("on_finish", r)
        return True

    def _run_eager(self, r):
        import jax.numpy as jnp

        from ..text.generation import generate_eager

        net = self._net
        eos = int(r.eos_id) if r.eos_id is not None else -1
        toks, lens = generate_eager(
            net.decoder, net.embed, net.project,
            jnp.asarray(np.asarray(r.memory)[None]),
            jnp.asarray(r.prompt[None]),
            jnp.asarray([r.prompt.shape[0]], jnp.int32),
            bos_id=0, eos_id=eos, max_new_tokens=r.max_new_tokens,
            pad_prompt_to=bucket_size(max(1, int(r.prompt.shape[0]))))
        n = min(int(np.asarray(lens)[0]), r.max_new_tokens)
        return np.asarray(toks)[0], n

    # ------------------------------------------------------------------
    def _decode_step(self, active):
        # plain vs speculative is the Stepper axis (layers.py): one
        # batched step, or the draft + k-token-verify pair with the
        # adaptive effective-k controller
        return self.stepper.decode(active)

    def _build_step(self, key):
        return self.placement.build(key, self.layout.step_body(key),
                                    has_aux=True)

    def _build_spec_step(self, vkey):
        return self.placement.build(
            vkey, self.layout.spec_step_body(vkey), has_aux=True)

    def _build_draft(self, dkey):
        # pure gathers over per-slot rows; under a mesh the SPMD
        # partitioner follows the operand layouts, no pinning needed —
        # every placement builds it plain
        import jax

        return jax.jit(self.layout.draft_body(dkey))

    # ------------------------------------------------------------------
    # zero-warmup startup: AOT precompile + persistent cache
    # ------------------------------------------------------------------
    def precompile(self, memory, *, dtype="float32",
                   prompt_buckets=(8, 16, 32, 64), cache=None,
                   persist=True):
        """Ready EVERY serving program of this pool config before the
        first request: one join program per prompt bucket plus the
        batched decode step (or the spec draft/verify pair; the paged
        pool adds attach/cow). Programs come out of the persistent
        `cache` (an `AotCompileCache` or a directory path) when a
        valid entry exists — zero compiles, the warm start — and are
        AOT lower().compile()d otherwise, with the result persisted
        for the NEXT start. `memory` is the cross-attention memory: an
        example [M, D] array or its shape tuple (+ `dtype`); it pins
        the pool config exactly like the first join would, so
        admission semantics are unchanged. Returns the cold_start
        report (also recorded in `ServingMetrics.snapshot()`)."""
        if hasattr(memory, "ndim") or isinstance(memory, np.ndarray):
            mem = np.asarray(memory)
        else:
            M, Dm = memory
            mem = np.zeros((int(M), int(Dm)), np.dtype(dtype))
        self._ensure_state(mem)
        progs = self._startup_programs(prompt_buckets)
        return self._precompile_run(progs, cache, persist)

    def _program_fingerprint(self):
        from ..tuning.aot_cache import model_fingerprint

        return (f"{type(self).__name__}|"
                f"{model_fingerprint(self._fm.params(), self._fm.buffers())}|"
                f"{self._pool_key}")

    def _startup_adapter_args(self):
        """Step-shaped (ids [S], banks) example args for precompile —
        placement-mirrored like every other example arg."""
        if self._apool is None:
            return ()
        import jax.numpy as jnp

        return (jnp.zeros((self.num_slots,), jnp.int32),
                self._placed_banks())

    def _startup_programs(self, prompt_buckets):
        import jax.numpy as jnp

        S = self.num_slots
        params, buffers, state = self._params(), self._buffers(), \
            self._state
        M, Dm = self._mem_shape
        mem1 = jnp.zeros((1, M, Dm), jnp.dtype(self._np_dtype))
        one = jnp.asarray([1], jnp.int32)
        active = jnp.zeros((S,), bool)
        jad = self._join_adapter_args(0)
        sad = self._startup_adapter_args()
        progs = []
        for Pb in sorted({bucket_size(int(p)) for p in prompt_buckets}):
            progs.append((
                ("join", Pb), lambda Pb=Pb: self._build_join(Pb),
                (params, buffers, state, jnp.int32(0),
                 jnp.zeros((1, Pb), jnp.int32), one, mem1) + jad))
        if self.spec_k:
            dkey = ("draft",) + self._pool_key
            progs.append((
                dkey, lambda dkey=dkey: self._build_draft(dkey),
                (state["hist"], state["tok"], state["plen"],
                 state["pbk"], state["inc"][0].index)))
            vkey = ("sstep",) + self._pool_key
            progs.append((
                vkey, lambda vkey=vkey: self._build_spec_step(vkey),
                (params, buffers, state) + sad +
                (jnp.zeros((S, self.spec_k - 1), jnp.int32), active,
                 active, jnp.int32(self.spec_k))))
        else:
            skey = ("step",) + self._pool_key
            progs.append((
                skey, lambda skey=skey: self._build_step(skey),
                (params, buffers, state) + sad + (active,)))
        if self.prefill_chunk:
            # bucket-length prompts chunk in full-size chunks only
            # (prefill_chunk divides every bucket it splits), so ONE
            # cjoin program covers the precompile surface; ragged
            # final chunks compile their smaller bucket on demand
            self._ensure_cross()
            spec_rows = ((jnp.zeros((1, self._pool_len), jnp.int32),)
                         if self.spec_k else ())
            Cb = self.prefill_chunk
            progs.append((
                ("cjoin", Cb), lambda Cb=Cb: self._build_cjoin(Cb),
                (params, buffers, self._cross_params(),
                 self._fm_cross.buffers(), state, jnp.int32(0),
                 jnp.zeros((1, Cb), jnp.int32), jnp.int32(0), one,
                 jnp.int32(2 * Cb), mem1) + spec_rows + jad))
        return progs


def _make_cross_kv_fm(decoder):
    """Functionalized 'memory -> per-layer cross-attention StaticCache'
    net: the prefix-hit attach path needs the joiner's OWN cross-attn
    K/V (memory is per-request) but must not run any self-attention
    prefill — this is the only model compute a shared-prefix join
    performs."""
    from ..nn.layer.layers import Layer
    from ..nn.layer.transformer import MultiHeadAttention as MHA
    from ..parallel.functional import functionalize

    class _CrossKV(Layer):
        def __init__(self, dec):
            super().__init__()
            self.dec = dec

        def forward(self, memory):
            return [layer.cross_attn.gen_cache(
                memory, type=MHA.StaticCache)
                for layer in self.dec.layers]

    return functionalize(_CrossKV(decoder))


class PagedServingEngine(ServingEngine):
    """The serving pool over PAGED KV storage: `ServingEngine(...,
    paged=True)`. Device K/V lives in a global pool of fixed-size pages
    ([num_pages + 1, H, page_size, D] per layer — static shape, one
    compile per pool config); each slot maps its logical positions
    through a host-owned int32 page table shipped to the device as a
    traced input every step, so page mapping, joins, and evictions
    never retrace:

      * slot join allocates only the pages the PROMPT bucket needs;
        decode pages are mapped on demand as the write position crosses
        page boundaries, so pool occupancy is bounded by actual tokens,
        not worst-case max_len — `num_pages` can be far below
        `num_slots * max_pages` (oversubscription);
      * a prompt already in the prefix cache joins with ZERO prefill
        FLOPs: the shared pages are mapped read-only (refcounted) and
        only the page the joiner will decode-write into is copied
        (copy-on-write), so co-resident requests sharing a prefix stay
        bit-isolated;
      * admission runs on free-page headroom (prompt pages + a decode
        reservation) — insufficient pages DEFER the queue head
        (OutOfPages backpressure, `metrics.page_waits`) instead of
        failing it; if oversubscription still runs dry mid-decode, the
        starved slot is evicted with partials + an `OutOfPages` cause
        (`metrics.oom_evictions`) and the pool keeps serving;
      * pages store fp32 (default: bit-identical to the dense pool's
        decode), bf16, or int8 + per-(page, head) scales behind
        `kv_dtype=`, dequantized at read time (in-kernel on TPU).

    Numerics contract: with `kv_dtype=None` (compute dtype) every
    request's tokens bit-match both the dense `ServingEngine` and a
    solo `generate_eager` run, provided `max_len` is a page multiple
    (it is rounded up to one — a non-multiple would change the masked
    softmax width)."""

    def __init__(self, decoder, embed, project, *, num_slots=8,
                 max_len=128, page_size=16, num_pages=None,
                 kv_dtype=None, prefix_cache=True, prefix_capacity=64,
                 radix_mid_page="round_down",
                 reserve_decode_frac=1.0, paged=True, **kw):
        page_size = int(page_size)
        max_len = pages_for(max_len, page_size) * page_size
        super().__init__(decoder, embed, project, num_slots=num_slots,
                         max_len=max_len, **kw)
        self.page_size = page_size
        if self.prefill_chunk is not None and \
                self.prefill_chunk % page_size:
            raise ValueError(
                f"prefill_chunk={self.prefill_chunk} must be a "
                f"multiple of page_size={page_size}: chunk frontiers "
                f"must be page-aligned so every finished chunk is a "
                f"radix-trie-insertable run of full pages")
        # a speculative pool writes up to spec_k tokens past a row's
        # admitted budget before rolling back — round the logical pool
        # length (and the table width) up to page-cover that overhang;
        # admission still enforces the max_len contract
        self._pool_len = pages_for(self.max_len + (self.spec_k or 0),
                                   page_size) * page_size
        self.max_pages = self._pool_len // page_size
        self.num_pages = (int(num_pages) if num_pages is not None
                          else self.num_slots * self.max_pages)
        self.kv_dtype = kv_dtype
        self.reserve_decode_frac = float(reserve_decode_frac)
        self._alloc = PageAllocator(self.num_pages, page_size)
        self._prefix = (RadixPrefixCache(self._alloc, prefix_capacity,
                                         page_size=page_size,
                                         mid_page=radix_mid_page)
                        if prefix_cache else None)
        self._partial_ok = None   # resolved lazily (needs jnp)
        if self._prefix is not None and self._apool is not None:
            # eager tenant invalidation: an adapter re-register drops
            # the stale subtree immediately (the generation key would
            # also catch it lazily on next touch)
            import weakref

            wr = weakref.ref(self)

            def _drop(name, gen):
                e = wr()
                if e is not None and e._prefix is not None:
                    e._prefix.drop_tenant(name)

            self._apool.on_invalidate(_drop)
        self._table = np.full((self.num_slots, self.max_pages), -1,
                              np.int32)
        self._index = np.zeros(self.num_slots, np.int32)
        # total pages each occupied slot will have mapped by the time
        # its request hits max_new_tokens — admission subtracts the
        # not-yet-mapped remainder from the free-page headroom so
        # reserve_decode_frac=1.0 is a no-OOM guarantee
        self._slot_pages_total = np.zeros(self.num_slots, np.int64)
        self._page_bytes = None
        self._pool_total_bytes = None  # ledger cache (watermark path)
        self._prefix_params = None   # param identity the cache is
        #                              valid for (see _check_params)
        self.prefill_count = 0   # real prefills run (prefix hits skip)

    # ------------------------------------------------------------------
    def _max_len_detail(self):
        return (f" (= {self.max_pages} pages x {self.page_size} "
                f"tokens, paged)")

    # ---- the live HBM ledger (paged) ----
    def table_bytes(self):
        """The int32 page table shipped to the device every step."""
        return self.num_slots * self.max_pages * 4

    def pool_bytes(self):
        b = _tree_bytes(self._state)
        return b + self.table_bytes() if b else 0

    def pool_in_use_bytes(self):
        """Committed pool minus the UNMAPPED pages: the paged pool's
        whole point is that live bytes track actual tokens, not
        worst-case max_len — this is the number the budget watermark
        and oversubscription monitoring care about."""
        total = self.pool_bytes()
        if not total or not self._page_bytes:
            return total
        return total - self._alloc.pages_free * self._page_bytes


    def _spec_overhang(self):
        """Cache positions a speculative verify may write past a row's
        accepted budget before the rollback (the force-rejected tail):
        admission and the per-slot page reservations must cover them."""
        return (self.spec_k - 1) if self.spec_k else 0

    def admit_check(self, r):
        super().admit_check(r)
        # liveness: a request the whole (empty) pool could never hold
        # must fail fast, not defer at the backpressure gate forever
        P = max(1, int(r.prompt.shape[0]))
        need = pages_for(bucket_size(P) + r.max_new_tokens +
                         self._spec_overhang(), self.page_size)
        if need > self.num_pages:
            raise ValueError(
                f"request needs {need} pages > pool num_pages "
                f"{self.num_pages} ({self.page_size}-token pages)")

    def _post_state_build(self):
        import jax.numpy as jnp

        from .paging import resolve_kv_dtype

        decoder = self._net.decoder
        storage, quantized = resolve_kv_dtype(
            self.kv_dtype, jnp.dtype(self._np_dtype))
        h0 = decoder.layers[0].self_attn
        per_buf = h0.num_heads * self.page_size * h0.head_dim \
            * jnp.dtype(storage).itemsize
        scale_b = h0.num_heads * 4 if quantized else 0
        self._page_bytes = 2 * len(decoder.layers) * (per_buf + scale_b)
        self._pool_total_bytes = self.pool_bytes()
        if self.metrics.budget_bytes > 0:
            self.metrics.check_memory_watermark(
                self.weights_bytes() + self.pool_in_use_bytes())

    # ---- host page bookkeeping ----
    def _alloc_pages(self, n):
        """Allocate n pages, reclaiming LRU prefix-cache entries under
        pressure first."""
        if self._alloc.pages_free < n and self._prefix is not None:
            self._prefix.reclaim(n)
        return self._alloc.alloc(n)

    def _release_slot(self, s):
        mapped = [int(p) for p in self._table[s] if p >= 0]
        if mapped:
            self._alloc.decref(mapped)
        self._table[s] = -1
        self._index[s] = 0
        self._slot_pages_total[s] = 0

    def _evict(self, s):
        super()._evict(s)          # adapter row release
        self._release_slot(s)

    def _device_table(self):
        import jax.numpy as jnp

        # unmapped entries point at the trash row (num_pages): inactive
        # slots' masked decode writes can never land on live pages
        return jnp.asarray(np.where(self._table < 0, self.num_pages,
                                    self._table).astype(np.int32))

    def flush_prefix_cache(self):
        """Drop every prefix-cache entry (releases the cache's page
        references; pages still mapped by live slots survive via their
        own refs). After a full drain this returns the allocator to
        all-free — the chaos leak check pivots on it."""
        if self._prefix is not None:
            self._prefix.flush()

    def _reset_pool(self):
        # a decode-step failure evicted every slot (pages returned);
        # the device pages are rebuilt zeroed on the next join, so the
        # prefix cache's pages would hold garbage — flush it
        self.flush_prefix_cache()
        self._table[:] = -1
        self._index[:] = 0
        self._state = None
        self._pool_total_bytes = None

    # ---- admission: free-page headroom ----
    def _pages_needed(self, r):
        P0 = max(1, int(r.prompt.shape[0]))
        Pb = bucket_size(P0)
        n_pp = pages_for(Pb, self.page_size)
        need_prompt = n_pp
        if self._prefix is not None:
            pad_id = int(r.eos_id) if r.eos_id is not None else 0
            row, P0, Pb = pad_prompt_row(r.prompt, pad_id)
            res = self._prefix.peek(
                row[0, :P0], P0, Pb, r.memory, self._tenant_key(r),
                allow_partial=self._radix_partial_ok())
            if res is not None and res[0] == "whole":
                # shared pages are free; only a COW of the partial
                # tail page (when the bucket ends mid-page) is new
                need_prompt = 1 if Pb % self.page_size else 0
            elif res is not None:
                # matched prefix pages are free; the joiner allocates
                # the rest (COW page included) + a possible tail COW
                m = len(res[1]["pages"])
                need_prompt = (n_pp - m) + \
                    (1 if Pb % self.page_size else 0)
        total = pages_for(Pb + r.max_new_tokens +
                          self._spec_overhang(), self.page_size)
        reserve = int(np.ceil(
            self.reserve_decode_frac * (total - n_pp)))
        return need_prompt + reserve

    def _outstanding_reservations(self):
        """Pages already-admitted slots will still map before they
        finish (scaled by the reservation fraction): subtracted from
        the free headroom so admission never promises the same page
        twice."""
        out = 0
        for s, r in enumerate(self.slots):
            if r is None:
                continue
            mapped = int((self._table[s] >= 0).sum())
            remain = max(0, int(self._slot_pages_total[s]) - mapped)
            out += int(np.ceil(self.reserve_decode_frac * remain))
        return out

    def _admission_gate(self, r):
        if not self._adapter_gate(r):
            return False
        need = self._pages_needed(r) + self._outstanding_reservations()
        if self._alloc.pages_free < need and self._prefix is not None:
            self._prefix.reclaim(need)
        if self._alloc.pages_free >= need:
            return True
        self.metrics.record_page_wait()
        return False

    def _iteration_gauges(self):
        gauges = dict(super()._iteration_gauges() or {})
        gauges.update({"pages_in_use": self._alloc.pages_in_use,
                       "pages_free": self._alloc.pages_free})
        if self._prefix is not None:
            st = self._prefix.stats()
            gauges.update({"trie_nodes": st["nodes"],
                           "trie_pages": st["pages"]})
        active_toks = sum(int(self._index[s])
                          for s, r in enumerate(self.slots)
                          if r is not None)
        if active_toks and self._page_bytes:
            gauges["bytes_per_active_token"] = \
                self._alloc.pages_in_use * self._page_bytes \
                / active_toks
        # budget watermark: live bytes grow page by page, so the
        # crossing fires while free headroom still exists — BEFORE the
        # OutOfPages backpressure/eviction paths. Costs two int ops per
        # iteration, and only when a budget was configured.
        if self.metrics.budget_bytes > 0 and \
                self._pool_total_bytes is not None:
            in_use = self.weights_bytes() + self._pool_total_bytes \
                - self._alloc.pages_free * (self._page_bytes or 0)
            self.metrics.check_memory_watermark(in_use)
        return gauges

    # ---- join: prefill into pages, or attach shared prefix pages ----
    def _tenant_key(self, r):
        """The radix trie's tenant scope. The prompt K/V depend on the
        adapter that prefilled them (LoRA on the K/V projections from
        token 0), so adapter traffic gets its own subtree keyed by
        (adapter name, registration GENERATION — never the recyclable
        bank row), while adapter-LESS requests share ONE base subtree
        across every logical tenant: base-model preambles are the only
        pages that are safely identical across tenants."""
        name = getattr(r, "adapter", None)
        if name is None or self._apool is None:
            return None
        return (name, self._apool.generation(name))

    def _radix_partial_ok(self):
        """Partial (tail-prefill) reuse is admitted only when pages
        store the COMPUTE dtype: the pattach tail attends to the seed
        K/V as STORED, while a cold prefill attends to full-precision
        K/V before quantization — under int8/bf16 storage the two
        diverge, so quantized pools keep whole-prompt reuse only
        (whole hits replay the same decode-read path either way)."""
        if self._partial_ok is None:
            import jax.numpy as jnp

            from .paging import resolve_kv_dtype

            storage, quantized = resolve_kv_dtype(
                self.kv_dtype, jnp.dtype(self._np_dtype))
            self._partial_ok = (not quantized and
                                storage == jnp.dtype(self._np_dtype))
        return self._partial_ok

    def _check_params(self):
        """Prefix-cache entries hold MODEL-DERIVED state (prompt K/V
        pages + the first greedy token), so a weight update makes them
        stale — unlike the compiled programs, which take params as
        arguments every call. Rebinding any `p._data` replaces the leaf
        array object, so an identity sweep over the param pytree (a few
        hundred `is` checks, no device work) detects the update and
        flushes the cache; holding the previous dict's array references
        makes the identity check sound (no id recycling)."""
        cur = self._fm.params()
        prev = self._prefix_params
        if prev is not None and len(prev) == len(cur) and \
                all(cur[k] is prev.get(k) for k in cur):
            return
        if prev is not None:
            self.flush_prefix_cache()
        self._prefix_params = cur

    def _join(self, s, r):
        self._ensure_state(r.memory)
        if self._prefix is not None:
            self._check_params()
        # idempotent under the retry loop: a half-joined earlier
        # attempt's pages are released before this one allocates, and
        # its pinned adapter row is released before this one acquires
        # (or the row's refcount leaks one per watchdog retry)
        self._release_slot(s)
        prev = int(self._adapter_rows[s])
        if prev:
            self._adapter_rows[s] = 0
            self._release_adapter_row(prev)
        row = self._acquire_adapter(r)
        try:
            tok0 = self._join_inner(s, r, row)
        except Exception:
            self._release_adapter_row(row)
            raise
        self._adapter_rows[s] = row
        return tok0

    def _join_inner(self, s, r, row):
        pad_id = int(r.eos_id) if r.eos_id is not None else 0
        prompt_b, P0, Pb = pad_prompt_row(r.prompt, pad_id)
        self._slot_pages_total[s] = pages_for(
            Pb + r.max_new_tokens + self._spec_overhang(),
            self.page_size)
        res = None
        if self._prefix is not None:
            res = self._prefix.lookup(
                prompt_b[0, :P0], P0, Pb, r.memory,
                self._tenant_key(r),
                allow_partial=self._radix_partial_ok())
            kind = res[0] if res is not None else "miss"
            matched = (P0 if kind == "whole"
                       else res[1]["seed_len"] if kind == "partial"
                       else 0)
            self.metrics.record_prefix(kind, matched_tokens=matched,
                                       prompt_tokens=P0)
        if r._trace is not None:
            _rt.on_join_attr(r, prompt_bucket=Pb,
                             prefix_hit=res is not None and
                             res[0] == "whole")
            if self._prefix is not None:
                psz = self.page_size
                _rt.on_prefix_match(
                    r, kind,
                    matched_pages=pages_for(matched, psz) if matched
                    else 0,
                    matched_tokens=matched)
        if res is not None and res[0] == "whole":
            return self._attach_shared(s, r, res[1], prompt_b, P0, Pb)
        chunk = self.prefill_chunk
        if res is not None:
            match = res[1]
            if chunk is not None and \
                    P0 - len(match["pages"]) * self.page_size > chunk:
                # long divergent tail: resume from the matched FULL
                # pages only (round-down — the mid-page j tokens
                # re-prefill inside the first chunk, trading a few
                # tokens of reuse for a page-aligned chunk frontier)
                # and chunk the rest instead of one huge pattach
                return self._chunk_begin(s, r, prompt_b, P0, Pb, row,
                                         matched=match["pages"])
            return self._pattach_join(s, r, match, prompt_b, P0, Pb,
                                      row)
        if chunk is not None and P0 > chunk:
            return self._chunk_begin(s, r, prompt_b, P0, Pb, row)
        return self._prefill_join(s, r, prompt_b, P0, Pb, row)

    def _prefill_join(self, s, r, prompt_b, P0, Pb, row=0):
        import jax.numpy as jnp

        _PT_PREFILL()
        n_pp = pages_for(Pb, self.page_size)
        pages = self._alloc_pages(n_pp)
        fn = self._program(("pjoin", Pb),
                           lambda: self._build_paged_join(Pb))
        try:
            self._state, tok0 = fn(
                self._params(), self._buffers(), self._state,
                jnp.int32(s), jnp.asarray(prompt_b),
                jnp.asarray([P0], jnp.int32),
                jnp.asarray(np.asarray(r.memory, self._np_dtype)[None]),
                jnp.asarray(np.asarray(pages, np.int32)),
                *self._join_adapter_args(row))
        except Exception:
            self._alloc.decref(pages)
            raise
        self._table[s, :n_pp] = pages
        self._index[s] = Pb
        self.prefill_count += 1
        # tok0 stays the traced scalar: the trie stores it raw and
        # resolves lazily at the first whole hit; the caller's
        # delivery resolves after the admission round's last dispatch
        if self._prefix is not None:
            self._prefix.insert(prompt_b[0, :P0], P0, Pb, r.memory,
                                self._tenant_key(r), pages, tok0)
        self._cow_tail(s, Pb)
        return tok0

    def _pattach_join(self, s, r, match, prompt_b, P0, Pb, row=0):
        """Radix PARTIAL hit: map the matched prefix pages read-only,
        COW the mid-page divergence point (when the match ends inside
        a page), and prefill ONLY the divergent tail through the
        bucketed `pattach` program — prefill FLOPs scale with the
        MISSED tokens, not the prompt. The extended prompt is inserted
        back into the trie, so a conversation tree deepens the shared
        prefix one branch at a time."""
        import jax.numpy as jnp

        _PT_PATTACH()
        psz = self.page_size
        matched = [int(p) for p in match["pages"]]
        m = len(matched)
        j = int(match["j"])
        seed_len = m * psz + j
        n_pp = pages_for(Pb, psz)
        self._ensure_cross()
        self._alloc.incref(matched)
        owned = []       # pages THIS join allocated (released on fail)
        try:
            if j:
                dst = self._alloc_pages(1)[0]
                owned.append(dst)
                fn = self._program(("cow",), self._build_cow)
                self._state = fn(self._state,
                                 jnp.int32(int(match["cow_src"])),
                                 jnp.int32(dst))
                self.metrics.record_cow_copy()
                head = matched + [dst]
            else:
                head = list(matched)
            fresh = self._alloc_pages(n_pp - len(head)) \
                if n_pp > len(head) else []
            owned.extend(fresh)
            full_pages = head + fresh
            n_tail = P0 - seed_len
            Tb = max(2, bucket_size(n_tail))   # >= 2: the tail block
            #                      must take the verify path, not the
            #                      single-token decode path
            Mb = bucket_size(m + (1 if j else 0), minimum=1)
            W = min(self.max_pages, Mb + pages_for(Tb, psz))
            key = ("pattach", Mb, Tb)
            fn = self._program(key,
                               lambda: self._build_pattach(Mb, Tb))
            trow = np.full((1, W), self.num_pages, np.int32)
            k = min(W, n_pp)
            trow[0, :k] = full_pages[:k]
            tail = np.full((1, Tb),
                           int(r.eos_id) if r.eos_id is not None else 0,
                           np.int32)
            tail[0, :n_tail] = np.asarray(prompt_b[0, seed_len:P0],
                                          np.int32)
            self._state, tok0 = fn(
                self._params(), self._buffers(), self._cross_params(),
                self._fm_cross.buffers(), self._state, jnp.int32(s),
                jnp.asarray(trow), jnp.asarray(tail),
                jnp.int32(seed_len), jnp.asarray([P0], jnp.int32),
                jnp.int32(Pb),
                jnp.asarray(np.asarray(r.memory, self._np_dtype)[None]),
                *self._attach_spec_rows(prompt_b, Pb),
                *self._join_adapter_args(row))
        except Exception:
            if owned:
                self._alloc.decref(owned)
            self._alloc.decref(matched)
            raise
        self._table[s, :n_pp] = full_pages
        self._index[s] = Pb
        # insert BEFORE the tail COW so the trie adopts the slot's
        # pages while they are still the canonical prompt pages — the
        # COW then sees the shared refcount and gives the slot its
        # private decode page (same ordering as the cold prefill path)
        self._prefix.insert(prompt_b[0, :P0], P0, Pb, r.memory,
                            self._tenant_key(r), full_pages, tok0)
        self._cow_tail(s, Pb)
        return tok0

    def _attach_shared(self, s, r, hit, prompt_b, P0, Pb):
        """Prefix-cache hit: map the shared prompt pages read-only and
        splice only the per-request rows (bias hole, memory, cross-attn
        K/V, cached first token, and the spec history mirror) — ZERO
        self-attention prefill FLOPs for the shared pages. One compiled
        program for every bucket (the bucket boundary rides in as a
        traced scalar; the history row is pre-padded to pool length)."""
        import jax.numpy as jnp

        pages = hit["pages"]
        self._alloc.incref(pages)
        self._ensure_cross()
        fn = self._program(("attach",), self._build_attach)
        try:
            self._state = fn(
                self._cross_params(), self._fm_cross.buffers(),
                self._state, jnp.int32(s), jnp.int32(hit["tok0"]),
                jnp.asarray([P0], jnp.int32), jnp.int32(Pb),
                jnp.asarray(np.asarray(r.memory, self._np_dtype)[None]),
                *self._attach_spec_rows(prompt_b, Pb))
        except Exception:
            self._alloc.decref(pages)
            raise
        self._table[s, :len(pages)] = pages
        self._index[s] = Pb
        self._cow_tail(s, Pb)
        return int(hit["tok0"])

    def _cow_tail(self, s, Pb):
        """Copy-on-write: when the bucket boundary falls mid-page, the
        first decode write lands inside the last prompt page — if that
        page is shared (prefix cache / co-resident holder), give this
        slot a private copy first so the shared original stays
        immutable."""
        import jax.numpy as jnp

        if Pb % self.page_size == 0:
            return
        pi = Pb // self.page_size
        src = int(self._table[s, pi])
        if src < 0 or self._alloc.refcount[src] <= 1:
            return
        dst = self._alloc_pages(1)[0]
        fn = self._program(("cow",), self._build_cow)
        try:
            self._state = fn(self._state, jnp.int32(src),
                             jnp.int32(dst))
        except Exception:
            self._alloc.decref([dst])
            raise
        self._alloc.decref([src])
        self._table[s, pi] = dst
        self.metrics.record_cow_copy()

    # ---- chunked prefill over pages (the pcjoin program) ----
    def _chunk_begin(self, s, r, prompt_b, P0, Pb, row, matched=()):
        """Paged chunk registration: matched full prefix pages (a
        radix partial hit rounded DOWN to the page boundary) map
        read-only up front and seed the chunk frontier; the chunks
        prefill only the divergent tail, page by page. The host index
        tracks the frontier mid-prompt — safe because pending slots
        are excluded from both the decode active mask and the
        on-demand page mapper, and the steps' masked garbage writes
        land at/past the frontier, where the next chunk (or the
        slot's own first decode write) overwrites them before any
        read."""
        self._ensure_cross()
        matched = [int(p) for p in matched]
        if matched:
            self._alloc.incref(matched)
            self._table[s, :len(matched)] = matched
        pos = len(matched) * self.page_size
        self._index[s] = pos
        self._adapter_rows[s] = row
        self._chunking[s] = {"r": r, "prompt_b": prompt_b, "P0": P0,
                             "Pb": Pb, "pos": pos}
        self._pending.add(s)
        self.metrics.record_chunked_join()
        return None   # token 0 arrives with the final chunk

    def _chunk_step(self, s, info):
        import jax.numpy as jnp

        r = info["r"]
        P0, Pb, pos = info["P0"], info["Pb"], info["pos"]
        psz = self.page_size
        Cb, final = self._chunk_bucket(pos, P0)
        end = pos + Cb
        n_have = pos // psz       # chunk frontiers are page-aligned
        n_need = pages_for(end, psz) - n_have
        fresh = self._alloc_pages(n_need) if n_need > 0 else []
        Mb = bucket_size(n_have, minimum=1)
        W = min(self.max_pages, Mb + pages_for(Cb, psz))
        trow = np.full((1, W), self.num_pages, np.int32)
        pages_now = [int(p) for p in self._table[s, :n_have]] + fresh
        k = min(W, len(pages_now))
        trow[0, :k] = pages_now[:k]
        rows = info["prompt_b"][:, pos:end]
        fn = self._program(("pcjoin", Mb, Cb),
                           lambda: self._build_pcjoin(Mb, Cb))
        try:
            self._state, tok0 = fn(
                self._params(), self._buffers(), self._cross_params(),
                self._fm_cross.buffers(), self._state, jnp.int32(s),
                jnp.asarray(trow), jnp.asarray(rows), jnp.int32(pos),
                jnp.asarray([P0], jnp.int32), jnp.int32(Pb),
                jnp.asarray(np.asarray(r.memory, self._np_dtype)[None]),
                *self._attach_spec_rows(info["prompt_b"], Pb),
                *self._join_adapter_args(int(self._adapter_rows[s])))
        except Exception:
            if fresh:
                self._alloc.decref(fresh)
            raise
        if fresh:
            self._table[s, n_have:n_have + len(fresh)] = fresh
        # mid-chunk the frontier sits mid-PROMPT; the final chunk
        # graduates the index to Pb so decode starts past the hole
        self._index[s] = Pb if final else end
        info["pos"] = end
        if final:
            info["tok0"] = tok0
        elif self._prefix is not None:
            # the PR-16 follow-up: every finished chunk extends the
            # request's radix-trie prefix by its full pages, so the
            # work survives a later slot failure (and co-arrivals
            # partial-match the growing prefix immediately)
            self._prefix.insert_prefix(
                info["prompt_b"][0, :end], r.memory,
                self._tenant_key(r),
                [int(p) for p in self._table[s, :end // psz]])
        return tok0

    def _chunk_finalize(self, s, info):
        r, P0, Pb = info["r"], info["P0"], info["Pb"]
        self.prefill_count += 1
        if self._prefix is not None:
            pages = [int(p) for p in self._table[s] if p >= 0]
            self._prefix.insert(
                info["prompt_b"][0, :P0], P0, Pb, r.memory,
                self._tenant_key(r), pages, info["tok0"])
        self._cow_tail(s, Pb)

    def _build_pcjoin(self, Mb, Cb):
        return self.placement.build(
            ("pcjoin", Mb, Cb), self.layout.pcjoin_body(Mb, Cb),
            has_aux=True)

    # ---- fairness-aware preemption: evict to the prefix cache ----
    def can_preempt(self, s):
        """Mechanics-only eligibility (class policy lives in the
        shaper): a RUNNING slot, not mid-chunk, with token 0 already
        out — its prompt K/V pages are complete, which is what the
        evict-to-trie resume contract parks — on a pool that HAS a
        prefix cache to park them in."""
        r = self.slots[s]
        return (self._prefix is not None and r is not None and
                s not in self._pending and r.state == "RUNNING" and
                len(r.tokens) >= 1)

    def preempt_slot(self, s, now):
        if not self.can_preempt(s):
            return None
        r = self.slots[s]
        _PT_PREEMPT()   # host-side, BEFORE any mutation: an injected
        #                 fault aborts the preemption with the slot,
        #                 its pages, and the queue all untouched
        pad_id = int(r.eos_id) if r.eos_id is not None else 0
        prompt_b, P0, Pb = pad_prompt_row(r.prompt, pad_id)
        pages = []
        for p in self._table[s, :pages_for(Pb, self.page_size)]:
            if p < 0:
                break
            pages.append(int(p))
        # park the prompt K/V in the radix trie (an existing terminal
        # just refreshes its tick; a new one increfs the pages), THEN
        # release the slot: the pages survive via the trie's refs and
        # the resume join rides a zero-FLOP whole-prefix attach
        self._prefix.insert(prompt_b[0, :P0], P0, Pb, r.memory,
                            self._tenant_key(r), pages,
                            int(r.tokens[0]))
        if r._trace is not None:
            _rt.on_preempt(r, s, len(r.tokens))
        self.slots[s] = None
        self._evict(s)
        r.slot = None
        r.state = "QUEUED"
        # greedy decode is deterministic, so the resumed slot re-emits
        # the tokens the caller already holds bit-identically;
        # _deliver absorbs exactly this many silently
        r._replay = len(r.tokens)
        r._preemptions += 1
        self.metrics.record_preemption()
        return r

    # ---- compiled programs (bodies live in layers.PagedLayout) ----
    def _build_paged_join(self, Pb):
        return self.placement.build(("pjoin", Pb),
                                    self.layout.join_body(Pb),
                                    has_aux=True)

    def _build_pattach(self, Mb, Tb):
        return self.placement.build(("pattach", Mb, Tb),
                                    self.layout.pattach_body(Mb, Tb),
                                    has_aux=True)

    def _build_attach(self):
        return self.placement.build(("attach",),
                                    self.layout.attach_body(),
                                    has_aux=False)

    def _build_cow(self):
        return self.placement.build(("cow",), self.layout.cow_body(),
                                    has_aux=False)

    def _build_paged_step(self, ck):
        return self._build_step(ck)

    # ---- decode: on-demand page mapping + one batched step; the page
    # mapping and index advance are the PagedLayout host hooks the
    # steppers drive (layers.py) ----
    def _evict_oom(self, s, exc, now):
        r = self.slots[s]
        self.slots[s] = None
        self._evict(s)
        self.metrics.record_oom_eviction()
        self.metrics.record_error("out_of_pages", exc)
        self.metrics.record_finish("error", len(r.tokens))
        r.finish("error", now, error=exc)
        self._cbs.emit("on_finish", r)

    # ---- zero-warmup startup (paged program set) ----
    def _startup_programs(self, prompt_buckets):
        import jax.numpy as jnp

        S = self.num_slots
        params, buffers, state = self._params(), self._buffers(), \
            self._state
        M, Dm = self._mem_shape
        mem1 = jnp.zeros((1, M, Dm), jnp.dtype(self._np_dtype))
        one = jnp.asarray([1], jnp.int32)
        active = jnp.zeros((S,), bool)
        table0 = jnp.zeros((S, self.max_pages), jnp.int32)
        index0 = jnp.zeros((S,), jnp.int32)
        jad = self._join_adapter_args(0)
        sad = self._startup_adapter_args()
        progs = []
        for Pb in sorted({bucket_size(int(p)) for p in prompt_buckets}):
            n_pp = pages_for(Pb, self.page_size)
            progs.append((
                ("pjoin", Pb),
                lambda Pb=Pb: self._build_paged_join(Pb),
                (params, buffers, state, jnp.int32(0),
                 jnp.zeros((1, Pb), jnp.int32), one, mem1,
                 jnp.zeros((n_pp,), jnp.int32)) + jad))
        if self._prefix is not None:
            self._ensure_cross()
            spec_rows = ((jnp.zeros((1, self._pool_len), jnp.int32),)
                         if self.spec_k else ())
            progs.append((
                ("attach",), self._build_attach,
                (self._cross_params(), self._fm_cross.buffers(), state,
                 jnp.int32(0), jnp.int32(0), one, jnp.int32(1), mem1)
                + spec_rows))
            progs.append((
                ("cow",), self._build_cow,
                (state, jnp.int32(0), jnp.int32(0))))
            if self._radix_partial_ok():
                # partial-attach pairs the radix cache will hit first:
                # a last-page divergence per admitted prompt bucket
                # (matched = all-but-one page, tail = one page)
                psz = self.page_size
                pairs = sorted({
                    (bucket_size(max(1, pages_for(Pb, psz) - 1)),
                     max(2, bucket_size(min(psz, Pb))))
                    for Pb in {bucket_size(int(p))
                               for p in prompt_buckets}})
                for Mb, Tb in pairs:
                    W = min(self.max_pages, Mb + pages_for(Tb, psz))
                    progs.append((
                        ("pattach", Mb, Tb),
                        lambda Mb=Mb, Tb=Tb: self._build_pattach(
                            Mb, Tb),
                        (params, buffers, self._cross_params(),
                         self._fm_cross.buffers(), state, jnp.int32(0),
                         jnp.full((1, W), self.num_pages, jnp.int32),
                         jnp.zeros((1, Tb), jnp.int32), jnp.int32(1),
                         one, jnp.int32(Tb), mem1) + spec_rows + jad))
        if self.prefill_chunk:
            # bucket-length prompts chunk in full-size chunks only,
            # so the precompile surface is one pcjoin per matched-page
            # bucket Mb the chunk walk visits; ragged final chunks
            # compile their smaller bucket on demand
            self._ensure_cross()
            crows = ((jnp.zeros((1, self._pool_len), jnp.int32),)
                     if self.spec_k else ())
            psz = self.page_size
            Cb = self.prefill_chunk
            mbs = set()
            for Pb in {bucket_size(int(p)) for p in prompt_buckets}:
                for pos in range(0, Pb, Cb) if Pb > Cb else ():
                    mbs.add(bucket_size(pos // psz, minimum=1))
            for Mb in sorted(mbs):
                W = min(self.max_pages, Mb + pages_for(Cb, psz))
                progs.append((
                    ("pcjoin", Mb, Cb),
                    lambda Mb=Mb, Cb=Cb: self._build_pcjoin(Mb, Cb),
                    (params, buffers, self._cross_params(),
                     self._fm_cross.buffers(), state, jnp.int32(0),
                     jnp.full((1, W), self.num_pages, jnp.int32),
                     jnp.zeros((1, Cb), jnp.int32), jnp.int32(0),
                     one, jnp.int32(2 * Cb), mem1) + crows + jad))
        if self.spec_k:
            dkey = ("draft",) + self._pool_key
            progs.append((
                dkey, lambda dkey=dkey: self._build_draft(dkey),
                (state["hist"], state["tok"], state["plen"],
                 state["pbk"], index0)))
            vkey = ("pverify",) + self._pool_key
            progs.append((
                vkey, lambda vkey=vkey: self._build_spec_step(vkey),
                (params, buffers, state, table0, index0) + sad +
                (jnp.zeros((S, self.spec_k - 1), jnp.int32), active,
                 active, jnp.int32(self.spec_k))))
        else:
            ck = ("pstep",) + self._pool_key
            progs.append((
                ck, lambda ck=ck: self._build_paged_step(ck),
                (params, buffers, state, table0, index0) + sad +
                (active,)))
        return progs


class ArtifactServingEngine(_EngineBase):
    """Continuous batching over a stateless causal-LM logits callable
    (an inference Program artifact: one int feed [B, S] -> one logits
    fetch [B, S, V], position t reading ids[:, :t+1] only). Program
    artifacts cannot thread a KV cache, so each iteration re-runs every
    active slot's prefix — bucketed to a power of two and batched
    across the S slots in ONE call, so the compile cache stays
    O(log max_len) programs for the whole pool (`shapes` records the
    (S, Lb) combos actually run). New arrivals join mid-flight instead
    of waiting for a full batch drain: this is `Predictor.generate`'s
    serving mode behind `Config.enable_serving_engine()`."""

    def __init__(self, logits_fn, *, num_slots=8, max_len=None,
                 dtype=np.int64, max_joins_per_iter=2, metrics=None,
                 callbacks=(), clock=time.monotonic):
        super().__init__(num_slots, max_joins_per_iter=max_joins_per_iter,
                         metrics=metrics, callbacks=callbacks, clock=clock)
        self._fn = logits_fn
        self.max_len = None if max_len is None else int(max_len)
        self._dtype = np.dtype(dtype)
        self._rows = [None] * self.num_slots   # per-slot id prefix
        self.shapes = set()                    # (S, Lb) combos run

    def admit_check(self, r):
        need = int(r.prompt.shape[0]) + r.max_new_tokens
        if self.max_len is not None and need > self.max_len:
            raise ValueError(f"request needs {need} positions > "
                             f"engine max_len {self.max_len}")

    def _join(self, s, r):
        _PT_PREFILL()
        self._rows[s] = [int(x) for x in r.prompt]
        return None   # token 0 falls out of the next batched pass

    def _evict(self, s):
        self._rows[s] = None

    def _decode_step(self, active):
        S = self.num_slots
        buf, Lb = pad_token_rows(self._rows, pad_id=0,
                                 dtype=self._dtype)
        shape = (S, Lb)
        if shape not in self.shapes:
            self.shapes.add(shape)
            self.trace_counts[("step",) + shape] += 1
        logits = np.asarray(self._fn(buf)[0])
        toks = np.zeros((S,), np.int64)
        for s in range(S):
            if active[s]:
                n = len(self._rows[s])
                t = int(logits[s, n - 1].argmax(-1))
                self._rows[s].append(t)
                toks[s] = t
        return toks
