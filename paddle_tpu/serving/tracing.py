"""Request-lifecycle tracing for the serving stack.

`profiler.trace` provides the tracer; this module is the serving-side
vocabulary: one trace per request (trace id = request id), spans for
every lifecycle phase, engine-track spans for the batched decode step
and every compile, and the waterfall reconstruction the report tool
and tests share. Instrumented call sites in scheduler/engine/server
all guard with ``if _trace._SESSION is not None:`` — one module-global
read when tracing is off.

Span taxonomy (exported Chrome-trace names):

  request         per-request root: submit() -> finish/fail
  queue           admission queue wait: submit -> slot pop (re-opened
                  when page backpressure defers the request back to
                  the queue head)
  join            slot join: prefill / prefix attach / disaggregated
                  dispatch -> return (attrs: slot, prompt bucket,
                  prefix_hit)
  join.prefix_match  instant under the join: the radix prefix-cache
                  consult (attrs: kind whole/partial/miss,
                  matched_pages, matched_tokens)
  pending_splice  disaggregated only: prefill dispatched -> K/V
                  spliced into the live pool (the window the slot is
                  occupied-but-masked)
  decode          slot residency in batched decode: activation -> last
                  token (attrs: steps, tokens)
  first_token     instant: the request's first delivered token (TTFT)
  finish          terminal instant: finish_reason for completed
                  requests
  error           terminal instant: failed/evicted requests, with the
                  cause
  prefill_chunk   one chunked-prefill dispatch interleaved between
                  decode steps (attrs: pos — the post-chunk prompt
                  frontier — and done on the final chunk)
  preempt         instant: the slot was evicted to the prefix cache to
                  free capacity (attrs: slot, tokens so far)
  decode.step     engine track: one batched decode step (attrs:
                  n_active, slots, occupancy, queue depth, page-pool
                  and shard gauges)
  decode.draft    engine track: a speculative draft proposal dispatch
                  (attrs: n_active, proposed)
  decode.verify   engine track: the k-token verify dispatch (attrs:
                  n_active, proposed, accepted)
  compile         engine track: one jit trace+compile (attrs: cache
                  key, duration, count)
  retrace         engine track instant: a retrace-sentinel violation
"""
from __future__ import annotations

import numpy as np

from ..profiler import trace as _trace

__all__ = [
    "SPAN_TAXONOMY", "retrace_sentinel", "RetraceSentinel",
    "RetraceError", "session_scope", "start_session", "end_session",
    "load_chrome_trace", "waterfalls", "waterfall_report",
]

# re-exported so serving code/tests have one import surface
RetraceError = _trace.RetraceError
RetraceSentinel = _trace.RetraceSentinel
retrace_sentinel = _trace.retrace_sentinel
session_scope = _trace.session_scope
start_session = _trace.start_session
end_session = _trace.end_session

#: (span name, meaning) — the README "Observability" table and the
#: report tool's legend both render from this
SPAN_TAXONOMY = (
    ("request", "per-request root: submit -> finish/fail"),
    ("queue", "admission queue wait: submit -> slot pop"),
    ("join", "slot join: prefill / prefix attach / disagg dispatch"),
    ("join.prefix_match", "instant: radix prefix-cache consult "
                          "(kind, matched pages/tokens)"),
    ("pending_splice", "disaggregated prefill in flight -> spliced"),
    ("decode", "slot residency in batched decode steps"),
    ("prefill_chunk", "one chunked-prefill dispatch interleaved "
                      "between decode steps (pos, done)"),
    ("preempt", "instant: slot evicted to the prefix cache for "
                "higher-priority work"),
    ("first_token", "instant: first delivered token (TTFT)"),
    ("finish", "terminal instant: finish_reason"),
    ("error", "terminal instant: failure cause"),
    ("decode.step", "engine track: one batched decode step"),
    ("decode.draft", "engine track: speculative draft proposal"),
    ("decode.verify", "engine track: k-token speculative verify"),
    ("compile", "engine track: one jit trace+compile"),
    ("precompile", "engine track: one startup program readied "
                   "(source: cache deserialize | AOT compile)"),
    ("retrace", "engine track: retrace-sentinel violation"),
)


class _ReqTrace:
    """Per-request span bookkeeping, attached as `Request._trace`."""

    __slots__ = ("tr", "tid", "root", "queue", "join", "splice",
                 "decode", "steps")

    def __init__(self, tr, tid, root, queue):
        self.tr = tr
        self.tid = tid
        self.root = root
        self.queue = queue
        self.join = None
        self.splice = None
        self.decode = None
        self.steps = 0


# ----------------------------------------------------------------------
# lifecycle hooks (call sites pre-check _trace._SESSION)
# ----------------------------------------------------------------------

def on_submit(r):
    tr = _trace._SESSION
    if tr is None:
        return
    # sampling mode (start_session(sample=...)): an unsampled request
    # costs exactly this one branch — r._trace stays None, so every
    # downstream hook short-circuits on the attribute it already reads
    if tr.sample is not None and not tr.should_sample(r.id):
        tr.count("requests_unsampled")
        return
    if tr.sample is not None:
        tr.count("requests_sampled")
    root = tr.begin("request", cat="request", trace_id=r.id,
                    attrs={"prompt_len": int(r.prompt.shape[0]),
                           "max_new_tokens": r.max_new_tokens})
    queue = tr.begin("queue", cat="request", trace_id=r.id,
                     parent=root)
    r._trace = _ReqTrace(tr, r.id, root, queue)


def on_queue_exit(r):
    rt = r._trace
    if rt is not None:
        rt.tr.end(rt.queue)


def on_requeue(r):
    """Page backpressure deferred the request back to the queue head:
    re-open a queue span so the waterfall shows the extra wait."""
    rt = r._trace
    if rt is not None:
        rt.queue = rt.tr.begin("queue", cat="request", trace_id=rt.tid,
                               parent=rt.root,
                               attrs={"deferred": True})


def on_join_begin(r, slot):
    rt = r._trace
    if rt is not None:
        rt.tr.end(rt.queue)          # idempotent if already ended
        rt.join = rt.tr.begin("join", cat="request", trace_id=rt.tid,
                              parent=rt.root, attrs={"slot": slot})


def on_join_attr(r, **attrs):
    rt = r._trace
    if rt is not None and rt.join is not None:
        rt.join.attrs.update(attrs)


def on_prefix_match(r, kind, matched_pages=0, matched_tokens=0):
    """Instant span under the join: what the radix prefix cache
    returned for this request ("whole" / "partial" / "miss") and how
    much of the prompt it served — the per-request view of the
    hit_token_ratio gauge."""
    rt = r._trace
    if rt is not None:
        rt.tr.instant("join.prefix_match", cat="request",
                      trace_id=rt.tid, parent=rt.join or rt.root,
                      attrs={"kind": kind,
                             "matched_pages": int(matched_pages),
                             "matched_tokens": int(matched_tokens)})


def on_join_end(r, ok=True, pending=False, error=None):
    rt = r._trace
    if rt is None:
        return
    attrs = {}
    if error is not None:
        attrs = {"error": type(error).__name__}
    rt.tr.end(rt.join, ok=ok, **attrs)
    if ok and pending:
        rt.splice = rt.tr.begin("pending_splice", cat="request",
                                trace_id=rt.tid, parent=rt.root)
    elif ok:
        _begin_decode(rt)


def _begin_decode(rt):
    if rt.decode is None:
        rt.decode = rt.tr.begin("decode", cat="request",
                                trace_id=rt.tid, parent=rt.root)


def on_splice_end(r, ok=True, error=None):
    rt = r._trace
    if rt is None:
        return
    attrs = {} if error is None else {"error": type(error).__name__}
    rt.tr.end(rt.splice, ok=ok, **attrs)
    if ok:
        _begin_decode(rt)


def on_chunk(r, t0, t1, pos, done):
    """One chunked-prefill dispatch for this request's slot ([t0, t1],
    engine clock): `pos` is the POST-chunk prompt frontier, `done`
    marks the final chunk (the join is complete and the slot decodes
    from here on)."""
    rt = r._trace
    if rt is not None:
        rt.tr.add_complete("prefill_chunk", t0, t1, cat="request",
                           trace_id=rt.tid, parent=rt.root,
                           attrs={"pos": int(pos), "done": bool(done)})
        if done:
            _begin_decode(rt)


def on_preempt(r, slot, n_tokens):
    """The shaping scheduler evicted this request's slot to the prefix
    cache; the decode span closes here and a fresh queue span opens
    (the request re-enters admission and resumes via attach)."""
    rt = r._trace
    if rt is None:
        return
    rt.tr.instant("preempt", cat="request", trace_id=rt.tid,
                  parent=rt.root,
                  attrs={"slot": int(slot), "tokens": int(n_tokens)})
    rt.tr.end(rt.decode, steps=rt.steps, tokens=int(n_tokens))
    rt.decode = None
    rt.queue = rt.tr.begin("queue", cat="request", trace_id=rt.tid,
                           parent=rt.root, attrs={"preempted": True})


def on_first_token(r):
    rt = r._trace
    if rt is not None:
        rt.tr.instant("first_token", cat="request", trace_id=rt.tid,
                      parent=rt.root)


def on_finish(r, reason, error=None):
    """Terminal hook — fired from Request.finish()/fail(), so every
    path (eos/length, deadline, cancel, eviction, server crash) closes
    the trace. Evicted/failed requests end with an ``error`` span."""
    rt = r._trace
    if rt is None:
        return
    tr = rt.tr
    tr.end(rt.queue)
    tr.end(rt.join)
    tr.end(rt.splice)
    tr.end(rt.decode, steps=rt.steps, tokens=len(r.tokens))
    if reason == "error" or error is not None:
        attrs = {"reason": reason}
        if error is not None:
            attrs["error"] = type(error).__name__
            attrs["message"] = str(error)[:200]
        tr.instant("error", cat="request", trace_id=rt.tid,
                   parent=rt.root, attrs=attrs)
    else:
        tr.instant("finish", cat="request", trace_id=rt.tid,
                   parent=rt.root, attrs={"reason": reason})
    tr.end(rt.root, reason=reason, tokens=len(r.tokens))
    r._trace = None


def on_decode_step(engine, t0, t1, active, scheduler=None):
    """Engine-track span for one batched decode step, with the page
    pool / shard gauges as attributes and the co-resident requests'
    trace ids in ``slots`` — every decode step a request co-resides in
    is recoverable from the trace."""
    tr = _trace._SESSION
    if tr is None:
        return
    tids = []
    for s, r in enumerate(engine.slots):
        if r is not None and active[s]:
            tids.append(r.id)
            rt = r._trace
            if rt is not None:
                _begin_decode(rt)
                rt.steps += 1
    attrs = {"n_active": len(tids), "slots": tids,
             "occupancy": engine.occupancy()}
    if scheduler is not None:
        attrs["queue_depth"] = scheduler.depth()
    for k, v in (engine._iteration_gauges() or {}).items():
        attrs[k] = (round(float(v), 3) if isinstance(v, float)
                    else list(v) if isinstance(v, (list, tuple))
                    else v)
    tr.add_complete("decode.step", t0, t1, cat="engine", attrs=attrs)


def on_spec_step(t0, t1, t2, n_active, proposed, accepted):
    """Engine-track spans for one speculative iteration's two
    dispatches: the draft proposal ([t0, t1]) and the k-token verify
    ([t1, t2]) with the device-side acceptance counts — the waterfall
    report's speculation-phase breakdown reads these."""
    tr = _trace._SESSION
    if tr is None:
        return
    tr.add_complete("decode.draft", t0, t1, cat="engine",
                    attrs={"n_active": n_active, "proposed": proposed})
    tr.add_complete("decode.verify", t1, t2, cat="engine",
                    attrs={"n_active": n_active, "proposed": proposed,
                           "accepted": accepted})


# ----------------------------------------------------------------------
# waterfall reconstruction (shared by tools/trace_report.py and tests)
# ----------------------------------------------------------------------

_PHASES = ("queue", "join", "pending_splice", "decode")


def load_chrome_trace(path):
    """Read a chrome-trace JSON file back into its event list."""
    import json

    with open(path) as f:
        payload = json.load(f)
    return payload["traceEvents"] if isinstance(payload, dict) \
        else payload


def waterfalls(events):
    """Group request-track events into per-request waterfalls:
    {trace_id: {"spans": [...], "phases": {phase: total_ms},
    "total_ms", "reason", "tokens", "complete"}}. `complete` requires
    the root request span plus queue, join and a terminal
    finish/error event — the acceptance contract for every admitted
    request."""
    out = {}
    for ev in events:
        if ev.get("ph") not in ("X",) or ev.get("cat") != "request":
            continue
        tid = ev.get("args", {}).get("trace_id")
        if tid is None:
            continue
        out.setdefault(tid, []).append(ev)
    result = {}
    for tid, evs in out.items():
        evs.sort(key=lambda e: e["ts"])
        phases = {p: 0.0 for p in _PHASES}
        root = None
        reason = None
        terminal = None
        tokens = None
        for e in evs:
            n = e["name"]
            if n == "request":
                root = e
                reason = e["args"].get("reason", reason)
                tokens = e["args"].get("tokens", tokens)
            elif n in phases:
                phases[n] += e.get("dur", 0.0) / 1e3
            elif n in ("finish", "error"):
                terminal = n
                reason = e["args"].get("reason", reason)
        total = (root.get("dur", 0.0) / 1e3) if root else None
        result[tid] = {
            "spans": evs,
            "phases": {k: round(v, 3) for k, v in phases.items()},
            "total_ms": None if total is None else round(total, 3),
            "reason": reason,
            "terminal": terminal,
            "tokens": tokens,
            "complete": (root is not None and terminal is not None
                         and any(e["name"] == "queue" for e in evs)
                         and any(e["name"] == "join" for e in evs)),
        }
    return result


def waterfall_report(events, percentiles=(50, 95), top=0, width=48):
    """Render the per-request latency breakdown: per-phase
    p<percentiles> across all requests, then (optionally) the `top`
    slowest requests as ASCII waterfalls."""
    wf = waterfalls(events)
    lines = []
    done = [w for w in wf.values() if w["total_ms"] is not None]
    lines.append(f"requests: {len(wf)} traced, {len(done)} finished, "
                 f"{sum(1 for w in wf.values() if w['complete'])} "
                 f"complete waterfalls")
    if not done:
        return "\n".join(lines)
    hdr = "phase".ljust(16) + "".join(
        f"p{int(q)}(ms)".rjust(12) for q in percentiles) \
        + "mean(ms)".rjust(12)
    lines.append(hdr)
    for phase in _PHASES + ("total",):
        vals = np.asarray([w["total_ms"] if phase == "total"
                           else w["phases"][phase] for w in done])
        row = phase.ljust(16) + "".join(
            f"{float(np.percentile(vals, q)):12.2f}"
            for q in percentiles) + f"{float(vals.mean()):12.2f}"
        lines.append(row)
    if top:
        lines.append("")
        slowest = sorted(wf.items(),
                         key=lambda kv: -(kv[1]["total_ms"] or 0))[:top]
        scale = max(w["total_ms"] or 0 for _, w in slowest) or 1.0
        glyph = {"queue": ".", "join": "#", "pending_splice": "~",
                 "decode": "="}
        for tid, w in slowest:
            bar = ""
            for p in _PHASES:
                n = int(round(w["phases"][p] / scale * width))
                bar += glyph[p] * n
            lines.append(f"req {tid:>6} {w['total_ms'] or 0:9.2f}ms "
                         f"|{bar:<{width}}| {w['reason']}")
        lines.append("legend: .=queue  #=join  ~=pending_splice  "
                     "==decode")
    return "\n".join(lines)
