"""Serving observability: counters, latency reservoirs, callbacks.

The serving loop is an always-on system — the numbers that matter are
the ones operators alarm on: time-to-first-token (admission + prefill),
per-token decode latency, sustained tokens/s, queue depth (backpressure
headroom), and slot occupancy (batching efficiency). `ServingMetrics`
records all of them with O(1) bounded memory (fixed-size reservoirs)
and serves them through `snapshot()`; `ServingCallback` is the
hapi-`Callback`-style hook surface the engine drives, so user code can
tap the same events (per-request logging, tracing, export to external
metric systems) without touching the engine."""
from __future__ import annotations

import threading
import time

__all__ = ["ServingMetrics", "ServingCallback", "CallbackList",
           "SNAPSHOT_DOCS", "flatten_snapshot", "to_prometheus"]

#: Every key `ServingMetrics.snapshot()` can emit, flattened with "."
#: (reservoir summaries are ONE documented key whose value is the
#: {n, mean, p50, p99, max} dict). This is the schema of record: the
#: README "Observability" table renders from it and
#: tests/test_tracing.py asserts a fully-populated snapshot flattens
#: to EXACTLY these keys — the snapshot cannot drift silently.
SNAPSHOT_DOCS = {
    "requests.submitted": ("counter", "requests accepted by submit()"),
    "requests.completed": ("counter",
                           "finished with eos / length / drain"),
    "requests.rejected": ("counter",
                          "QueueFull backpressure + admission rejects"),
    "requests.cancelled": ("counter", "caller-cancelled requests"),
    "requests.timeouts": ("counter",
                          "deadline evictions (queued or mid-decode)"),
    "requests.failed": ("counter", "finished with reason 'error'"),
    "requests.aborted": ("counter",
                         "finalized by a non-drain shutdown"),
    "errors.count": ("counter", "internal failures recorded anywhere"),
    "errors.retries": ("counter", "retry attempts after a failed op"),
    "errors.evictions_on_error": (
        "counter", "in-flight victims of a failed decode step"),
    "errors.fallbacks": ("counter",
                         "requests degraded to the solo eager path"),
    "errors.last": ("info",
                    "last recorded error {where, type, message, at}"),
    "joins": ("counter", "successful slot joins"),
    "iterations": ("counter", "engine iterations run"),
    "tokens_out": ("counter",
                   "delivered tokens incl. the prefill first token"),
    "tokens_per_s": ("gauge", "decode tokens / decode wall seconds"),
    "ttft_ms": ("summary", "time to first token (submit -> token 0)"),
    "per_token_ms": ("summary", "batched decode-step wall latency"),
    "queue_depth": ("summary", "scheduler depth sampled per iteration"),
    "slot_occupancy": ("summary",
                       "occupied-slot fraction sampled per iteration"),
    # sharded pools (PR 7) — the section appears once any of these
    # record
    "sharding.prefill_step_ms": (
        "summary", "prefill-slice step: dispatch -> arrays ready"),
    "sharding.decode_step_ms": (
        "summary", "decode-step latency (the per_token_ms reservoir)"),
    "sharding.step_gap_ms": (
        "summary",
        "decode-step inter-arrival co-resident requests see"),
    "sharding.per_shard_occupancy": (
        "gauge", "last-iteration occupancy per dp shard of the pool"),
    "sharding.collective_ms": (
        "counter", "host-timed cross-slice transfer milliseconds"),
    "sharding.collective_events": (
        "counter", "cross-slice transfers (splices, param placement)"),
    "sharding.collective_time_share": (
        "gauge", "collective / (collective + prefill + decode) time"),
    # paged pools (PR 6) — the section appears once a paged engine
    # records
    "paging.pages_in_use": ("gauge", "pages mapped at last iteration"),
    "paging.pages_free": ("gauge", "allocator free pages"),
    "paging.prefix_hits": ("counter",
                           "joins served from the prefix cache"),
    "paging.prefix_misses": ("counter", "joins that ran a real prefill"),
    "paging.prefix_hit_rate": ("gauge", "hits / (hits + misses)"),
    "paging.page_waits": ("counter",
                          "admissions deferred on page headroom"),
    "paging.oom_evictions": ("counter", "mid-decode OutOfPages victims"),
    "paging.bytes_per_active_token": (
        "summary", "cache bytes per live token (oversubscription)"),
    # radix prefix cache (PR 16) — the section appears once a paged
    # join consults the trie
    "prefix.whole_hits": ("counter",
                          "joins fully served by cached pages (zero "
                          "prefill FLOPs)"),
    "prefix.partial_hits": ("counter",
                            "joins that matched a prefix and prefilled "
                            "only the divergent tail (pattach)"),
    "prefix.misses": ("counter", "joins that ran a full cold prefill"),
    "prefix.hit_token_ratio": (
        "gauge", "prefix tokens served from cache / prompt tokens "
                 "offered — the prefill-FLOPs savings lever"),
    "prefix.cow_copies": ("counter",
                          "copy-on-write page copies (mid-page "
                          "divergence + shared decode tails)"),
    "prefix.trie_nodes": ("gauge",
                          "radix-trie page nodes at last iteration"),
    "prefix.trie_pages": ("gauge",
                          "physical pages referenced by the trie"),
    # live HBM ledger (PR 9) — the section appears once the engine
    # registers its memory provider (model-backed engines always do)
    "memory.weights_bytes": (
        "gauge", "param + buffer bytes the pool serves"),
    "memory.pool_bytes": (
        "gauge",
        "KV pool + per-slot row arrays (paged: pages/scales/table)"),
    "memory.adapter_bytes": (
        "gauge", "stacked LoRA bank bytes (0 without an AdapterPool)"),
    "memory.total_bytes": (
        "gauge",
        "weights + pool + adapters: the committed device footprint"),
    "memory.in_use_bytes": (
        "gauge", "weights + rows/pages actually live right now"),
    "memory.budget_bytes": ("gauge", "configured HBM budget (0=unset)"),
    "memory.budget_used_frac": ("gauge", "in_use / budget"),
    "memory.compile_temp_peak_bytes": (
        "gauge", "XLA temp-buffer high-water across compiled programs"),
    "memory.watermark_warnings": (
        "counter", "budget-watermark crossings (warns BEFORE OOM)"),
    # MFU / bandwidth gauges (PR 9) — the section appears while a
    # profiler.costs accounting session records per-step utilization
    "mfu.device": ("info",
                   "roofline spec {name, peak_tflops, peak_gbps, ...}"),
    "mfu.cost_source": ("info",
                        "{source}: xla cost_analysis or analytic hint"),
    "mfu.flops_per_step": ("gauge", "compiled decode-step flops"),
    "mfu.bytes_per_step": ("gauge", "decode-step bytes accessed"),
    "mfu.model_flops_util": (
        "summary", "per-step achieved flops / DeviceSpec peak"),
    "mfu.bandwidth_util": (
        "summary", "per-step bytes accessed / DeviceSpec peak BW"),
    # goodput (PR 9): how much of the produced work reached callers
    "goodput.useful_tokens": (
        "counter", "tokens of requests that completed (eos/length/drain)"),
    "goodput.wasted_tokens": (
        "counter",
        "partial tokens of evicted/failed/timed-out/cancelled requests"),
    "goodput.warmup_tokens": (
        "counter", "tokens produced inside begin_warmup()/end_warmup()"),
    "goodput.retry_tokens": (
        "counter", "token-slots burned by retried decode attempts"),
    "goodput.ratio": (
        "gauge", "useful / (useful + wasted + warmup + retried + "
                 "rejected-draft)"),
    # speculative decoding (PR 10) — the section appears once a
    # spec-enabled engine records a draft/verify step pair
    "speculation.rounds": ("counter", "draft + verify step pairs run"),
    "speculation.drafts_proposed": (
        "counter", "draft tokens proposed across all spec steps"),
    "speculation.drafts_accepted": (
        "counter", "draft tokens that matched the verify oracle"),
    "speculation.acceptance_rate": (
        "gauge", "drafts_accepted / drafts_proposed"),
    "speculation.accepted_per_step": (
        "summary", "accepted draft tokens per verify step"),
    "speculation.draft_step_ms": (
        "summary", "draft-proposal dispatch wall latency"),
    "speculation.verify_step_ms": (
        "summary", "k-token verify dispatch wall latency"),
    "speculation.wasted_draft_tokens": (
        "counter",
        "rejected drafts — verify lanes burned; in the goodput "
        "denominator"),
    "speculation.effective_k": (
        "gauge", "adaptive batch-wide draft depth the spec stepper "
                 "currently runs at (None until a spec step records)"),
    "speculation.k_shrink_events": (
        "counter", "adaptive-k downshifts (acceptance EMA under the "
                   "low band past the hysteresis patience)"),
    "speculation.k_grow_events": (
        "counter", "adaptive-k upshifts (acceptance EMA over the high "
                   "band past the hysteresis patience)"),
    "speculation.step_ms_by_variant": (
        "info", "per-pool-variant (dense/paged/sharded-*) draft/"
                "verify step-ms p50 split"),
    # multi-tenant serving (PR 15) — the section appears once an
    # adapter-carrying engine records a tenancy event
    "tenancy.tenants": (
        "gauge", "distinct tenants (adapter names + base) served"),
    "tenancy.active_slots_by_tenant": (
        "info", "last-iteration occupied-slot count per tenant"),
    "tenancy.tokens_by_tenant": (
        "info", "delivered tokens per tenant (the fairness input)"),
    "tenancy.adapter_loads": (
        "counter", "adapter bank hot-loads (device writes)"),
    "tenancy.adapter_evictions": (
        "counter", "zero-reference adapters evicted for their row"),
    "tenancy.adapter_hit_rate": (
        "gauge", "acquires served by an already-hot bank row"),
    "tenancy.adapter_waits": (
        "counter", "admissions deferred on OutOfAdapters backpressure"),
    "tenancy.fairness": (
        "gauge", "Jain index over tokens_by_tenant (1.0 = even)"),
    # cold start (PR 11) — the section appears once the engine runs
    # precompile(): startup AOT compile / persistent-cache accounting.
    # Cold-start latency is a production metric: these are the numbers
    # a restart dashboard alarms on.
    "cold_start.time_to_ready_s": (
        "gauge", "precompile() wall seconds until every serving "
                 "program was ready"),
    "cold_start.programs": (
        "gauge", "serving programs readied at startup (join buckets + "
                 "steps + paged attach/cow)"),
    "cold_start.loaded_from_cache": (
        "gauge", "programs deserialized from the persistent AOT "
                 "cache — no compile paid"),
    "cold_start.compiled": (
        "gauge", "programs AOT-compiled fresh at startup (cache "
                 "miss/cold)"),
    "cold_start.cache_errors": (
        "counter", "corrupt/stale cache entries that fell back to a "
                   "fresh compile (never a crash)"),
    "cold_start.warm": (
        "gauge", "1 when every program loaded from cache — the "
                 "zero-compile warm start"),
    "cold_start.first_ttft_ms": (
        "gauge", "TTFT of the very first request after start (the "
                 "number warm vs cold starts A/B)"),
    # traffic shaping (PR 19) — the section appears once a shaping
    # feature records: chunked prefill, preemption/resume, SLO-classed
    # finishes, or WFQ lag published by the ShapingScheduler
    "slo.preemptions": (
        "counter", "batch-class slots evicted to the prefix cache "
                   "under pressure"),
    "slo.resumes": (
        "counter", "preempted requests re-admitted (resume rides the "
                   "prefix cache, not a re-prefill)"),
    "slo.replay_tokens": (
        "counter", "already-delivered tokens a resumed request "
                   "re-absorbed silently"),
    "slo.chunked_prefills": (
        "counter", "joins split into chunked prefill (prompt past the "
                   "prefill_chunk knob)"),
    "slo.chunks": (
        "counter", "prefill chunks dispatched between decode steps"),
    "slo.ttft_attainment": (
        "info", "per-class fraction of finished requests that met "
                "their TTFT target"),
    "slo.tpot_attainment": (
        "info", "per-class fraction of finished requests that met "
                "their TPOT target"),
    "slo.wfq_lag_by_tenant": (
        "info", "per-tenant WFQ virtual-time lag (pending finish tag "
                "minus pool virtual time; 0 = keeping pace)"),
}

_SUMMARY_KEYS = {"n", "mean", "p50", "p99", "max"}
_LEAF_DICTS = {"errors.last", "mfu.device",
               "speculation.step_ms_by_variant",
               "tenancy.active_slots_by_tenant",
               "tenancy.tokens_by_tenant",
               "slo.ttft_attainment", "slo.tpot_attainment",
               "slo.wfq_lag_by_tenant"}


def flatten_snapshot(snap, _prefix=""):
    """Flatten a snapshot() dict to {dotted_key: leaf}. Reservoir
    summaries ({n, mean, p50, p99, max}) and the last-error record stay
    leaves — the flattened key set must equal SNAPSHOT_DOCS for a
    fully-populated snapshot."""
    out = {}
    for k, v in snap.items():
        key = f"{_prefix}{k}"
        if isinstance(v, dict) and key not in _LEAF_DICTS and \
                not set(v) <= _SUMMARY_KEYS:
            out.update(flatten_snapshot(v, key + "."))
        else:
            out[key] = v
    return out


def _prom_escape(s):
    return (str(s).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def to_prometheus(snapshot, tracer=None, prefix="paddle_tpu_serving"):
    """Render a snapshot() (plus, optionally, a `profiler.trace.Tracer`
    session's counters) in the Prometheus text exposition format —
    `tools/metrics_dump.py` is the CLI over this."""
    lines = []

    def head(name, kind, doc):
        lines.append(f"# HELP {name} {doc}")
        lines.append(f"# TYPE {name} "
                     f"{'counter' if kind == 'counter' else 'gauge'}")

    flat = flatten_snapshot(snapshot)
    for key in sorted(flat):
        kind, doc = SNAPSHOT_DOCS.get(key, ("gauge", ""))
        v = flat[key]
        name = prefix + "_" + key.replace(".", "_")
        if v is None:
            continue
        if isinstance(v, dict) and set(v) <= _SUMMARY_KEYS:
            head(name, "gauge", doc)
            for stat in sorted(v):
                lines.append(f'{name}{{stat="{stat}"}} {float(v[stat])}')
        elif kind == "info" and isinstance(v, dict):
            head(name, "gauge", doc)
            labels = ",".join(f'{lk}="{_prom_escape(lv)}"'
                              for lk, lv in sorted(v.items()))
            lines.append(f"{name}{{{labels}}} 1")
        elif isinstance(v, (list, tuple)):
            head(name, kind, doc)
            for i, sv in enumerate(v):
                lines.append(f'{name}{{index="{i}"}} {float(sv)}')
        elif isinstance(v, (int, float)):
            head(name, kind, doc)
            lines.append(f"{name} {float(v)}")
    if tracer is not None:
        name = prefix + "_tracer_events"
        head(name, "counter", "tracer session counters")
        for cname in sorted(tracer.counters):
            lines.append(f'{name}{{counter="{_prom_escape(cname)}"}} '
                         f'{float(tracer.counters[cname])}')
        head(prefix + "_tracer_spans_dropped", "counter",
             "spans overwritten past the ring-buffer capacity")
        lines.append(f"{prefix}_tracer_spans_dropped "
                     f"{float(tracer.dropped)}")
    return "\n".join(lines) + "\n"


def _jain(tokens_by_tenant):
    """Jain's fairness index over per-tenant delivered tokens:
    (sum x)^2 / (n * sum x^2) — 1.0 when every tenant got an equal
    share, 1/n when one tenant took everything. The number the
    multi-tenant scheduler is judged on."""
    xs = [float(v) for v in tokens_by_tenant.values() if v > 0]
    if not xs:
        return 1.0
    s = sum(xs)
    return round((s * s) / (len(xs) * sum(x * x for x in xs)), 4)


class _Reservoir:
    """Bounded sample buffer (ring overwrite) with percentile reads —
    latency distributions over the most recent `cap` observations."""

    def __init__(self, cap=2048):
        self.cap = int(cap)
        self._buf = []
        self._next = 0
        self.count = 0

    def add(self, x):
        x = float(x)
        if len(self._buf) < self.cap:
            self._buf.append(x)
        else:
            self._buf[self._next] = x
            self._next = (self._next + 1) % self.cap
        self.count += 1

    def summary(self, scale=1.0, digits=3):
        import numpy as np

        if not self._buf:
            return {"n": 0}
        a = np.asarray(self._buf, dtype=np.float64) * scale
        return {"n": self.count,
                "mean": round(float(a.mean()), digits),
                "p50": round(float(np.percentile(a, 50)), digits),
                "p99": round(float(np.percentile(a, 99)), digits),
                "max": round(float(a.max()), digits)}


class ServingMetrics:
    """Thread-safe metric sink for the serving runtime. The engine and
    the frontend both record into it; `snapshot()` can be called from
    any thread at any time (monitoring endpoints, tests, the bench)."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        # identity wiring that reset() keeps: the ledger provider and
        # its armed budget describe the ENGINE, not a measurement epoch
        self._memory_provider = None
        self.budget_bytes = 0
        self.watermark_frac = 0.9
        self._init_counters()

    # callers hold the lock: __init__ (exempt by construction) and
    # reset() (wraps the call in `with self._lock:`)
    def _init_counters(self):   # analysis: single-threaded
        """(Re)zero every counter, gauge and reservoir. Split out of
        __init__ so reset() can start a fresh measurement epoch without
        touching identity wiring (clock, lock, ledger provider,
        budget)."""
        self.submitted = 0
        self.completed = 0          # finished with "eos" / "length"
        self.rejected = 0           # backpressure (QueueFull)
        self.cancelled = 0
        self.timeouts = 0           # deadline evictions
        self.aborted = 0            # non-drain shutdown
        self.joins = 0
        self.iterations = 0
        self.tokens_out = 0         # every delivered token (incl. the
        #                             prefill-produced first token)
        self.decode_tokens = 0      # tokens out of batched decode steps
        self.decode_time_s = 0.0
        self.ttft_s = _Reservoir()
        self.token_latency_s = _Reservoir()
        self.queue_depth = _Reservoir(512)
        self.occupancy = _Reservoir(512)
        # fault accounting (per-request isolation + retry layer)
        self.failed = 0             # finished with reason "error"
        self.errors = 0             # recorded internal errors, any kind
        self.retries = 0            # retry attempts after a failure
        self.evictions_on_error = 0  # in-flight requests evicted by a
        #                              decode-step failure
        self.fallbacks = 0          # requests degraded to the eager path
        self.last_error = None      # {"where","type","message","at"}
        # paging accounting (None until a paged engine records — the
        # snapshot only grows a "paging" section for paged pools)
        self.pages_in_use = None    # last-iteration gauge
        self.pages_free = None
        self.prefix_hits = 0        # joins served from the prefix cache
        self.prefix_misses = 0      # joins that ran a real prefill
        # radix prefix-cache accounting (PR 16): the snapshot grows a
        # "prefix" section once a join consults the trie
        self._prefix_recorded = False
        self.prefix_whole_hits = 0
        self.prefix_partial_hits = 0
        self.prefix_matched_tokens = 0   # prompt tokens served cached
        self.prefix_prompt_tokens = 0    # prompt tokens offered
        self.cow_copies = 0
        self.trie_nodes = None      # last-iteration gauges
        self.trie_pages = None
        self.page_waits = 0         # admissions deferred on page headroom
        self.oom_evictions = 0      # mid-decode OutOfPages victims
        self.bytes_per_token = _Reservoir(512)  # bytes / active token
        # sharded-serving accounting (the snapshot grows a "sharding"
        # section once any of these record — single-chip pools don't
        # pay for keys they never touch). Phases follow the
        # prefill/decode disaggregation split: "prefill" latencies are
        # the prefill-slice step (dispatch -> arrays ready), "decode"
        # rides the existing decode reservoirs; step_gap_s is the
        # decode-step INTER-ARRIVAL co-resident requests see between
        # tokens — the number inline prefill inflates and a
        # disaggregated prefill slice does not.
        self._sharded = False
        self.prefill_step_s = _Reservoir()
        self.step_gap_s = _Reservoir()
        self.collective_s = 0.0     # cross-slice transfers (prefill ->
        #                             decode splices, param placement)
        self.collective_events = 0
        self.shard_occupancy = None  # last-iteration per-dp-shard list
        # live HBM ledger (PR 9): the engine registers a provider that
        # returns {weights_bytes, pool_bytes, in_use_bytes,
        # compile_temp_peak_bytes}; snapshot() formats it into the
        # "memory" section. budget_bytes arms the watermark: crossing
        # watermark_frac * budget bumps watermark_warnings ONCE per
        # excursion (warn before OutOfPages/OOM, not after).
        self.watermark_warnings = 0
        self._above_watermark = False
        # goodput accounting: token-denominated usefulness, classified
        # at finish time (the engines pass each request's token count)
        self.useful_tokens = 0
        self.wasted_tokens = 0
        self.warmup_tokens = 0
        self.retry_tokens = 0
        self._warmup = False
        # speculative decoding (the snapshot grows a "speculation"
        # section once a spec-enabled engine records): device-side
        # acceptance accounting plus the two dispatch latencies of the
        # draft/verify pair; wasted drafts feed the goodput denominator
        self._spec_recorded = False
        self.spec_rounds = 0
        self.drafts_proposed = 0
        self.drafts_accepted = 0
        self.accepted_per_step = _Reservoir(512)
        self.draft_step_s = _Reservoir(512)
        self.verify_step_s = _Reservoir(512)
        # adaptive effective k (the batch-wide draft depth the spec
        # stepper is currently running) + its hysteresis transitions,
        # and the draft/verify latency split keyed by pool variant
        # (dense / paged / sharded-*) so a mixed deployment's spec
        # steps stay attributable
        self.spec_k_eff = None
        self.spec_k_shrinks = 0
        self.spec_k_grows = 0
        self._spec_by_variant = {}
        # multi-tenant serving (the snapshot grows a "tenancy" section
        # once an adapter-carrying engine records): per-tenant token /
        # slot accounting plus the AdapterPool's load/evict/hit-rate
        # counters mirrored by the pool itself
        self._tenancy = False
        self.tokens_by_tenant = {}
        self.tenant_slots = None       # last-iteration gauge
        self.adapter_loads = 0
        self.adapter_evictions = 0
        self.adapter_hits = 0
        self.adapter_misses = 0
        self.adapter_waits = 0
        # cold start (PR 11): the engine's precompile() report — how
        # the pool reached readiness (cache-warm vs compiled) and the
        # first request's TTFT (what a restart actually costs callers)
        self._cold_start = None
        self.first_ttft_s = None
        # MFU / bandwidth gauges: recorded per decode step only while
        # a profiler.costs accounting session is armed
        self._mfu = False
        self._spec = None             # DeviceSpec (dict at snapshot)
        self.cost_source = None       # "xla" | "analytic"
        self.flops_per_step = 0.0
        self.bytes_per_step = 0.0
        self.mfu_util = _Reservoir(512)
        self.bw_util = _Reservoir(512)
        # traffic shaping (PR 19): chunked-prefill and preemption
        # counters plus per-SLO-class attainment and the WFQ lag the
        # ShapingScheduler publishes each iteration — the snapshot
        # grows an "slo" section once any of them records
        self._slo = False
        self.preemptions = 0
        self.resumes = 0
        self.replay_tokens = 0
        self.chunked_prefills = 0
        self.chunks = 0
        self.slo_finishes = {}     # class -> {n, ttft_ok, tpot_ok}
        self.wfq_lag = {}          # tenant -> virtual-time lag

    def reset(self):
        """Start a fresh measurement epoch: zero every counter,
        reservoir and gauge while keeping identity wiring (clock, lock,
        ledger provider, HBM budget). Benches and tests call this
        between phases instead of zeroing individual fields by hand."""
        with self._lock:
            self._init_counters()

    # ---- recording (engine / frontend side) ----
    def record_submit(self):
        with self._lock:
            self.submitted += 1

    def record_reject(self):
        with self._lock:
            self.rejected += 1

    def record_join(self):
        with self._lock:
            self.joins += 1

    def record_first_token(self, ttft_s):
        with self._lock:
            self.ttft_s.add(ttft_s)
            if self.first_ttft_s is None:
                # the first request ever: the cold-start A/B's number
                self.first_ttft_s = float(ttft_s)

    def record_token(self, tenant=None):
        with self._lock:
            self.tokens_out += 1
            if tenant is not None:
                self._tenancy = True
                self.tokens_by_tenant[tenant] = \
                    self.tokens_by_tenant.get(tenant, 0) + 1

    def record_decode(self, n_tokens, dt_s):
        """One engine iteration produced `n_tokens` across the active
        slots in `dt_s` seconds of decode wall time."""
        with self._lock:
            self.decode_tokens += n_tokens
            self.decode_time_s += dt_s
            if n_tokens:
                self.token_latency_s.add(dt_s)

    def record_finish(self, reason, n_tokens=0):
        """Request finished with `reason`; `n_tokens` (the tokens it
        was delivered) feeds the goodput split: completions count as
        useful, evictions/failures/timeouts as wasted, and anything
        produced inside a warmup window as warmup."""
        with self._lock:
            if reason in ("eos", "length", "drain"):
                self.completed += 1
                if self._warmup:
                    self.warmup_tokens += int(n_tokens)
                else:
                    self.useful_tokens += int(n_tokens)
            else:
                self.wasted_tokens += int(n_tokens)
                if reason == "cancelled":
                    self.cancelled += 1
                elif reason == "timeout":
                    self.timeouts += 1
                elif reason == "error":
                    self.failed += 1
                else:
                    self.aborted += 1

    # ---- goodput / warmup ----
    def begin_warmup(self):
        """Tokens finished until end_warmup() classify as warmup, not
        useful — benches/servers call this around bucket warm loops so
        goodput reflects steady-state serving only."""
        with self._lock:
            self._warmup = True

    def end_warmup(self):
        with self._lock:
            self._warmup = False

    # ---- fault accounting ----
    def record_error(self, where, exc):
        """An internal failure was observed at `where` (slot_join,
        decode_step, stream_cb, callback.*, server_crash, ...): bump
        the counter and keep a last-error snapshot for operators."""
        with self._lock:
            self.errors += 1
            self.last_error = {"where": where,
                               "type": type(exc).__name__,
                               "message": str(exc),
                               "at": self._clock()}

    def record_retry(self, where, n_tokens=0):
        """A failed op is being retried; for decode steps `n_tokens` is
        the active-slot count — the token-slots of work the failed
        attempt burned (goodput's retry term)."""
        with self._lock:
            self.retries += 1
            self.retry_tokens += int(n_tokens)

    def record_eviction_on_error(self, n=1):
        with self._lock:
            self.evictions_on_error += n

    def record_fallback(self):
        with self._lock:
            self.fallbacks += 1

    def record_prefix(self, kind, matched_tokens=0, prompt_tokens=0):
        """A paged join consulted the prefix cache. `kind` is "whole"
        (every prompt page mapped shared, zero prefill), "partial"
        (matched prefix mapped, only the divergent tail prefilled) or
        "miss" (full cold prefill); bools keep the pre-radix contract
        (True = whole). The token counts feed hit_token_ratio — the
        prefill-FLOPs savings the radix cache exists for."""
        if isinstance(kind, bool):
            kind = "whole" if kind else "miss"
        with self._lock:
            self._prefix_recorded = True
            if kind == "whole":
                self.prefix_hits += 1
                self.prefix_whole_hits += 1
            elif kind == "partial":
                self.prefix_hits += 1
                self.prefix_partial_hits += 1
            else:
                self.prefix_misses += 1
            self.prefix_matched_tokens += int(matched_tokens)
            self.prefix_prompt_tokens += int(prompt_tokens)

    def record_cow_copy(self, n=1):
        """A copy-on-write page copy ran (a joiner's decode tail page
        was shared, or a partial hit diverged mid-page)."""
        with self._lock:
            self._prefix_recorded = True
            self.cow_copies += n

    def record_page_wait(self):
        """Admission deferred: not enough free pages for the queue head
        (the OutOfPages backpressure path — the request stays queued)."""
        with self._lock:
            self.page_waits += 1

    def record_oom_eviction(self, n=1):
        with self._lock:
            self.oom_evictions += n

    # ---- multi-tenant accounting (the AdapterPool mirrors its own
    # events here via bind_metrics; the engine records the waits) ----
    def record_adapter_acquire(self, hit):
        """An adapter acquire resolved: hit = an already-hot bank row
        (the adapter cache), miss = a load had to run."""
        with self._lock:
            self._tenancy = True
            if hit:
                self.adapter_hits += 1
            else:
                self.adapter_misses += 1

    def record_adapter_load(self):
        with self._lock:
            self._tenancy = True
            self.adapter_loads += 1

    def record_adapter_eviction(self):
        with self._lock:
            self._tenancy = True
            self.adapter_evictions += 1

    def record_adapter_wait(self):
        """Admission deferred: every adapter row pinned by live slots
        (the OutOfAdapters backpressure path — the request stays
        queued at the head)."""
        with self._lock:
            self._tenancy = True
            self.adapter_waits += 1

    # ---- traffic shaping (PR 19) ----
    def record_chunked_join(self):
        """A join went chunked: the prompt exceeded prefill_chunk, so
        its prefill will interleave with decode steps chunk by chunk."""
        with self._lock:
            self._slo = True
            self.chunked_prefills += 1

    def record_chunk(self):
        """One prefill chunk dispatched between decode steps."""
        with self._lock:
            self._slo = True
            self.chunks += 1

    def record_preemption(self):
        """A batch-class slot was evicted to the prefix cache to free
        capacity for higher-priority work."""
        with self._lock:
            self._slo = True
            self.preemptions += 1

    def record_resume(self):
        """A preempted request re-joined (resume rides the prefix
        cache whole-hit attach — prefill_count proves no re-prefill)."""
        with self._lock:
            self._slo = True
            self.resumes += 1

    def record_replay_token(self):
        """A resumed request re-produced an already-delivered token;
        the engine absorbed it silently (no double delivery)."""
        with self._lock:
            self._slo = True
            self.replay_tokens += 1

    def record_slo_finish(self, name, ttft_s, tpot_s, ttft_target_s,
                          tpot_target_s):
        """An SLO-classed request completed: fold its TTFT/TPOT against
        the class targets into the per-class attainment fractions."""
        with self._lock:
            self._slo = True
            c = self.slo_finishes.setdefault(
                name, {"n": 0, "ttft_ok": 0, "tpot_ok": 0})
            c["n"] += 1
            if float(ttft_s) <= float(ttft_target_s):
                c["ttft_ok"] += 1
            if float(tpot_s) <= float(tpot_target_s):
                c["tpot_ok"] += 1

    def set_wfq_lag(self, lag_by_tenant):
        """The ShapingScheduler's per-tenant WFQ virtual-time lag at
        the last iteration (pending finish tag minus pool virtual
        time; 0 = the tenant is keeping pace with its weight)."""
        with self._lock:
            if lag_by_tenant:
                self._slo = True
            self.wfq_lag = {str(t): round(float(v), 4)
                            for t, v in lag_by_tenant.items()}

    # ---- HBM ledger / MFU accounting (PR 9) ----
    def set_memory_provider(self, provider, budget_bytes=None,
                            watermark_frac=None):
        """Register the engine's ledger closure: `provider()` returns
        {weights_bytes, pool_bytes, in_use_bytes,
        compile_temp_peak_bytes} (or None before the pool exists).
        snapshot() calls it OUTSIDE the metrics lock."""
        with self._lock:
            self._memory_provider = provider
            if budget_bytes is not None:
                self.budget_bytes = int(budget_bytes)
            if watermark_frac is not None:
                self.watermark_frac = float(watermark_frac)

    def check_memory_watermark(self, in_use_bytes):
        """Engine-side liveness check against the configured budget:
        the first crossing of watermark_frac * budget bumps the warning
        counter (and arms hysteresis so a pool hovering at the line
        warns once per excursion, not per iteration). Returns True
        while above the watermark."""
        if self.budget_bytes <= 0:
            return False
        above = in_use_bytes >= self.watermark_frac * self.budget_bytes
        with self._lock:
            if above and not self._above_watermark:
                self.watermark_warnings += 1
            self._above_watermark = above
        return above

    def watermark_exceeded(self):
        """True while the ledger last sat above the armed watermark —
        the shaping scheduler's admission gate reads this to pause
        batch-class admission while the pool nears its HBM budget."""
        with self._lock:
            return self._above_watermark

    def record_step_utilization(self, flops, bytes_accessed, dt_s,
                                spec, source):
        """One decode step's roofline position: the compiled program's
        flops / bytes against the DeviceSpec peaks. Armed-only (the
        engine guards on the costs session), so the disarmed hot path
        never reaches here."""
        with self._lock:
            self._mfu = True
            self._spec = spec
            self.cost_source = source
            self.flops_per_step = float(flops)
            self.bytes_per_step = float(bytes_accessed)
            if dt_s > 0:
                self.mfu_util.add(flops / dt_s / spec.peak_flops)
                self.bw_util.add(
                    bytes_accessed / dt_s / spec.peak_bytes_per_s)

    # ---- cold-start accounting (PR 11) ----
    def record_cold_start(self, report):
        """The engine's precompile() report: {time_to_ready_s,
        programs, loaded_from_cache, compiled, cache_errors, warm}.
        A second call (another precompile pass on the same engine)
        accumulates program counts and keeps the first ready time."""
        with self._lock:
            if self._cold_start is None:
                self._cold_start = dict(report)
            else:
                c = self._cold_start
                for k in ("programs", "loaded_from_cache", "compiled",
                          "cache_errors"):
                    c[k] = c.get(k, 0) + int(report.get(k, 0))
                c["warm"] = int(bool(c.get("warm"))
                                and bool(report.get("warm")))

    # ---- speculative-decoding accounting ----
    def record_spec_step(self, n_active, proposed, accepted, draft_s,
                         verify_s, k_eff=None, variant=None,
                         k_shrinks=None, k_grows=None):
        """One speculative iteration: `proposed` draft tokens went into
        the verify step for the spec-enabled active slots, `accepted`
        of them matched the oracle; `draft_s`/`verify_s` are the two
        dispatch wall times. Rejected drafts are wasted verify lanes —
        they join the goodput denominator. `k_eff` is the adaptive
        batch-wide draft depth this round ran at (with the stepper's
        cumulative shrink/grow transition counts), `variant` the pool
        flavor (dense/paged/sharded-*) keying the per-variant step-ms
        split."""
        with self._lock:
            self._spec_recorded = True
            self.spec_rounds += 1
            self.drafts_proposed += int(proposed)
            self.drafts_accepted += int(accepted)
            if n_active:
                self.accepted_per_step.add(accepted / n_active)
            self.draft_step_s.add(draft_s)
            self.verify_step_s.add(verify_s)
            if k_eff is not None:
                self.spec_k_eff = int(k_eff)
            if k_shrinks is not None:
                self.spec_k_shrinks = int(k_shrinks)
            if k_grows is not None:
                self.spec_k_grows = int(k_grows)
            if variant is not None:
                v = self._spec_by_variant.get(variant)
                if v is None:
                    v = {"draft": _Reservoir(256),
                         "verify": _Reservoir(256)}
                    self._spec_by_variant[variant] = v
                v["draft"].add(draft_s)
                v["verify"].add(verify_s)

    # ---- sharded-serving accounting ----
    def record_step_gap(self, dt_s):
        """Wall time between two consecutive decode-step completions
        while the pool stayed active: per-token latency as co-resident
        requests experience it, join/prefill stalls included."""
        with self._lock:
            self.step_gap_s.add(dt_s)

    def record_prefill_step(self, dt_s):
        """One prefill-slice step completed (disaggregated: dispatch ->
        arrays ready, polled at iteration granularity; inline: the
        blocking join call)."""
        with self._lock:
            self._sharded = True
            self.prefill_step_s.add(dt_s)

    def record_collective(self, dt_s):
        """Host-timed cross-slice communication: a prefill-slice ->
        decode-slice K/V transfer (or a param re-placement). In-program
        collectives are XLA's to schedule and are not visible here;
        this tracks the traffic the ENGINE moves between mesh slices."""
        with self._lock:
            self._sharded = True
            self.collective_s += float(dt_s)
            self.collective_events += 1

    def record_iteration(self, queue_depth, occupancy, pages_in_use=None,
                         pages_free=None, bytes_per_active_token=None,
                         shard_occupancy=None, tenant_slots=None,
                         trie_nodes=None, trie_pages=None):
        with self._lock:
            self.iterations += 1
            self.queue_depth.add(queue_depth)
            self.occupancy.add(occupancy)
            if tenant_slots is not None:
                self._tenancy = True
                self.tenant_slots = dict(tenant_slots)
            if pages_in_use is not None:
                self.pages_in_use = int(pages_in_use)
            if pages_free is not None:
                self.pages_free = int(pages_free)
            if trie_nodes is not None:
                self.trie_nodes = int(trie_nodes)
            if trie_pages is not None:
                self.trie_pages = int(trie_pages)
            if bytes_per_active_token is not None:
                self.bytes_per_token.add(bytes_per_active_token)
            if shard_occupancy is not None:
                self._sharded = True
                self.shard_occupancy = [round(float(x), 3)
                                        for x in shard_occupancy]

    # ---- reading ----
    def snapshot(self):
        # the ledger provider walks engine state — call it OUTSIDE the
        # metrics lock (it must stay free to call metrics methods)
        ledger = None
        if self._memory_provider is not None:
            try:
                ledger = self._memory_provider()
            except Exception:
                ledger = None
        with self._lock:
            tps = (self.decode_tokens / self.decode_time_s
                   if self.decode_time_s > 0 else 0.0)
            mem = None
            if ledger is not None:
                w = int(ledger.get("weights_bytes", 0))
                p = int(ledger.get("pool_bytes", 0))
                a = int(ledger.get("adapter_bytes", 0))
                used = int(ledger.get("in_use_bytes", w + p + a))
                b = self.budget_bytes
                mem = {
                    "weights_bytes": w,
                    "pool_bytes": p,
                    "adapter_bytes": a,
                    "total_bytes": w + p + a,
                    "in_use_bytes": used,
                    "budget_bytes": b,
                    "budget_used_frac":
                        round(used / b, 4) if b > 0 else 0.0,
                    "compile_temp_peak_bytes":
                        int(ledger.get("compile_temp_peak_bytes", 0)),
                    "watermark_warnings": self.watermark_warnings,
                }
            wasted_drafts = self.drafts_proposed - self.drafts_accepted
            good_denom = (self.useful_tokens + self.wasted_tokens +
                          self.warmup_tokens + self.retry_tokens +
                          wasted_drafts)
            return {
                "requests": {"submitted": self.submitted,
                             "completed": self.completed,
                             "rejected": self.rejected,
                             "cancelled": self.cancelled,
                             "timeouts": self.timeouts,
                             "failed": self.failed,
                             "aborted": self.aborted},
                "errors": {"count": self.errors,
                           "retries": self.retries,
                           "evictions_on_error":
                               self.evictions_on_error,
                           "fallbacks": self.fallbacks,
                           "last": self.last_error},
                "joins": self.joins,
                "iterations": self.iterations,
                "tokens_out": self.tokens_out,
                "tokens_per_s": round(tps, 1),
                "ttft_ms": self.ttft_s.summary(scale=1e3),
                "per_token_ms": self.token_latency_s.summary(scale=1e3),
                "queue_depth": self.queue_depth.summary(digits=2),
                "slot_occupancy": self.occupancy.summary(digits=3),
                "goodput": {
                    "useful_tokens": self.useful_tokens,
                    "wasted_tokens": self.wasted_tokens,
                    "warmup_tokens": self.warmup_tokens,
                    "retry_tokens": self.retry_tokens,
                    "ratio": round(self.useful_tokens / good_denom, 4)
                    if good_denom else 1.0,
                },
                **({} if not self._tenancy else {"tenancy": {
                    "tenants": len(self.tokens_by_tenant),
                    "active_slots_by_tenant":
                        dict(self.tenant_slots or {}),
                    "tokens_by_tenant": dict(self.tokens_by_tenant),
                    "adapter_loads": self.adapter_loads,
                    "adapter_evictions": self.adapter_evictions,
                    "adapter_hit_rate": round(
                        self.adapter_hits /
                        max(1, self.adapter_hits +
                            self.adapter_misses), 4),
                    "adapter_waits": self.adapter_waits,
                    "fairness": _jain(self.tokens_by_tenant),
                }}),
                **({} if self._cold_start is None else {"cold_start": {
                    "time_to_ready_s":
                        self._cold_start.get("time_to_ready_s", 0.0),
                    "programs": self._cold_start.get("programs", 0),
                    "loaded_from_cache":
                        self._cold_start.get("loaded_from_cache", 0),
                    "compiled": self._cold_start.get("compiled", 0),
                    "cache_errors":
                        self._cold_start.get("cache_errors", 0),
                    "warm": int(bool(self._cold_start.get("warm"))),
                    "first_ttft_ms":
                        None if self.first_ttft_s is None else
                        round(self.first_ttft_s * 1e3, 3),
                }}),
                **({} if not self._spec_recorded else {"speculation": {
                    "rounds": self.spec_rounds,
                    "drafts_proposed": self.drafts_proposed,
                    "drafts_accepted": self.drafts_accepted,
                    "acceptance_rate": round(
                        self.drafts_accepted /
                        max(1, self.drafts_proposed), 4),
                    "accepted_per_step":
                        self.accepted_per_step.summary(digits=3),
                    "draft_step_ms":
                        self.draft_step_s.summary(scale=1e3),
                    "verify_step_ms":
                        self.verify_step_s.summary(scale=1e3),
                    "wasted_draft_tokens": wasted_drafts,
                    "effective_k": self.spec_k_eff,
                    "k_shrink_events": self.spec_k_shrinks,
                    "k_grow_events": self.spec_k_grows,
                    "step_ms_by_variant": {
                        v: {"draft_p50":
                                r["draft"].summary(scale=1e3)
                                .get("p50"),
                            "verify_p50":
                                r["verify"].summary(scale=1e3)
                                .get("p50")}
                        for v, r in self._spec_by_variant.items()},
                }}),
                **({} if mem is None else {"memory": mem}),
                **({} if not self._mfu else {"mfu": {
                    "device": self._spec.as_dict(),
                    "cost_source": self.cost_source,
                    "flops_per_step": self.flops_per_step,
                    "bytes_per_step": self.bytes_per_step,
                    "model_flops_util": self.mfu_util.summary(digits=5),
                    "bandwidth_util": self.bw_util.summary(digits=5),
                }}),
                **({} if not self._sharded else {"sharding": {
                    # prefill-slice vs decode-slice step latency: the
                    # disaggregation split's two phases side by side
                    "prefill_step_ms":
                        self.prefill_step_s.summary(scale=1e3),
                    "decode_step_ms":
                        self.token_latency_s.summary(scale=1e3),
                    "step_gap_ms": self.step_gap_s.summary(scale=1e3),
                    "per_shard_occupancy": self.shard_occupancy,
                    "collective_ms": round(self.collective_s * 1e3, 3),
                    "collective_events": self.collective_events,
                    "collective_time_share": round(
                        self.collective_s /
                        max(1e-9, self.collective_s + self.decode_time_s
                            + sum(self.prefill_step_s._buf)), 4),
                }}),
                **({} if self.pages_in_use is None else {"paging": {
                    "pages_in_use": self.pages_in_use,
                    "pages_free": self.pages_free,
                    "prefix_hits": self.prefix_hits,
                    "prefix_misses": self.prefix_misses,
                    "prefix_hit_rate": round(
                        self.prefix_hits /
                        max(1, self.prefix_hits + self.prefix_misses),
                        3),
                    "page_waits": self.page_waits,
                    "oom_evictions": self.oom_evictions,
                    "bytes_per_active_token":
                        self.bytes_per_token.summary(digits=1),
                }}),
                **({} if not self._slo else {"slo": {
                    "preemptions": self.preemptions,
                    "resumes": self.resumes,
                    "replay_tokens": self.replay_tokens,
                    "chunked_prefills": self.chunked_prefills,
                    "chunks": self.chunks,
                    "ttft_attainment": {
                        n: round(c["ttft_ok"] / max(1, c["n"]), 4)
                        for n, c in self.slo_finishes.items()},
                    "tpot_attainment": {
                        n: round(c["tpot_ok"] / max(1, c["n"]), 4)
                        for n, c in self.slo_finishes.items()},
                    "wfq_lag_by_tenant": dict(self.wfq_lag),
                }}),
                **({} if not self._prefix_recorded else {"prefix": {
                    "whole_hits": self.prefix_whole_hits,
                    "partial_hits": self.prefix_partial_hits,
                    "misses": self.prefix_misses,
                    "hit_token_ratio": round(
                        self.prefix_matched_tokens /
                        max(1, self.prefix_prompt_tokens), 4),
                    "cow_copies": self.cow_copies,
                    "trie_nodes": self.trie_nodes or 0,
                    "trie_pages": self.trie_pages or 0,
                }}),
            }


class ServingCallback:
    """hapi-style hook surface: subclass, override what you need, pass
    instances to the engine/server. Every hook is a no-op by default;
    hooks run on the engine thread, so keep them cheap."""

    def on_submit(self, request):
        pass

    def on_reject(self, request, reason):
        pass

    def on_join(self, request, slot):
        pass

    def on_token(self, request, token):
        pass

    def on_finish(self, request):
        pass

    def on_iteration(self, stats):
        pass


class CallbackList:
    """Fan-out invoker (mirrors hapi.callbacks.CallbackList): exceptions
    in one hook never take down the serving loop — they are reported to
    `on_error(hook_name, exc)` (the engine routes it into
    ServingMetrics.record_error) instead of vanishing."""

    def __init__(self, callbacks=(), on_error=None):
        self.callbacks = list(callbacks)
        self.on_error = on_error

    def append(self, cb):
        self.callbacks.append(cb)

    def emit(self, name, *args):
        for cb in self.callbacks:
            fn = getattr(cb, name, None)
            if fn is None:
                continue
            try:
                fn(*args)
            except Exception as e:
                if self.on_error is not None:
                    self.on_error(name, e)
