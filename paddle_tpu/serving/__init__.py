"""Continuous-batching serving runtime.

An always-on generation engine with iteration-level (Orca/vLLM-style)
batching over the static KV cache:

  * `engine.ServingEngine` — fixed pool of S cache slots; ONE jitted
    decode step of static shape [S, ...] with a per-slot active mask;
    slot join = batch-1 bucketed prefill spliced into the live pool
    (never retraces);
  * `paging` / `engine.PagedServingEngine` (`ServingEngine(...,
    paged=True)`) — the slot pool over a global pool of fixed-size KV
    pages: free-list + refcount `PageAllocator`, per-slot int32 page
    table (traced input — page mapping never retraces), whole-prompt
    `PrefixCache` with zero-re-prefill shared joins + copy-on-write,
    fp32/bf16/int8 pages behind `kv_dtype=`, free-page admission with
    `OutOfPages` backpressure (README "Paged KV cache");
  * `scheduler.Scheduler` / `Request` — bounded FIFO admission with
    backpressure (`QueueFull`), deadlines, cancellation, drain;
  * `shaping.ShapingScheduler` / `SLOClass` — the traffic-shaping
    control plane over the same surface (README "Traffic shaping"):
    SLO classes (interactive vs batch TTFT/TPOT targets), weighted
    fair queueing across tenants, preemption of batch slots to the
    prefix cache, and watermark/goodput admission gating; pairs with
    the engines' `prefill_chunk=` chunked prefill so one long prompt
    never stalls co-resident decode;
  * `server.ServingServer` — thread frontend: submit() -> future with
    per-token streaming;
  * `metrics.ServingMetrics` — TTFT / per-token latency / tokens/s /
    queue depth / occupancy, `snapshot()` (schema of record:
    `SNAPSHOT_DOCS`; Prometheus text dump via `to_prometheus` /
    tools/metrics_dump.py) + hapi-style callbacks;
  * `tracing` — per-request span timelines over `profiler.trace`
    (queue -> join/prefill -> decode -> finish waterfalls, compile
    observer, chrome-trace export) and the `retrace_sentinel` standing
    "never retraces" assertion (README "Observability").

Multi-tenant serving (README "Multi-tenant serving"):
`adapters.AdapterPool` serves many LoRA fine-tunes from ONE slot pool —
per-slot adapter ids as traced inputs + stacked A/B banks gathered in
ONE batched matmul inside the existing step programs (tenant switches
and hot-load/evict never retrace), refcounted bank rows with
`OutOfAdapters` backpressure, and `quantize="int8"` base weights
(symmetric per-output-channel, fp32 compute) shrinking the shared base
so the freed HBM pays for slots and adapters.

Failure isolation (README "Fault tolerance"): joins/decodes run under
retry+backoff with an optional watchdog; a failed join kills one
future (or degrades to `generate_eager`), a failed decode step evicts
in-flight requests with partials + the cause and the pool keeps
serving, and a wedged loop marks the server dead (`ServerCrashed`)
with every future resolved. All of it is deterministically testable
via the `serving.*` fault points in `paddle_tpu.testing.faults`.
"""
from .adapters import AdapterPool, OutOfAdapters, quantize_net
from .engine import (ArtifactServingEngine, PagedServingEngine,
                     PoolCarryLost, ServingEngine, WatchdogTimeout)
from .metrics import (CallbackList, ServingCallback, ServingMetrics,
                      to_prometheus)
from .paging import (OutOfPages, PageAllocator, PagedKVCache,
                     PrefixCache, RadixPrefixCache)
from .scheduler import QueueFull, Request, RequestResult, Scheduler
from .server import ServerCrashed, ServingServer
from .shaping import BATCH, INTERACTIVE, ShapingScheduler, SLOClass
from .sharded import ShardedPagedServingEngine, ShardedServingEngine
from .tracing import (RetraceError, RetraceSentinel, retrace_sentinel,
                      session_scope)

__all__ = [
    "ServingEngine", "PagedServingEngine", "ArtifactServingEngine",
    "ShardedServingEngine", "ShardedPagedServingEngine",
    "ServingServer", "Scheduler", "Request", "RequestResult",
    "QueueFull", "ServingMetrics", "ServingCallback", "CallbackList",
    "WatchdogTimeout", "PoolCarryLost", "ServerCrashed", "OutOfPages",
    "PageAllocator",
    "PagedKVCache", "PrefixCache", "RadixPrefixCache", "RetraceError",
    "RetraceSentinel",
    "retrace_sentinel", "session_scope", "to_prometheus",
    "AdapterPool", "OutOfAdapters", "quantize_net",
    "ShapingScheduler", "SLOClass", "INTERACTIVE", "BATCH",
]
