"""Continuous-batching serving runtime.

An always-on generation engine with iteration-level (Orca/vLLM-style)
batching over the static KV cache:

  * `engine.ServingEngine` — fixed pool of S cache slots; ONE jitted
    decode step of static shape [S, ...] with a per-slot active mask;
    slot join = batch-1 bucketed prefill spliced into the live pool
    (never retraces);
  * `scheduler.Scheduler` / `Request` — bounded FIFO admission with
    backpressure (`QueueFull`), deadlines, cancellation, drain;
  * `server.ServingServer` — thread frontend: submit() -> future with
    per-token streaming;
  * `metrics.ServingMetrics` — TTFT / per-token latency / tokens/s /
    queue depth / occupancy, `snapshot()` + hapi-style callbacks.

Failure isolation (README "Fault tolerance"): joins/decodes run under
retry+backoff with an optional watchdog; a failed join kills one
future (or degrades to `generate_eager`), a failed decode step evicts
in-flight requests with partials + the cause and the pool keeps
serving, and a wedged loop marks the server dead (`ServerCrashed`)
with every future resolved. All of it is deterministically testable
via the `serving.*` fault points in `paddle_tpu.testing.faults`.
"""
from .engine import ArtifactServingEngine, ServingEngine, WatchdogTimeout
from .metrics import CallbackList, ServingCallback, ServingMetrics
from .scheduler import QueueFull, Request, RequestResult, Scheduler
from .server import ServerCrashed, ServingServer

__all__ = [
    "ServingEngine", "ArtifactServingEngine", "ServingServer",
    "Scheduler", "Request", "RequestResult", "QueueFull",
    "ServingMetrics", "ServingCallback", "CallbackList",
    "WatchdogTimeout", "ServerCrashed",
]
