"""Continuous-batching serving runtime.

An always-on generation engine with iteration-level (Orca/vLLM-style)
batching over the static KV cache:

  * `engine.ServingEngine` — fixed pool of S cache slots; ONE jitted
    decode step of static shape [S, ...] with a per-slot active mask;
    slot join = batch-1 bucketed prefill spliced into the live pool
    (never retraces);
  * `scheduler.Scheduler` / `Request` — bounded FIFO admission with
    backpressure (`QueueFull`), deadlines, cancellation, drain;
  * `server.ServingServer` — thread frontend: submit() -> future with
    per-token streaming;
  * `metrics.ServingMetrics` — TTFT / per-token latency / tokens/s /
    queue depth / occupancy, `snapshot()` + hapi-style callbacks.

See the "Serving runtime" section of the README for the slot
lifecycle, backpressure and deadline semantics, and the metrics table.
"""
from .engine import ArtifactServingEngine, ServingEngine
from .metrics import CallbackList, ServingCallback, ServingMetrics
from .scheduler import QueueFull, Request, RequestResult, Scheduler
from .server import ServingServer

__all__ = [
    "ServingEngine", "ArtifactServingEngine", "ServingServer",
    "Scheduler", "Request", "RequestResult", "QueueFull",
    "ServingMetrics", "ServingCallback", "CallbackList",
]
