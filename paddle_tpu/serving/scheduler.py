"""Admission control for the continuous-batching serving runtime.

Requests arrive at arbitrary times; slots free up one request at a
time. The scheduler sits between them as a bounded FIFO with the
serving-side policies the engine itself should not know about:

  * **backpressure** — past the high-water mark `submit` raises
    `QueueFull` instead of queueing unboundedly (the caller sheds load
    or retries; an unbounded queue just converts overload into
    timeouts);
  * **deadline / timeout eviction** — a request carries an absolute
    `deadline` (engine clock); expired requests are finalized with
    their partial output instead of occupying a slot;
  * **cancellation** — `Request.cancel()` marks the request; queued
    requests are finalized on the next pop, in-flight ones are evicted
    by the engine's fault harvest at the next iteration boundary;
  * **graceful drain** — `drain()` closes admission while everything
    already accepted runs to completion.

The scheduler never touches device state: it hands `Request` objects to
the engine's `run_iteration` and finalizes the ones that die in the
queue. All methods are thread-safe; `Request.future` is a
`concurrent.futures.Future` resolving to a `RequestResult` (partial
tokens included for timeout/cancel — delivery semantics are "best
effort by the deadline", not all-or-nothing)."""
from __future__ import annotations

import collections
import itertools
import threading
import time

import numpy as np
from concurrent.futures import Future, InvalidStateError

from ..profiler import trace as _trace
from ..testing import faults
from . import tracing as _rt

__all__ = ["QueueFull", "Request", "RequestResult", "Scheduler"]

_PT_ADMIT = faults.point("scheduler.admit")

#: terminal finish reasons
FINISH_REASONS = ("eos", "length", "cancelled", "timeout", "drain",
                  "shutdown", "error")


class QueueFull(RuntimeError):
    """Backpressure: the bounded request queue is at its high-water
    mark. Shed load or retry later."""


class RequestResult(collections.namedtuple(
        "RequestResult", ["tokens", "finish_reason", "ttft_s",
                          "latency_s", "error"])):
    """What a request's future resolves to. `tokens` is the generated
    int32 array (possibly partial for timeout/cancel), `finish_reason`
    one of FINISH_REASONS, `ttft_s`/`latency_s` the request's own
    time-to-first-token and end-to-end latency (None when it never
    produced a token). `error` carries the cause when the engine
    evicted the request on an internal failure (finish_reason
    "error" with partial tokens) — None otherwise."""
    __slots__ = ()

    def __new__(cls, tokens, finish_reason, ttft_s=None, latency_s=None,
                error=None):
        return super().__new__(cls, tokens, finish_reason, ttft_s,
                               latency_s, error)

    @property
    def ok(self):
        return self.finish_reason in ("eos", "length", "drain")


class Request:
    """One generation request. Built by the frontend (or directly in
    tests), consumed by the scheduler + engine. Host-side only."""

    _ids = itertools.count()

    def __init__(self, prompt, memory=None, *, max_new_tokens=32,
                 eos_id=1, deadline=None, stream_cb=None, spec=True,
                 adapter=None, slo=None):
        prompt = np.asarray(prompt)
        if prompt.ndim != 1:
            raise ValueError(f"prompt must be 1-D [P], got "
                             f"{prompt.shape}")
        self.id = next(Request._ids)
        self.prompt = prompt
        self.memory = None if memory is None else np.asarray(memory)
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.eos_id = eos_id
        self.deadline = deadline      # absolute engine-clock seconds
        self.stream_cb = stream_cb    # called (request, token) per token
        # speculative decoding opt-out: on a spec-enabled engine a
        # spec=False request decodes one oracle token per step (its
        # draft lanes ride along unmatched) — output is identical
        # either way, this only trades verify width for latency
        self.spec = bool(spec)
        # multi-tenant serving: the registered adapter name this
        # request decodes under (None = the base model; per-request
        # opt-out rides the same compiled program with bank row 0's
        # zero delta)
        self.adapter = adapter
        # traffic shaping (serving.shaping): the request's SLO class —
        # an SLOClass, or a class name the ShapingScheduler resolves at
        # submit. None under the plain FIFO = no class semantics.
        self.slo = slo
        # preemption bookkeeping (paged engines only): tokens already
        # delivered that a post-resume replay must re-absorb silently,
        # and how many times this request has been preempted
        self._replay = 0
        self._preemptions = 0
        self.tokens = []              # generated so far (ints)
        self.state = "QUEUED"         # QUEUED -> RUNNING -> DONE
        self.finish_reason = None
        self.future = Future()
        self.slot = None
        self.submitted_at = None
        self.first_token_at = None
        self.finished_at = None
        self._cancelled = threading.Event()
        self._trace = None            # _ReqTrace under a tracer session

    # ---- caller-facing ----
    def cancel(self):
        """Request cancellation. Queued requests die on the next
        scheduler pop; in-flight ones are evicted at the next engine
        iteration boundary (their partial tokens are delivered)."""
        self._cancelled.set()

    @property
    def cancelled(self):
        return self._cancelled.is_set()

    def result(self, timeout=None):
        """Block for the RequestResult (partial for timeout/cancel)."""
        return self.future.result(timeout)

    # ---- engine/scheduler-facing ----
    def expired(self, now):
        return self.deadline is not None and now >= self.deadline

    def finish(self, reason, now, error=None):
        if self.state == "DONE":      # idempotent: harvest races drain
            return
        self.state = "DONE"
        self.finish_reason = reason
        self.finished_at = now
        if self._trace is not None:
            _rt.on_finish(self, reason, error)
        ttft = (None if self.first_token_at is None or
                self.submitted_at is None
                else self.first_token_at - self.submitted_at)
        lat = (None if self.submitted_at is None
               else now - self.submitted_at)
        try:
            self.future.set_result(RequestResult(
                np.asarray(self.tokens, np.int32), reason, ttft, lat,
                error))
        except InvalidStateError:
            pass   # already failed by a server-crash declaration

    def fail(self, exc, now):
        """Fail THIS request's future with the cause (per-request
        isolation: a broken join/admission kills one future, never the
        pool). Idempotent against a concurrent finish()."""
        if self.state == "DONE":
            return
        self.state = "DONE"
        self.finish_reason = "error"
        self.finished_at = now
        if self._trace is not None:
            _rt.on_finish(self, "error", exc)
        try:
            self.future.set_exception(exc)
        except InvalidStateError:
            pass


class Scheduler:
    """Bounded FIFO with deadline/cancel screening and drain."""

    def __init__(self, max_queue=64, clock=time.monotonic):
        self.max_queue = int(max_queue)
        self.clock = clock
        self._q = collections.deque()
        self._lock = threading.Lock()
        self._draining = False

    def submit(self, request):
        """Enqueue, or raise QueueFull past the high-water mark /
        RuntimeError after drain started. Sets `submitted_at`."""
        now = self.clock()
        _PT_ADMIT()   # fault point: an injected raise = admission lost
        with self._lock:
            if self._draining:
                raise RuntimeError("scheduler is draining: admission "
                                   "closed")
            if len(self._q) >= self.max_queue:
                raise QueueFull(
                    f"request queue at high-water mark "
                    f"({self.max_queue}); shed load or retry")
            request.submitted_at = now
            self._q.append(request)
        if _trace._SESSION is not None:
            _rt.on_submit(request)
        return request

    def pop_ready(self, now=None, on_dead=None):
        """Next admissible request (FIFO), finalizing any queued
        request that was cancelled or missed its deadline on the way
        (`on_dead(request)` fires for each — the engine's metrics
        hook). Returns None when the queue is empty."""
        if now is None:
            now = self.clock()
        while True:
            with self._lock:
                if not self._q:
                    return None
                r = self._q.popleft()
            if r.cancelled or r.expired(now):
                r.finish("cancelled" if r.cancelled else "timeout", now)
                if on_dead is not None:
                    on_dead(r)
                continue
            if r._trace is not None:
                _rt.on_queue_exit(r)
            return r

    def push_front(self, request):
        """Return an already-admitted request to the HEAD of the queue
        (FIFO order preserved): the paged engine's page-headroom gate
        defers the queue head when free pages can't cover its prompt +
        reservation — OutOfPages backpressure keeps it queued instead
        of failing it. Bypasses the high-water mark and drain checks on
        purpose: the request was admitted once already."""
        if request._trace is not None:
            _rt.on_requeue(request)
        with self._lock:
            self._q.appendleft(request)

    def depth(self):
        with self._lock:
            return len(self._q)

    # ---- drain / teardown ----
    def drain(self):
        """Close admission; queued + running work keeps flowing."""
        with self._lock:
            self._draining = True

    @property
    def draining(self):
        return self._draining

    def pop_all(self):
        """Drain the queue raw (server-crash path): the requests are
        returned unfinalized for the caller to fail/finish."""
        with self._lock:
            out = list(self._q)
            self._q.clear()
        return out

    def abort_queued(self, reason, now=None):
        """Finalize everything still queued (non-drain shutdown)."""
        if now is None:
            now = self.clock()
        out = []
        while True:
            with self._lock:
                if not self._q:
                    return out
                r = self._q.popleft()
            r.finish(reason if not r.cancelled else "cancelled", now)
            out.append(r)
