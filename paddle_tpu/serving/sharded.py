"""Mesh-sharded serving: tp/fsdp-parallel decode over the slot pool.

The single-chip engines (engine.py) bound the servable model by one
device's memory and pin the pool size to one chip. This module runs the
SAME slot lifecycle over a `parallel.mesh` Mesh:

  * **weights** lay out tp/fsdp via `parallel.sharding`'s
    `serving_param_rules` — the default ``"gathered"`` layout shards
    every large weight's output-feature dim (vocab dim for embeddings)
    jointly over (fsdp, tp), so the SPMD partitioner materializes
    activations by all-gather (concatenation), never by partial-sum
    psum: float reduction order is untouched and every request's tokens
    stay BIT-IDENTICAL to the single-chip engine. ``layout="megatron"``
    flips to the canonical TP layout (contraction dims split, psum per
    matmul) where interconnect bandwidth beats the bit-exact contract;
  * **the slot pool** shards its slot axis data-parallel over ``dp``:
    pooled `StaticKVCache` rows / `PagedKVCache` pages + scales,
    per-row write indices, bias rows, memory rows, cross-attn K/V, and
    the paged engine's table/index all carry `PartitionSpec("dp")`
    leading dims, pinned with `with_sharding_constraint` on EVERY carry
    of the decode step — the pool scales with the mesh;
  * **the decode step stays ONE jitted per-pool-config call**: the
    engine bodies are the single-chip ones (engine.py `_*_body`),
    re-wrapped in sharding annotations (`ops.attention.decode_shardings`
    spec-annotates the unchanged decode kernels) — joins, evictions and
    page maps never retrace, proven by the same `trace_counts` keys;
  * **prefill/decode disaggregation** (``prefill="disaggregated"``):
    the dp axis is carved into a decode slice and a prefill slice
    (`DeviceMesh.slice_axis`), prompt prefill runs asynchronously on
    the prefill slice's own weight copy, and the finished K/V is
    spliced into the live pool (`static_kv_splice`/`splice_rows` with
    the pool constraints) once its arrays are ready — a long-prompt
    join no longer blocks the decode step, which shows up directly in
    the `step_gap_ms` (decode-step inter-arrival) metric the
    `serving_sharded` bench A/Bs.

Numerics contract (fp32, ``layout="gathered"``): every request's token
stream bit-matches both the single-chip `ServingEngine` and a solo
`generate_eager` run — tests/test_serving_sharded.py soaks it on the
8-device CPU mesh with ragged arrivals, chaos cells, and the
single-trace-per-bucket proof.
"""
from __future__ import annotations

import time

import numpy as np

from ..core.bucketing import bucket_size, pad_prompt_row
from ..testing import faults
from . import tracing as _rt
from .engine import (PagedServingEngine, ServingEngine, _PT_PREFILL,
                     _tree_bytes)

__all__ = ["ShardedServingEngine", "ShardedPagedServingEngine"]

#: fault point for the disaggregated splice (prefill-slice K/V landing
#: in the live pool) — chaos tests pin per-request isolation on it
_PT_SPLICE = faults.point("serving.prefill_splice")


class ShardedServingEngine(ServingEngine):
    """`ServingEngine` over a device mesh. Use exactly like the
    single-chip engine; extra knobs:

      mesh       parallel.DeviceMesh (default: the installed global
                 mesh). Needs a ``dp`` axis whose size divides
                 `num_slots`; ``fsdp``/``tp`` axes engage weight
                 sharding when present (absent axes are dropped from
                 the rules, so the same engine runs on a dp-only mesh).
      rules      parallel.ShardingRules for the step-net weights
                 (default: `serving_param_rules(layout)`).
      layout     "gathered" (bit-exact, default) | "megatron".
      prefill    "inline" (joins block, single-chip semantics) |
                 "disaggregated" (prompt prefill runs on a dedicated
                 dp slice with its own weight copy; joins splice in
                 asynchronously).
      prefill_dp how many dp rows the prefill slice takes (default 1).

    `paged=True` routes to `ShardedPagedServingEngine` the same way
    `ServingEngine(paged=True)` routes to the paged pool.

    Weights are PLACED at construction: after updating the underlying
    layers call `refresh_params()` to re-place them on the mesh.
    """

    _accepts_sharded_params = True

    def __new__(cls, *args, **kw):
        if cls is ShardedServingEngine and kw.get("paged"):
            return object.__new__(ShardedPagedServingEngine)
        return object.__new__(cls)

    def __init__(self, decoder, embed, project, *, mesh=None, rules=None,
                 layout="gathered", prefill="inline", prefill_dp=1,
                 num_slots=8, max_len=128, **kw):
        from ..parallel.mesh import get_mesh
        from ..parallel.sharding import serving_param_rules

        self._mesh = mesh if mesh is not None else get_mesh()
        self._rules = rules if rules is not None \
            else serving_param_rules(layout)
        self.layout = layout
        if prefill not in ("inline", "disaggregated"):
            raise ValueError(
                f"prefill policy must be 'inline' or 'disaggregated', "
                f"got {prefill!r}")
        self._prefill_policy = prefill
        dp = self._mesh.axis_size("dp")
        if prefill == "disaggregated":
            prefill_dp = int(prefill_dp)
            if dp < prefill_dp + 1:
                raise ValueError(
                    f"disaggregated prefill needs dp >= {prefill_dp + 1} "
                    f"(a decode slice plus {prefill_dp} prefill row(s)); "
                    f"mesh has dp={dp}")
            self._decode_dm = self._mesh.slice_axis(
                "dp", 0, dp - prefill_dp)
            self._prefill_dm = self._mesh.slice_axis(
                "dp", dp - prefill_dp, dp)
        else:
            self._decode_dm = self._mesh
            self._prefill_dm = None
        self._pool_dp = max(1, self._decode_dm.axis_size("dp"))
        if int(num_slots) % self._pool_dp:
            raise ValueError(
                f"num_slots ({num_slots}) must be divisible by the "
                f"decode slice's dp axis ({self._pool_dp}) — the slot "
                f"pool shards over it")
        self._pending_info = {}
        #: seconds a dispatched prefill may stay not-ready before
        #: _poll_pending stops polling and blocks for it (see there)
        self.poll_block_s = 0.5
        super().__init__(decoder, embed, project, num_slots=num_slots,
                         max_len=max_len, **kw)
        self._build_shardings()
        self._place_params()

    # ------------------------------------------------------------------
    # placement
    # ------------------------------------------------------------------
    def _build_shardings(self):
        import jax
        from jax.sharding import PartitionSpec as P

        mesh = self._decode_dm.mesh
        self._ns_pool = jax.sharding.NamedSharding(mesh, P("dp"))
        self._ns_repl = jax.sharding.NamedSharding(mesh, P())

    def _place_params(self):
        """device_put the step-net weights onto the mesh per the layout
        rules (and, when disaggregated, a second copy onto the prefill
        slice). Timed into the collective budget — placement is the
        engine-driven cross-device traffic operators should see."""
        import jax

        from ..parallel.sharding import fitted_sharding, infer_param_specs

        t0 = time.monotonic()
        params = self._fm.params()
        specs = infer_param_specs(params, self._rules)
        self._sparams = {
            n: jax.device_put(v, fitted_sharding(v.shape, specs[n],
                                                 self._decode_dm))
            for n, v in params.items()}
        self._sbuffers = {
            n: jax.device_put(v, self._ns_repl)
            for n, v in self._fm.buffers().items()}
        if self._prefill_dm is not None:
            import jax.sharding as jsh
            from jax.sharding import PartitionSpec as P

            self._pparams = {
                n: jax.device_put(v, fitted_sharding(
                    v.shape, specs[n], self._prefill_dm))
                for n, v in params.items()}
            self._pbuffers = {
                n: jax.device_put(v, jsh.NamedSharding(
                    self._prefill_dm.mesh, P()))
                for n, v in self._fm.buffers().items()}
        self.metrics.record_collective(time.monotonic() - t0)

    def refresh_params(self):
        """Re-place the (possibly updated) layer weights onto the mesh;
        compiled programs are pure and stay cached."""
        self._place_params()
        self._weights_bytes = None   # ledger cache: shapes may change

    # ---- adapter banks on the mesh ----
    def _placed_banks(self):
        """The LoRA banks REPLICATED on the decode mesh (they are tiny
        next to the base weights and every dp shard gathers from
        them), re-placed only when a hot-load bumps the pool version —
        a steady pool pays one int compare per dispatch."""
        pool = self._apool
        cached = getattr(self, "_banks_placed", None)
        if cached is not None and cached[0] == pool.version:
            return cached[1]
        import jax

        placed = jax.device_put(pool.banks(), self._ns_repl)
        self._banks_placed = (pool.version, placed)
        return placed

    def _prefill_banks(self):
        """The banks replicated on the PREFILL slice's mesh (the
        disaggregated prefill program's copy)."""
        pool = self._apool
        cached = getattr(self, "_banks_prefill", None)
        if cached is not None and cached[0] == pool.version:
            return cached[1]
        import jax
        import jax.sharding as jsh
        from jax.sharding import PartitionSpec as P

        placed = jax.device_put(
            pool.banks(),
            jsh.NamedSharding(self._prefill_dm.mesh, P()))
        self._banks_prefill = (pool.version, placed)
        return placed

    def _prefill_adapter_args(self, row):
        if self._apool is None:
            return ()
        import jax.numpy as jnp

        return (jnp.int32(row), self._prefill_banks())

    def _params(self):
        return self._sparams

    def _buffers(self):
        return self._sbuffers

    def weights_bytes(self):
        """GLOBAL logical weight bytes across the mesh: the placed
        decode-slice copy plus, under disaggregation, the prefill
        slice's second copy (each addressable shard holds 1/n of a
        sharded leaf; replicated leaves cost the full size per device
        — the ledger reports the logical total, the number capacity
        planning sums against per-chip HBM)."""
        if self._weights_bytes is None:
            b = _tree_bytes(self._sparams) + _tree_bytes(self._sbuffers)
            if self._prefill_dm is not None:
                b += _tree_bytes(self._pparams) + \
                    _tree_bytes(self._pbuffers)
            self._weights_bytes = b
        return self._weights_bytes

    # ------------------------------------------------------------------
    # sharded compilation + pool-state placement: the ShardedPlacement
    # layer (serving/layers.py) wraps the SAME single-chip bodies in
    # the mesh annotations and lays fresh pool state out over dp
    # ------------------------------------------------------------------
    def _make_placement(self):
        from .layers import ShardedPlacement

        return ShardedPlacement(self)

    def _ensure_state(self, memory):
        if self._state is not None:
            return
        super()._ensure_state(memory)
        self._state = self.placement.place_state(self._state)

    # ------------------------------------------------------------------
    # shard-aware slot policy + gauges
    # ------------------------------------------------------------------
    def _shard_of(self, s):
        return s // (self.num_slots // self._pool_dp)

    def _shard_occupancies(self):
        per = self.num_slots // self._pool_dp
        return [sum(self.slots[g * per + i] is not None
                    for i in range(per)) / per
                for g in range(self._pool_dp)]

    def _choose_slot(self, free):
        """Balance occupancy across the dp shards of the slot axis so
        one mesh row never saturates while another idles."""
        occ = self._shard_occupancies()
        return min(free, key=lambda s: (occ[self._shard_of(s)], s))

    def _iteration_gauges(self):
        gauges = dict(super()._iteration_gauges() or {})
        gauges["shard_occupancy"] = self._shard_occupancies()
        return gauges

    # ------------------------------------------------------------------
    # disaggregated prefill: dispatch on the prefill slice, splice
    # asynchronously into the live pool
    # ------------------------------------------------------------------
    def _join(self, s, r):
        if self._prefill_dm is None:
            return super()._join(s, r)
        return self._dispatch_prefill(s, r)

    def _dispatch_prefill(self, s, r):
        import jax.numpy as jnp

        _PT_PREFILL()
        self._ensure_state(r.memory)
        row = self._acquire_adapter(r)
        pad_id = int(r.eos_id) if r.eos_id is not None else 0
        prompt_b, P0, Pb = pad_prompt_row(r.prompt, pad_id)
        key = ("prefill", Pb)
        fn = self._compiled.get(key)
        if fn is None:
            fn = self._build_prefill(Pb)
            self._compiled[key] = fn
            fn = self._compiled[key]   # the observed wrapper
        mem = np.asarray(r.memory, self._np_dtype)[None]
        try:
            outs = fn(self._pparams, self._pbuffers,
                      jnp.asarray(prompt_b),
                      jnp.asarray([P0], jnp.int32), jnp.asarray(mem),
                      *self._prefill_adapter_args(row))
        except Exception:
            self._release_adapter_row(row)
            raise
        self._adapter_rows[s] = row
        self._pending.add(s)
        self._pending_info[s] = {
            "req": r, "outs": outs, "mem": mem, "Pb": Pb,
            "prompt": np.asarray(prompt_b, np.int32), "P0": P0,
            "t0": time.monotonic()}
        return None   # token 0 is delivered by the splice

    def _build_prefill(self, Pb):
        """The prefill-slice program: the single-chip join's prefill
        half (prompt -> batch-1 K/V + first token), no pool splice —
        it runs on the prefill mesh's own weight copy and its outputs
        travel to the decode slice when ready."""
        import jax
        import jax.numpy as jnp

        from ..text.generation import NEG

        fm = self._fm
        decoder = self._net.decoder
        L = self._pool_len
        key = ("prefill", Pb)
        neg = float(NEG)

        def prefill_fn(params, buffers, prompt, length, memory, *ad):
            self.trace_counts[key] += 1  # one per trace = one compile
            kpos = jnp.arange(L, dtype=jnp.int32)
            hole = (kpos[None, :] >= length[:, None]) & \
                (kpos[None, :] < jnp.int32(Pb))
            bias_row = jnp.where(hole, jnp.float32(neg),
                                 jnp.float32(0.0))           # [1, L]
            positions = jnp.arange(Pb, dtype=jnp.int32)[None]
            inc0 = [layer.self_attn.gen_cache(
                None, max_length=Pb, batch_size=1, dtype=memory.dtype)
                for layer in decoder.layers]
            with self._lora_ctx(ad):
                (lg, inc1, static1), _ = fm.apply(
                    params, buffers, None, prompt, positions, memory,
                    training=False, tgt_mask=bias_row[:, :Pb],
                    memory_mask=None, inc=inc0, prefill=True)
            last = jnp.take_along_axis(
                lg, (length - 1)[:, None, None], axis=1)[:, 0]
            tok0 = last.argmax(-1).astype(jnp.int32)[0]
            kvs = [(c.k, c.v) for c in inc1]
            return tok0, kvs, static1, bias_row

        return jax.jit(prefill_fn)

    def _splice_math(self, Pb):
        """The per-entry splice math (no trace counter): land one
        travelled prefill's K/V + bias + memory + first token in the
        pool at the traced slot — `static_kv_splice`/`splice_rows`
        with the pool constraints. Shared verbatim by the single-entry
        splice program and the batched scan over it."""
        import jax
        import jax.numpy as jnp

        from ..nn.layer.transformer import MultiHeadAttention as MHA

        ns, ns1 = self._ns_pool, self._ns_pool
        L = self._pool_len
        spec = bool(self.spec_k)

        def splice(state, slot, tok0, bias_row, kvs, statics,
                   memory, prompt, length):
            new_inc = [MHA.static_kv_splice(pool, slot, k, v,
                                            jnp.int32(Pb),
                                            constraint=(ns, ns1))
                       for pool, (k, v) in zip(state["inc"], kvs)]
            new_static = [
                (MHA.splice_rows(pk, slot, sk, constraint=ns),
                 MHA.splice_rows(pv, slot, sv, constraint=ns))
                for (pk, pv), (sk, sv) in zip(state["static"], statics)]
            out = dict(
                state,
                tok=jax.lax.with_sharding_constraint(
                    jax.lax.dynamic_update_slice(
                        state["tok"], tok0[None], (slot,)), ns),
                bias=MHA.splice_rows(state["bias"], slot, bias_row,
                                     constraint=ns),
                mem=MHA.splice_rows(state["mem"], slot, memory,
                                    constraint=ns),
                inc=new_inc, static=new_static)
            if spec:
                hist_row = jnp.concatenate(
                    [prompt, jnp.zeros((1, L - Pb), jnp.int32)], 1)
                out["hist"] = MHA.splice_rows(state["hist"], slot,
                                              hist_row, constraint=ns)
                out["plen"] = jax.lax.with_sharding_constraint(
                    jax.lax.dynamic_update_slice(
                        state["plen"], length.astype(jnp.int32),
                        (slot,)), ns)
                out["pbk"] = jax.lax.with_sharding_constraint(
                    jax.lax.dynamic_update_slice(
                        state["pbk"], jnp.full((1,), Pb, jnp.int32),
                        (slot,)), ns)
            return out

        return splice

    def _build_splice(self, Pb):
        """The decode-slice half of one disaggregated join, as its own
        program — the single-entry path."""
        import jax

        key = ("splice", Pb)
        math = self._splice_math(Pb)

        def splice_fn(state, slot, tok0, bias_row, kvs, statics,
                      memory, prompt, length):
            self.trace_counts[key] += 1
            return math(state, slot, tok0, bias_row, kvs, statics,
                        memory, prompt, length)

        # the pool carry donates like the rest of the join family (the
        # shared _DONATED_KINDS declaration the PTA102 audit reads) —
        # the splice lands in the pool in place, no whole-pool copy
        return jax.jit(splice_fn,
                       donate_argnums=self._donate_argnums(key))

    def _build_batched_splice(self, Pb, nb):
        """`nb` ready prefills of one bucket land in the pool as ONE
        program: a `lax.scan` of the per-entry splice math over the
        stacked entries. Entry counts bucket to powers of two and the
        pad repeats entry 0 — splicing the same (slot, data) twice is
        idempotent, so padding never corrupts state. One dispatch per
        (bucket, count-bucket) instead of one per request: a join
        burst stops serializing `_poll_pending`."""
        import jax

        key = ("bsplice", Pb, nb)
        math = self._splice_math(Pb)

        def bsplice_fn(state, slots, tok0s, bias_rows, kvss, staticss,
                       memories, prompts, lengths):
            self.trace_counts[key] += 1

            def body(st, xs):
                slot, tok0, bias_row, kvs, statics, memory, prompt, \
                    length = xs
                return math(st, slot, tok0, bias_row, kvs, statics,
                            memory, prompt, length), None

            st, _ = jax.lax.scan(
                body, state, (slots, tok0s, bias_rows, kvss, staticss,
                              memories, prompts, lengths))
            # the per-entry constraints live inside the scan body; pin
            # the final carry too so the program's OUTPUT layout is
            # explicit (the every-carry contract the analyzer audits)
            return self.placement.constrain_state(st)

        return jax.jit(bsplice_fn,
                       donate_argnums=self._donate_argnums(key))

    def _fail_pending_splice(self, s, r, e):
        """Per-request isolation: the failed splice kills THIS
        request's future, frees the slot, pool keeps serving."""
        self.slots[s] = None
        self._evict(s)
        r.slot = None
        if r._trace is not None:
            _rt.on_splice_end(r, ok=False, error=e)
        self.metrics.record_error("prefill_splice", e)
        r.fail(e, self.clock())
        self.metrics.record_finish("error", len(r.tokens))
        self._cbs.emit("on_finish", r)

    def _finish_splice(self, s, r, tok0):
        self._pending.discard(s)
        self._pending_info.pop(s, None)
        if r._trace is not None:
            _rt.on_splice_end(r, ok=True)
        self._deliver(r, tok0, self.clock())

    def _splice_one(self, s, info, r, deferred=None):
        """Single ready prefill: the per-bucket splice program. With a
        `deferred` list the first-token resolution is batched out of
        the dispatch path: the (slot, request, traced tok0) triple is
        appended and _poll_pending finishes the whole round after its
        LAST dispatch (one host sync, not one per splice)."""
        import jax
        import jax.numpy as jnp

        Pb = info["Pb"]
        try:
            t1 = time.monotonic()
            moved = jax.device_put(info["outs"], self._ns_repl)
            jax.block_until_ready(moved)
            self.metrics.record_collective(time.monotonic() - t1)
            fn = self._program(("splice", Pb),
                               lambda: self._build_splice(Pb))
            tok0, kvs, statics, bias_row = moved
            self._state = fn(self._state, jnp.int32(s), tok0,
                             bias_row, kvs, statics,
                             jnp.asarray(info["mem"]),
                             jnp.asarray(info["prompt"]),
                             jnp.asarray([info["P0"]], jnp.int32))
        except Exception as e:
            self._fail_pending_splice(s, r, e)
            if not self._carry_alive():
                # the donated carry died mid-splice with no
                # replacement: every co-resident slot is poisoned —
                # all-or-nothing recovery rebuilds the pool
                self._fail_active(e)
            return False
        if deferred is None or self.sync_tok0:
            self._finish_splice(s, r, int(tok0))
        else:
            deferred.append((s, r, tok0))
        return True

    def _splice_batch(self, Pb, ss):
        """>= 2 ready prefills of one bucket: stack their travelled
        outputs, move them to the decode slice in one transfer, and
        land them with ONE scanned program. A dispatch failure fails
        only the batch's requests (the pool keeps serving); the
        fault-point gate already ran per entry, so injected faults
        keep per-request isolation."""
        import jax
        import jax.numpy as jnp

        infos = [self._pending_info[s] for s in ss]
        reqs = [self.slots[s] for s in ss]
        nb = bucket_size(len(ss))
        pad = [0] * (nb - len(ss))        # repeat entry 0: idempotent
        ix = list(range(len(ss))) + pad
        try:
            t1 = time.monotonic()
            stacked = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs),
                *[infos[i]["outs"] for i in ix])
            moved = jax.device_put(stacked, self._ns_repl)
            jax.block_until_ready(moved)
            self.metrics.record_collective(time.monotonic() - t1)
            fn = self._program(
                ("bsplice", Pb, nb),
                lambda: self._build_batched_splice(Pb, nb))
            tok0s, kvss, staticss, bias_rows = moved
            slots = jnp.asarray([ss[i] for i in ix], jnp.int32)
            mems = jnp.asarray(np.stack(
                [np.asarray(infos[i]["mem"]) for i in ix]))
            prompts = jnp.asarray(np.stack(
                [infos[i]["prompt"] for i in ix]))
            lengths = jnp.asarray(
                [[infos[i]["P0"]] for i in ix], jnp.int32)
            self._state = fn(self._state, slots, tok0s, bias_rows,
                             kvss, staticss, mems, prompts, lengths)
            toks = np.asarray(tok0s)
        except Exception as e:
            for s, r in zip(ss, reqs):
                self._fail_pending_splice(s, r, e)
            if not self._carry_alive():
                self._fail_active(e)
            return False
        for i, (s, r) in enumerate(zip(ss, reqs)):
            self._finish_splice(s, r, int(toks[i]))
        return True

    def _poll_pending(self, now):
        """Splice every finished prefill into the pool. Runs once per
        iteration; a prefill whose arrays are not ready yet just stays
        pending (the decode step keeps running without it). Ready
        prefills GROUP by prompt bucket: each group past one entry
        lands via the batched-splice program — one dispatch per
        bucket, not one per request."""
        if not self._pending:
            return False
        import jax

        ready = []
        for s in sorted(self._pending):
            if s in self._chunking:
                # mid chunked-prefill, not a disaggregated splice:
                # _advance_chunks owns this slot's pending state
                continue
            info = self._pending_info.get(s)
            r = self.slots[s]
            if info is None or r is None:   # evicted while pending
                self._pending.discard(s)
                self._pending_info.pop(s, None)
                continue
            leaves = jax.tree_util.tree_leaves(info["outs"])
            if not all(getattr(x, "is_ready", lambda: True)()
                       for x in leaves):
                # bounded-wait escape valve: an AOT-precompiled
                # prefill dispatches asynchronously, and on a
                # starved host (1-core box, idle pool spinning this
                # poll) its arrays may never flip ready on their
                # own — past the deadline, block for them. The
                # overlap win is gone by then anyway; liveness wins.
                # When NOTHING is decoding (every occupied slot is
                # itself pending) the poll loop is a pure spin, so
                # there is no overlap to protect: block right away —
                # a fast driver can burn its iteration budget before
                # poll_block_s of wall time ever elapses.
                spin = self.occupancy() == len(self._pending)
                if (not spin and
                        time.monotonic() - info["t0"] < self.poll_block_s):
                    continue
                jax.block_until_ready(info["outs"])
            self.metrics.record_prefill_step(
                time.monotonic() - info["t0"])
            # the fault-point gate fires PER REQUEST before any
            # batching, so an injected splice fault isolates exactly
            # one request whether or not its bucket batches
            try:
                _PT_SPLICE()
            except Exception as e:
                self._fail_pending_splice(s, r, e)
                continue
            ready.append(s)
        groups = {}
        for s in ready:
            groups.setdefault(self._pending_info[s]["Pb"],
                              []).append(s)
        activated = False
        deferred = []   # (slot, request, traced tok0) per single splice
        for Pb, ss in sorted(groups.items()):
            if len(ss) == 1:
                s = ss[0]
                activated |= self._splice_one(
                    s, self._pending_info[s], self.slots[s], deferred)
            else:
                activated |= self._splice_batch(Pb, ss)
        # resolve the round's first tokens after the LAST dispatch —
        # one natural host sync instead of a blocking int() per splice
        for s, r, t in deferred:
            self._finish_splice(s, r, int(t))
        return activated

    def _evict(self, s):
        self._pending.discard(s)
        self._pending_info.pop(s, None)
        super()._evict(s)

    # ------------------------------------------------------------------
    # zero-warmup startup: the sharded program set
    # ------------------------------------------------------------------
    def _program_fingerprint(self):
        # mesh geometry + prefill policy change the compiled programs'
        # layouts: fold them into the persistent-cache identity
        return (f"{super()._program_fingerprint()}|"
                f"dp{self._pool_dp}|{self._prefill_policy}|"
                f"{self.layout}")

    def _startup_programs(self, prompt_buckets):
        progs = super()._startup_programs(prompt_buckets)
        if self._prefill_dm is None:
            return progs
        import jax
        import jax.numpy as jnp

        decoder = self._net.decoder
        M, Dm = self._mem_shape
        dt = jnp.dtype(self._np_dtype)
        mem1 = jnp.zeros((1, M, Dm), dt)
        one = jnp.asarray([1], jnp.int32)
        L = self._pool_len
        state = self._state
        repl = self._ns_repl
        pad = self._prefill_adapter_args(0)
        for Pb in sorted({bucket_size(int(p)) for p in prompt_buckets}):
            progs.append((
                ("prefill", Pb),
                lambda Pb=Pb: self._build_prefill(Pb),
                (self._pparams, self._pbuffers,
                 jnp.zeros((1, Pb), jnp.int32), one, mem1) + pad))
            # the splice half sees the travelled prefill outputs
            # REPLICATED on the decode slice (_poll_pending device_puts
            # them to _ns_repl before the call) — mirror that placement
            # so the AOT executable's input layouts match the hot path
            kvs = [jax.device_put(
                (jnp.zeros((1, ly.self_attn.num_heads, Pb,
                            ly.self_attn.head_dim), dt),) * 2, repl)
                for ly in decoder.layers]
            statics = [jax.device_put(
                (jnp.zeros((1, ly.cross_attn.num_heads, M,
                            ly.cross_attn.head_dim), dt),) * 2, repl)
                for ly in decoder.layers]
            progs.append((
                ("splice", Pb),
                lambda Pb=Pb: self._build_splice(Pb),
                (state, jnp.int32(0),
                 jax.device_put(jnp.int32(0), repl),
                 jax.device_put(jnp.zeros((1, L), jnp.float32), repl),
                 kvs, statics, mem1, jnp.zeros((1, Pb), jnp.int32),
                 one)))
            # the batched-splice program for a 2-burst (larger bursts
            # bucket up and compile on first use): warm-started AND
            # audited by the program analyzer alongside the rest
            stack2 = lambda t: jax.tree_util.tree_map(  # noqa: E731
                lambda *xs: jnp.stack(xs), t, t)
            progs.append((
                ("bsplice", Pb, 2),
                lambda Pb=Pb: self._build_batched_splice(Pb, 2),
                (state, jnp.zeros((2,), jnp.int32),
                 jax.device_put(jnp.zeros((2,), jnp.int32), repl),
                 jax.device_put(jnp.zeros((2, 1, L), jnp.float32),
                                repl),
                 jax.device_put(stack2(kvs), repl),
                 jax.device_put(stack2(statics), repl),
                 jnp.zeros((2, 1, M, Dm), dt),
                 jnp.zeros((2, 1, Pb), jnp.int32),
                 jnp.ones((2, 1), jnp.int32))))
        return progs

    def _inflight_prefills(self):
        return len(self._pending)


class ShardedPagedServingEngine(ShardedServingEngine, PagedServingEngine):
    """`ShardedServingEngine(..., paged=True)`: the paged pool's host
    bookkeeping (allocator, prefix cache, COW, page tables) is
    unchanged; the DEVICE side shards the page/scale arrays over dp
    alongside the slot-leading state, so cache memory scales with the
    mesh while page mapping stays a traced input that never retraces.
    Page reads/writes are pure selection (gather/scatter), so dp-laid
    pages keep the bit-exactness contract of `kv_dtype=None`.

    Disaggregated prefill is not wired through the paged join yet
    (prefix-attach and COW interleave with allocation host-side);
    constructing with ``prefill="disaggregated"`` raises."""

    def __init__(self, decoder, embed, project, *, prefill="inline",
                 **kw):
        if prefill != "inline":
            raise NotImplementedError(
                "ShardedPagedServingEngine supports prefill='inline' "
                "only (disaggregation of the paged join — prefix "
                "attach + COW — is a follow-up); use the dense "
                "ShardedServingEngine for disaggregated prefill")
        kw.pop("paged", None)
        super().__init__(decoder, embed, project, prefill="inline",
                         **kw)

    def _cross_params(self):
        if getattr(self, "_scross", None) is None:
            import jax

            self._scross = {
                n: jax.device_put(v, self._ns_repl)
                for n, v in self._fm_cross.params().items()}
        return self._scross

    def _check_params(self):
        prev = self._prefix_params
        super()._check_params()
        if prev is not None and self._prefix_params is not prev:
            # weights changed: re-place the mesh copies too
            self._scross = None
            self._place_params()
