"""Mesh-sharded serving: tp/fsdp-parallel decode over the slot pool.

The single-chip engines (engine.py) bound the servable model by one
device's memory and pin the pool size to one chip. This module runs the
SAME slot lifecycle over a `parallel.mesh` Mesh:

  * **weights** lay out tp/fsdp via `parallel.sharding`'s
    `serving_param_rules` — the default ``"gathered"`` layout shards
    every large weight's output-feature dim (vocab dim for embeddings)
    jointly over (fsdp, tp), so the SPMD partitioner materializes
    activations by all-gather (concatenation), never by partial-sum
    psum: float reduction order is untouched and every request's tokens
    stay BIT-IDENTICAL to the single-chip engine. ``layout="megatron"``
    flips to the canonical TP layout (contraction dims split, psum per
    matmul) where interconnect bandwidth beats the bit-exact contract;
  * **the slot pool** shards its slot axis data-parallel over ``dp``:
    pooled `StaticKVCache` rows / `PagedKVCache` pages + scales,
    per-row write indices, bias rows, memory rows, cross-attn K/V, and
    the paged engine's table/index all carry `PartitionSpec("dp")`
    leading dims, pinned with `with_sharding_constraint` on EVERY carry
    of the decode step — the pool scales with the mesh;
  * **the decode step stays ONE jitted per-pool-config call**: the
    engine bodies are the single-chip ones (engine.py `_*_body`),
    re-wrapped in sharding annotations (`ops.attention.decode_shardings`
    spec-annotates the unchanged decode kernels) — joins, evictions and
    page maps never retrace, proven by the same `trace_counts` keys;
  * **prefill/decode disaggregation** (``prefill="disaggregated"``):
    the dp axis is carved into a decode slice and a prefill slice
    (`DeviceMesh.slice_axis`), prompt prefill runs asynchronously on
    the prefill slice's own weight copy, and the finished K/V is
    spliced into the live pool (`static_kv_splice`/`splice_rows` with
    the pool constraints) once its arrays are ready — a long-prompt
    join no longer blocks the decode step, which shows up directly in
    the `step_gap_ms` (decode-step inter-arrival) metric the
    `serving_sharded` bench A/Bs.

Numerics contract (fp32, ``layout="gathered"``): every request's token
stream bit-matches both the single-chip `ServingEngine` and a solo
`generate_eager` run — tests/test_serving_sharded.py soaks it on the
8-device CPU mesh with ragged arrivals, chaos cells, and the
single-trace-per-bucket proof.
"""
from __future__ import annotations

import time

import numpy as np

from ..core.bucketing import bucket_size, pad_prompt_row
from ..testing import faults
from . import tracing as _rt
from .engine import (PagedServingEngine, ServingEngine, _PT_PREFILL,
                     _tree_bytes)

__all__ = ["ShardedServingEngine", "ShardedPagedServingEngine"]

#: fault point for the disaggregated splice (prefill-slice K/V landing
#: in the live pool) — chaos tests pin per-request isolation on it
_PT_SPLICE = faults.point("serving.prefill_splice")


class ShardedServingEngine(ServingEngine):
    """`ServingEngine` over a device mesh. Use exactly like the
    single-chip engine; extra knobs:

      mesh       parallel.DeviceMesh (default: the installed global
                 mesh). Needs a ``dp`` axis whose size divides
                 `num_slots`; ``fsdp``/``tp`` axes engage weight
                 sharding when present (absent axes are dropped from
                 the rules, so the same engine runs on a dp-only mesh).
      rules      parallel.ShardingRules for the step-net weights
                 (default: `serving_param_rules(layout)`).
      layout     "gathered" (bit-exact, default) | "megatron".
      prefill    "inline" (joins block, single-chip semantics) |
                 "disaggregated" (prompt prefill runs on a dedicated
                 dp slice with its own weight copy; joins splice in
                 asynchronously).
      prefill_dp how many dp rows the prefill slice takes (default 1).

    `paged=True` routes to `ShardedPagedServingEngine` the same way
    `ServingEngine(paged=True)` routes to the paged pool.

    Weights are PLACED at construction: after updating the underlying
    layers call `refresh_params()` to re-place them on the mesh.
    """

    _accepts_sharded_params = True

    def __new__(cls, *args, **kw):
        if cls is ShardedServingEngine and kw.get("paged"):
            return object.__new__(ShardedPagedServingEngine)
        return object.__new__(cls)

    def __init__(self, decoder, embed, project, *, mesh=None, rules=None,
                 layout="gathered", prefill="inline", prefill_dp=1,
                 num_slots=8, max_len=128, **kw):
        from ..parallel.mesh import get_mesh
        from ..parallel.sharding import serving_param_rules

        self._mesh = mesh if mesh is not None else get_mesh()
        self._rules = rules if rules is not None \
            else serving_param_rules(layout)
        self.layout = layout
        if prefill not in ("inline", "disaggregated"):
            raise ValueError(
                f"prefill policy must be 'inline' or 'disaggregated', "
                f"got {prefill!r}")
        self._prefill_policy = prefill
        dp = self._mesh.axis_size("dp")
        if prefill == "disaggregated":
            prefill_dp = int(prefill_dp)
            if dp < prefill_dp + 1:
                raise ValueError(
                    f"disaggregated prefill needs dp >= {prefill_dp + 1} "
                    f"(a decode slice plus {prefill_dp} prefill row(s)); "
                    f"mesh has dp={dp}")
            self._decode_dm = self._mesh.slice_axis(
                "dp", 0, dp - prefill_dp)
            self._prefill_dm = self._mesh.slice_axis(
                "dp", dp - prefill_dp, dp)
        else:
            self._decode_dm = self._mesh
            self._prefill_dm = None
        self._pool_dp = max(1, self._decode_dm.axis_size("dp"))
        if int(num_slots) % self._pool_dp:
            raise ValueError(
                f"num_slots ({num_slots}) must be divisible by the "
                f"decode slice's dp axis ({self._pool_dp}) — the slot "
                f"pool shards over it")
        self._pending_info = {}
        #: seconds a dispatched prefill may stay not-ready before
        #: _poll_pending stops polling and blocks for it (see there)
        self.poll_block_s = 0.5
        super().__init__(decoder, embed, project, num_slots=num_slots,
                         max_len=max_len, **kw)
        self._build_shardings()
        self._place_params()

    # ------------------------------------------------------------------
    # placement
    # ------------------------------------------------------------------
    def _build_shardings(self):
        import jax
        from jax.sharding import PartitionSpec as P

        mesh = self._decode_dm.mesh
        self._ns_pool = jax.sharding.NamedSharding(mesh, P("dp"))
        self._ns_repl = jax.sharding.NamedSharding(mesh, P())

    def _place_params(self):
        """device_put the step-net weights onto the mesh per the layout
        rules (and, when disaggregated, a second copy onto the prefill
        slice). Timed into the collective budget — placement is the
        engine-driven cross-device traffic operators should see."""
        import jax

        from ..parallel.sharding import fitted_sharding, infer_param_specs

        t0 = time.monotonic()
        params = self._fm.params()
        specs = infer_param_specs(params, self._rules)
        self._sparams = {
            n: jax.device_put(v, fitted_sharding(v.shape, specs[n],
                                                 self._decode_dm))
            for n, v in params.items()}
        self._sbuffers = {
            n: jax.device_put(v, self._ns_repl)
            for n, v in self._fm.buffers().items()}
        if self._prefill_dm is not None:
            import jax.sharding as jsh
            from jax.sharding import PartitionSpec as P

            self._pparams = {
                n: jax.device_put(v, fitted_sharding(
                    v.shape, specs[n], self._prefill_dm))
                for n, v in params.items()}
            self._pbuffers = {
                n: jax.device_put(v, jsh.NamedSharding(
                    self._prefill_dm.mesh, P()))
                for n, v in self._fm.buffers().items()}
        self.metrics.record_collective(time.monotonic() - t0)

    def refresh_params(self):
        """Re-place the (possibly updated) layer weights onto the mesh;
        compiled programs are pure and stay cached."""
        self._place_params()
        self._weights_bytes = None   # ledger cache: shapes may change

    def _params(self):
        return self._sparams

    def _buffers(self):
        return self._sbuffers

    def weights_bytes(self):
        """GLOBAL logical weight bytes across the mesh: the placed
        decode-slice copy plus, under disaggregation, the prefill
        slice's second copy (each addressable shard holds 1/n of a
        sharded leaf; replicated leaves cost the full size per device
        — the ledger reports the logical total, the number capacity
        planning sums against per-chip HBM)."""
        if self._weights_bytes is None:
            b = _tree_bytes(self._sparams) + _tree_bytes(self._sbuffers)
            if self._prefill_dm is not None:
                b += _tree_bytes(self._pparams) + \
                    _tree_bytes(self._pbuffers)
            self._weights_bytes = b
        return self._weights_bytes

    # ------------------------------------------------------------------
    # sharded compilation: same bodies, annotated
    # ------------------------------------------------------------------
    def _decode_specs(self):
        return {"q": self._ns_pool, "kv": self._ns_pool,
                "pages": self._ns_pool, "out": self._ns_pool}

    def _constrain_state(self, state):
        """Pin PartitionSpec('dp') on every pool carry (slot-leading
        leaves; the paged page/scale arrays shard their page axis the
        same way), replicating nothing implicitly — the ISSUE's
        every-carry contract."""
        import jax

        from ..nn.layer.transformer import MultiHeadAttention as MHA

        c = lambda x: jax.lax.with_sharding_constraint(  # noqa: E731
            x, self._ns_pool)
        out = dict(state)
        for k in ("tok", "bias", "mem", "hist", "plen", "pbk"):
            if k in out:
                out[k] = c(out[k])
        if "inc" in out:
            out["inc"] = [MHA.StaticKVCache(c(cc.k), c(cc.v),
                                            c(cc.index))
                          for cc in out["inc"]]
        if "static" in out:
            out["static"] = [(c(sk), c(sv)) for sk, sv in out["static"]]
        if "paged" in out:
            out["paged"] = [
                {"k": c(pc["k"]), "v": c(pc["v"]),
                 "ks": None if pc["ks"] is None else c(pc["ks"]),
                 "vs": None if pc["vs"] is None else c(pc["vs"])}
                for pc in out["paged"]]
        return out

    def _wrap_state_out(self, body, has_aux, key):
        """jit a single-chip engine body with the sharded annotations:
        decode kernels constrained via `decode_shardings`, every
        returned carry pinned to the pool layout, the step-family
        state carry donated per the shared `_donate_argnums`
        declaration (same donation audit as the single-chip builders)."""
        import jax

        from ..ops import attention as A

        specs = self._decode_specs()

        def fn(*args):
            with A.decode_shardings(specs):
                out = body(*args)
            if has_aux:
                st, aux = out
                return self._constrain_state(st), aux
            return self._constrain_state(out)

        return jax.jit(fn, donate_argnums=self._donate_argnums(key))

    def _build_join(self, Pb):
        return self._wrap_state_out(self._join_body(Pb), True,
                                    ("join", Pb))

    def _build_step(self, key):
        return self._wrap_state_out(self._step_body(key), True, key)

    def _build_spec_step(self, vkey):
        # the spec verify body returns (state, (emit, n_emit)) — the
        # same state-out contract, annotated identically
        return self._wrap_state_out(self._spec_step_body(vkey), True,
                                    vkey)

    def _build_draft(self, dkey):
        # pure gathers over dp-sharded per-slot rows; the SPMD
        # partitioner follows the operand layouts, no pinning needed
        import jax

        return jax.jit(self._draft_body(dkey))

    # ------------------------------------------------------------------
    # pool state placement
    # ------------------------------------------------------------------
    def _ensure_state(self, memory):
        if self._state is not None:
            return
        super()._ensure_state(memory)
        self._state = self._place_state(self._state)

    def _place_state(self, state):
        """Lay the freshly-built pool state out on the decode mesh:
        slot-leading leaves shard over dp (the KV pool is REBUILT with
        `gen_cache`'s sharded constructors so the zeros never
        materialize on one device)."""
        import jax

        L, S = self._pool_len, self.num_slots
        dtype = state["mem"].dtype
        decoder = self._net.decoder
        out = dict(state)
        for k in ("tok", "bias", "mem", "hist", "plen", "pbk"):
            if k in state:
                out[k] = jax.device_put(state[k], self._ns_pool)
        out["static"] = [
            (jax.device_put(sk, self._ns_pool),
             jax.device_put(sv, self._ns_pool))
            for sk, sv in state["static"]]
        if "inc" in state:
            out["inc"] = [layer.self_attn.gen_cache(
                None, max_length=L, batch_size=S, dtype=dtype,
                kv_sharding=self._ns_pool,
                index_sharding=self._ns_pool)
                for layer in decoder.layers]
        if "paged" in state:
            # pad the page-row count to a dp multiple so the page axis
            # lays out evenly; rows past the trash row (num_pages) are
            # never referenced by any table entry — pure padding
            rows = self.num_pages + 1
            padded = -(-rows // self._pool_dp) * self._pool_dp
            paged = []
            for layer in decoder.layers:
                cc = layer.self_attn.gen_paged_cache(
                    padded - 1, self.page_size, S, self.max_pages,
                    dtype, self.kv_dtype, page_sharding=self._ns_pool)
                paged.append({"k": cc.k, "v": cc.v, "ks": cc.k_scale,
                              "vs": cc.v_scale})
            out["paged"] = paged
        return out

    # ------------------------------------------------------------------
    # shard-aware slot policy + gauges
    # ------------------------------------------------------------------
    def _shard_of(self, s):
        return s // (self.num_slots // self._pool_dp)

    def _shard_occupancies(self):
        per = self.num_slots // self._pool_dp
        return [sum(self.slots[g * per + i] is not None
                    for i in range(per)) / per
                for g in range(self._pool_dp)]

    def _choose_slot(self, free):
        """Balance occupancy across the dp shards of the slot axis so
        one mesh row never saturates while another idles."""
        occ = self._shard_occupancies()
        return min(free, key=lambda s: (occ[self._shard_of(s)], s))

    def _iteration_gauges(self):
        gauges = dict(super()._iteration_gauges() or {})
        gauges["shard_occupancy"] = self._shard_occupancies()
        return gauges

    # ------------------------------------------------------------------
    # disaggregated prefill: dispatch on the prefill slice, splice
    # asynchronously into the live pool
    # ------------------------------------------------------------------
    def _join(self, s, r):
        if self._prefill_dm is None:
            return super()._join(s, r)
        return self._dispatch_prefill(s, r)

    def _dispatch_prefill(self, s, r):
        import jax.numpy as jnp

        _PT_PREFILL()
        self._ensure_state(r.memory)
        pad_id = int(r.eos_id) if r.eos_id is not None else 0
        prompt_b, P0, Pb = pad_prompt_row(r.prompt, pad_id)
        key = ("prefill", Pb)
        fn = self._compiled.get(key)
        if fn is None:
            fn = self._build_prefill(Pb)
            self._compiled[key] = fn
            fn = self._compiled[key]   # the observed wrapper
        mem = np.asarray(r.memory, self._np_dtype)[None]
        outs = fn(self._pparams, self._pbuffers,
                  jnp.asarray(prompt_b), jnp.asarray([P0], jnp.int32),
                  jnp.asarray(mem))
        self._pending.add(s)
        self._pending_info[s] = {
            "req": r, "outs": outs, "mem": mem, "Pb": Pb,
            "prompt": np.asarray(prompt_b, np.int32), "P0": P0,
            "t0": time.monotonic()}
        return None   # token 0 is delivered by the splice

    def _build_prefill(self, Pb):
        """The prefill-slice program: the single-chip join's prefill
        half (prompt -> batch-1 K/V + first token), no pool splice —
        it runs on the prefill mesh's own weight copy and its outputs
        travel to the decode slice when ready."""
        import jax
        import jax.numpy as jnp

        from ..text.generation import NEG

        fm = self._fm
        decoder = self._net.decoder
        L = self._pool_len
        key = ("prefill", Pb)
        neg = float(NEG)

        def prefill_fn(params, buffers, prompt, length, memory):
            self.trace_counts[key] += 1  # one per trace = one compile
            kpos = jnp.arange(L, dtype=jnp.int32)
            hole = (kpos[None, :] >= length[:, None]) & \
                (kpos[None, :] < jnp.int32(Pb))
            bias_row = jnp.where(hole, jnp.float32(neg),
                                 jnp.float32(0.0))           # [1, L]
            positions = jnp.arange(Pb, dtype=jnp.int32)[None]
            inc0 = [layer.self_attn.gen_cache(
                None, max_length=Pb, batch_size=1, dtype=memory.dtype)
                for layer in decoder.layers]
            (lg, inc1, static1), _ = fm.apply(
                params, buffers, None, prompt, positions, memory,
                training=False, tgt_mask=bias_row[:, :Pb],
                memory_mask=None, inc=inc0, prefill=True)
            last = jnp.take_along_axis(
                lg, (length - 1)[:, None, None], axis=1)[:, 0]
            tok0 = last.argmax(-1).astype(jnp.int32)[0]
            kvs = [(c.k, c.v) for c in inc1]
            return tok0, kvs, static1, bias_row

        return jax.jit(prefill_fn)

    def _build_splice(self, Pb):
        """The decode-slice half of a disaggregated join: land the
        travelled K/V + bias + memory + first token in the pool at the
        traced slot — `static_kv_splice`/`splice_rows` with the pool
        constraints, one compile per prompt bucket."""
        import jax
        import jax.numpy as jnp

        from ..nn.layer.transformer import MultiHeadAttention as MHA

        key = ("splice", Pb)
        ns, ns1 = self._ns_pool, self._ns_pool
        L = self._pool_len
        spec = bool(self.spec_k)

        def splice_fn(state, slot, tok0, bias_row, kvs, statics,
                      memory, prompt, length):
            self.trace_counts[key] += 1
            new_inc = [MHA.static_kv_splice(pool, slot, k, v,
                                            jnp.int32(Pb),
                                            constraint=(ns, ns1))
                       for pool, (k, v) in zip(state["inc"], kvs)]
            new_static = [
                (MHA.splice_rows(pk, slot, sk, constraint=ns),
                 MHA.splice_rows(pv, slot, sv, constraint=ns))
                for (pk, pv), (sk, sv) in zip(state["static"], statics)]
            out = dict(
                state,
                tok=jax.lax.with_sharding_constraint(
                    jax.lax.dynamic_update_slice(
                        state["tok"], tok0[None], (slot,)), ns),
                bias=MHA.splice_rows(state["bias"], slot, bias_row,
                                     constraint=ns),
                mem=MHA.splice_rows(state["mem"], slot, memory,
                                    constraint=ns),
                inc=new_inc, static=new_static)
            if spec:
                hist_row = jnp.concatenate(
                    [prompt, jnp.zeros((1, L - Pb), jnp.int32)], 1)
                out["hist"] = MHA.splice_rows(state["hist"], slot,
                                              hist_row, constraint=ns)
                out["plen"] = jax.lax.with_sharding_constraint(
                    jax.lax.dynamic_update_slice(
                        state["plen"], length.astype(jnp.int32),
                        (slot,)), ns)
                out["pbk"] = jax.lax.with_sharding_constraint(
                    jax.lax.dynamic_update_slice(
                        state["pbk"], jnp.full((1,), Pb, jnp.int32),
                        (slot,)), ns)
            return out

        return jax.jit(splice_fn)

    def _poll_pending(self, now):
        """Splice every finished prefill into the pool. Runs once per
        iteration; a prefill whose arrays are not ready yet just stays
        pending (the decode step keeps running without it)."""
        if not self._pending:
            return False
        import jax
        import jax.numpy as jnp

        activated = False
        for s in sorted(self._pending):
            info = self._pending_info.get(s)
            r = self.slots[s]
            if info is None or r is None:   # evicted while pending
                self._pending.discard(s)
                self._pending_info.pop(s, None)
                continue
            leaves = jax.tree_util.tree_leaves(info["outs"])
            if not all(getattr(x, "is_ready", lambda: True)()
                       for x in leaves):
                # bounded-wait escape valve: an AOT-precompiled
                # prefill dispatches asynchronously, and on a
                # starved host (1-core box, idle pool spinning this
                # poll) its arrays may never flip ready on their
                # own — past the deadline, block for them. The
                # overlap win is gone by then anyway; liveness wins.
                if time.monotonic() - info["t0"] < self.poll_block_s:
                    continue
                jax.block_until_ready(info["outs"])
            self.metrics.record_prefill_step(
                time.monotonic() - info["t0"])
            Pb = info["Pb"]
            try:
                _PT_SPLICE()
                t1 = time.monotonic()
                moved = jax.device_put(info["outs"], self._ns_repl)
                jax.block_until_ready(moved)
                self.metrics.record_collective(time.monotonic() - t1)
                key = ("splice", Pb)
                fn = self._compiled.get(key)
                if fn is None:
                    fn = self._build_splice(Pb)
                    self._compiled[key] = fn
                    fn = self._compiled[key]   # observed wrapper
                tok0, kvs, statics, bias_row = moved
                self._state = fn(self._state, jnp.int32(s), tok0,
                                 bias_row, kvs, statics,
                                 jnp.asarray(info["mem"]),
                                 jnp.asarray(info["prompt"]),
                                 jnp.asarray([info["P0"]], jnp.int32))
                tok0 = int(tok0)
            except Exception as e:
                # per-request isolation: the failed splice kills THIS
                # request's future, frees the slot, pool keeps serving
                self.slots[s] = None
                self._evict(s)
                r.slot = None
                if r._trace is not None:
                    _rt.on_splice_end(r, ok=False, error=e)
                self.metrics.record_error("prefill_splice", e)
                r.fail(e, self.clock())
                self.metrics.record_finish("error", len(r.tokens))
                self._cbs.emit("on_finish", r)
                continue
            self._pending.discard(s)
            self._pending_info.pop(s, None)
            if r._trace is not None:
                _rt.on_splice_end(r, ok=True)
            self._deliver(r, tok0, self.clock())
            activated = True
        return activated

    def _evict(self, s):
        self._pending.discard(s)
        self._pending_info.pop(s, None)
        super()._evict(s)

    # ------------------------------------------------------------------
    # zero-warmup startup: the sharded program set
    # ------------------------------------------------------------------
    def _program_fingerprint(self):
        # mesh geometry + prefill policy change the compiled programs'
        # layouts: fold them into the persistent-cache identity
        return (f"{super()._program_fingerprint()}|"
                f"dp{self._pool_dp}|{self._prefill_policy}|"
                f"{self.layout}")

    def _startup_programs(self, prompt_buckets):
        progs = super()._startup_programs(prompt_buckets)
        if self._prefill_dm is None:
            return progs
        import jax
        import jax.numpy as jnp

        decoder = self._net.decoder
        M, Dm = self._mem_shape
        dt = jnp.dtype(self._np_dtype)
        mem1 = jnp.zeros((1, M, Dm), dt)
        one = jnp.asarray([1], jnp.int32)
        L = self._pool_len
        state = self._state
        repl = self._ns_repl
        for Pb in sorted({bucket_size(int(p)) for p in prompt_buckets}):
            progs.append((
                ("prefill", Pb),
                lambda Pb=Pb: self._build_prefill(Pb),
                (self._pparams, self._pbuffers,
                 jnp.zeros((1, Pb), jnp.int32), one, mem1)))
            # the splice half sees the travelled prefill outputs
            # REPLICATED on the decode slice (_poll_pending device_puts
            # them to _ns_repl before the call) — mirror that placement
            # so the AOT executable's input layouts match the hot path
            kvs = [jax.device_put(
                (jnp.zeros((1, ly.self_attn.num_heads, Pb,
                            ly.self_attn.head_dim), dt),) * 2, repl)
                for ly in decoder.layers]
            statics = [jax.device_put(
                (jnp.zeros((1, ly.cross_attn.num_heads, M,
                            ly.cross_attn.head_dim), dt),) * 2, repl)
                for ly in decoder.layers]
            progs.append((
                ("splice", Pb),
                lambda Pb=Pb: self._build_splice(Pb),
                (state, jnp.int32(0),
                 jax.device_put(jnp.int32(0), repl),
                 jax.device_put(jnp.zeros((1, L), jnp.float32), repl),
                 kvs, statics, mem1, jnp.zeros((1, Pb), jnp.int32),
                 one)))
        return progs

    def _inflight_prefills(self):
        return len(self._pending)


class ShardedPagedServingEngine(ShardedServingEngine, PagedServingEngine):
    """`ShardedServingEngine(..., paged=True)`: the paged pool's host
    bookkeeping (allocator, prefix cache, COW, page tables) is
    unchanged; the DEVICE side shards the page/scale arrays over dp
    alongside the slot-leading state, so cache memory scales with the
    mesh while page mapping stays a traced input that never retraces.
    Page reads/writes are pure selection (gather/scatter), so dp-laid
    pages keep the bit-exactness contract of `kv_dtype=None`.

    Disaggregated prefill is not wired through the paged join yet
    (prefix-attach and COW interleave with allocation host-side);
    constructing with ``prefill="disaggregated"`` raises."""

    def __init__(self, decoder, embed, project, *, prefill="inline",
                 **kw):
        if prefill != "inline":
            raise NotImplementedError(
                "ShardedPagedServingEngine supports prefill='inline' "
                "only (disaggregation of the paged join — prefix "
                "attach + COW — is a follow-up); use the dense "
                "ShardedServingEngine for disaggregated prefill")
        kw.pop("paged", None)
        super().__init__(decoder, embed, project, prefill="inline",
                         **kw)

    def _cross_params(self):
        if getattr(self, "_scross", None) is None:
            import jax

            self._scross = {
                n: jax.device_put(v, self._ns_repl)
                for n, v in self._fm_cross.params().items()}
        return self._scross

    def _check_params(self):
        prev = self._prefix_params
        super()._check_params()
        if prev is not None and self._prefix_params is not prev:
            # weights changed: re-place the mesh copies too
            self._scross = None
            self._place_params()

    def _build_paged_join(self, Pb):
        return self._wrap_state_out(self._paged_join_body(Pb), True,
                                    ("pjoin", Pb))

    def _build_paged_step(self, ck):
        return self._wrap_state_out(self._paged_step_body(ck), True, ck)

    def _build_attach(self):
        return self._wrap_state_out(self._attach_body(), False,
                                    ("attach",))

    def _build_cow(self):
        return self._wrap_state_out(self._cow_body(), False, ("cow",))
