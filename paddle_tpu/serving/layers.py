"""Composable pool layers: the serving engines' program families as
orthogonal strategy objects over ONE slot-pool core.

Before this module, each serving capability lived in its own engine
subclass: the paged pool re-implemented the join/step program pair,
the sharded engine re-wrapped the single-chip bodies, and speculative
decoding was wired through the dense pool only — so every new
capability had to be built once per pool variant (and the paged pool
simply rejected `spec_k`). Here the `(dense|paged) x (single|sharded)
x (spec on|off)` grid is three independent axes:

  * **CacheLayout** (`DenseLayout` | `PagedLayout`) owns the pool's
    device-state shape and every traceable program body that touches
    it: state construction, the join/attach/cow programs, the plain
    batched step, and the speculative verify step. The paged layout's
    verify body is the NEW program of this family: a k-token
    `write_tokens` page write (boundary-crossing, grow-only int8
    rescale) + `paged_verify_attention` through the block table.
  * **Placement** (`SinglePlacement` | `ShardedPlacement`) owns how a
    body becomes a compiled program: plain `jax.jit` with the shared
    donation declaration, or the mesh-annotated wrap (decode-kernel
    sharding scope + a `with_sharding_constraint` pin on every
    returned pool carry) — the same body traces identically either
    way, so the trace-count keys never depend on placement.
  * **Stepper** (`PlainStepper` | `SpecStepper`) owns the per-
    iteration host dispatch: which program family runs one decode
    step, how the paged table/index ride in as traced inputs, and the
    adaptive effective-k controller (speculation only).

An engine is the composition `layout x placement x stepper`; the
public classes in engine.py/sharded.py are thin configuration shims.
Every body keeps its `trace_counts[key] += 1` side effect, so one
trace still means one compile wherever the body was built from.
"""
from __future__ import annotations

import time

import numpy as np

__all__ = ["DenseLayout", "PagedLayout", "SinglePlacement",
           "ShardedPlacement", "PlainStepper", "SpecStepper"]


# --------------------------------------------------------------------------
# cache layouts: pool state + the traceable program bodies
# --------------------------------------------------------------------------

class CacheLayout:
    """Base: the engine-agnostic program bodies (the draft proposal is
    pure jnp over per-slot rows — identical for every layout)."""

    def __init__(self, eng):
        self.eng = eng

    def __repr__(self):
        # folded into the persistent program-cache fingerprint: must
        # be stable across processes (no default object address repr).
        # Layouts are parameterless — pool geometry already lives in
        # the engine half of the fingerprint — so the class name is
        # the whole identity.
        return type(self).__name__

    @staticmethod
    def distinct_leaves(state):
        """Donated carries must not alias each other: the cache
        constructors share one zero buffer between K and V halves
        (cheap when the state is only read), but XLA rejects donating
        the same buffer twice in one call — give every repeated leaf
        its own buffer before the state becomes a donated carry."""
        import jax

        seen = set()

        def fix(x):
            if not hasattr(x, "copy"):
                return x
            if id(x) in seen:
                return x.copy()
            seen.add(id(x))
            return x

        return jax.tree_util.tree_map(fix, state)

    # ---- program-family keys ----
    def join_key(self, Pb):
        raise NotImplementedError

    def step_key(self):
        raise NotImplementedError

    def spec_step_key(self):
        raise NotImplementedError

    def draft_key(self):
        return ("draft",) + self.eng._pool_key

    # ---- host hooks the steppers drive ----
    def map_step_pages(self, active, width):
        """Make the next `width` write positions of every occupied
        slot physically backed (paged: map pages, evicting a starved
        slot under oversubscription). Returns the possibly-updated
        active mask."""
        return active

    def step_extra_args(self):
        """Extra traced inputs the step programs take between the pool
        state and the per-slot masks (paged: the device table + per-
        slot write indices, shipped fresh so mapping never retraces)."""
        return ()

    def row_index(self):
        """Per-slot written-token counts, as a traced input for the
        draft proposal."""
        raise NotImplementedError

    def advance_rows(self, n_emit):
        """Advance host-owned write indices after a step delivered
        `n_emit` tokens per slot (dense carries its indices in-state —
        no-op)."""

    # ---- the draft proposal body (pure jnp, layout-independent) ----
    def draft_body(self, dkey):
        from ..text import speculative as SP

        eng = self.eng
        k, ngram = eng.spec_k, eng.spec_ngram

        def draft_fn(hist, tok, plen, pbk, index):
            eng.trace_counts[dkey] += 1  # one per trace = one compile
            return SP.ngram_propose(hist, tok, plen, pbk, k - 1,
                                    index - pbk, ngram)

        return draft_fn

    @staticmethod
    def _spec_join_rows(jnp, MHA, jax, state, out, prompt, length, Pb,
                        slot, L, constrain=None):
        """The speculation state a join splices alongside the K/V: the
        row's token history mirror (prompt at [0, Pb)), its true
        prompt length, and its bucket — shared by the dense and paged
        join bodies (and the disaggregated splice)."""
        c = constrain if constrain is not None else (lambda x: x)
        hist_row = jnp.concatenate(
            [prompt, jnp.zeros((1, L - prompt.shape[1]), jnp.int32)], 1)
        out["hist"] = c(MHA.splice_rows(state["hist"], slot, hist_row))
        out["plen"] = c(jax.lax.dynamic_update_slice(
            state["plen"], length.astype(jnp.int32), (slot,)))
        out["pbk"] = c(jax.lax.dynamic_update_slice(
            state["pbk"], jnp.full((1,), Pb, jnp.int32), (slot,)))
        return out


class DenseLayout(CacheLayout):
    """The contiguous [S, H, pool_len, D] StaticKVCache pool: every
    slot owns its worst-case rows, write indices live in the carry."""

    def join_key(self, Pb):
        return ("join", Pb)

    def step_key(self):
        return ("step",) + self.eng._pool_key

    def spec_step_key(self):
        return ("sstep",) + self.eng._pool_key

    def row_index(self):
        return self.eng._state["inc"][0].index

    # ---- state ----
    def build_state(self, memory):
        import jax.numpy as jnp

        eng = self.eng
        decoder = eng._net.decoder
        M, Dm = memory.shape
        dtype = jnp.asarray(np.asarray(memory)).dtype
        S, L = eng.num_slots, eng._pool_len
        inc = [layer.self_attn.gen_cache(None, max_length=L,
                                         batch_size=S, dtype=dtype)
               for layer in decoder.layers]
        static = []
        for layer in decoder.layers:
            z = jnp.zeros((S, layer.cross_attn.num_heads, M,
                           layer.cross_attn.head_dim), dtype)
            static.append((z, z))
        state = {
            "tok": jnp.zeros((S,), jnp.int32),
            "bias": jnp.zeros((S, L), jnp.float32),
            "mem": jnp.zeros((S, M, Dm), dtype),
            "inc": inc,
            "static": static,
        }
        if eng.spec_k:
            # the n-gram draft source's token mirror of the cache, plus
            # each slot's true prompt length / bucket for the logical
            # (hole-skipping) history view
            state["hist"] = jnp.zeros((S, L), jnp.int32)
            state["plen"] = jnp.zeros((S,), jnp.int32)
            state["pbk"] = jnp.zeros((S,), jnp.int32)
        return self.distinct_leaves(state)

    def pool_key(self, memory):
        eng = self.eng
        M, Dm = memory.shape
        import jax.numpy as jnp

        dtype = jnp.asarray(np.asarray(memory)).dtype
        return (eng.num_slots, eng._pool_len, M, Dm, str(dtype)) + \
            ((("spec", eng.spec_k, eng.spec_ngram),)
             if eng.spec_k else ()) + eng._adapter_pool_key()

    # ---- the join program (prefill + splice) ----
    # Every join-family body takes the pool `state` as a DONATED carry
    # (engine._DONATED_KINDS): the returned state's leaves are
    # slot-local dynamic-update-slices over the input leaves, which
    # XLA turns into in-place writes on the donated buffers — a join
    # costs its own slot's rows, not a whole-pool copy. Bodies must
    # therefore keep every non-updated leaf IDENTITY-passed (no
    # gratuitous reshapes/casts of untouched pool leaves), or the
    # aliasing degrades back to a copy.
    def join_body(self, Pb):
        import jax
        import jax.numpy as jnp

        from ..nn.layer.transformer import MultiHeadAttention as MHA

        eng = self.eng
        fm = eng._fm
        decoder = eng._net.decoder
        L = eng._pool_len
        spec = bool(eng.spec_k)
        key = self.join_key(Pb)
        neg = eng._neg

        def join_fn(params, buffers, state, slot, prompt, length,
                    memory, *ad):
            eng.trace_counts[key] += 1  # python side effect: one per
            #                             trace = one per compile
            kpos = jnp.arange(L, dtype=jnp.int32)
            hole = (kpos[None, :] >= length[:, None]) & \
                (kpos[None, :] < jnp.int32(Pb))
            bias_row = jnp.where(hole, jnp.float32(neg),
                                 jnp.float32(0.0))           # [1, L]
            positions = jnp.arange(Pb, dtype=jnp.int32)[None]
            inc0 = [layer.self_attn.gen_cache(
                None, max_length=Pb, batch_size=1, dtype=memory.dtype)
                for layer in decoder.layers]
            # `ad` = (adapter id, banks) on adapter-carrying engines:
            # the prefill runs under the tenant's LoRA delta
            with eng._lora_ctx(ad):
                (lg, inc1, static1), _ = fm.apply(
                    params, buffers, None, prompt, positions, memory,
                    training=False, tgt_mask=bias_row[:, :Pb],
                    memory_mask=None, inc=inc0, prefill=True)
            # token 0 conditions on the row's LAST REAL prompt position
            last = jnp.take_along_axis(
                lg, (length - 1)[:, None, None], axis=1)[:, 0]
            tok0 = last.argmax(-1).astype(jnp.int32)[0]
            new_inc = [MHA.static_kv_splice(pool, slot, c.k, c.v,
                                            jnp.int32(Pb))
                       for pool, c in zip(state["inc"], inc1)]
            new_static = [(MHA.splice_rows(pk, slot, sk),
                           MHA.splice_rows(pv, slot, sv))
                          for (pk, pv), (sk, sv) in zip(state["static"],
                                                        static1)]
            new_state = {
                "tok": jax.lax.dynamic_update_slice(
                    state["tok"], tok0[None], (slot,)),
                "bias": MHA.splice_rows(state["bias"], slot, bias_row),
                "mem": MHA.splice_rows(state["mem"], slot, memory),
                "inc": new_inc,
                "static": new_static,
            }
            if spec:
                new_state = self._spec_join_rows(
                    jnp, MHA, jax, state, new_state, prompt, length,
                    Pb, slot, L)
            return new_state, tok0

        return join_fn

    # ---- the chunked-prefill program (verify-mode chunk append) ----
    def cjoin_body(self, Cb):
        """Prefill ONE Cb-token chunk of a prompt straight into the
        slot's pool rows: a batch-1 view of the slot's K/V runs the
        chunk through the verify-mode attention path (multi-token
        write at [seed, seed + Cb), causal read over everything the
        earlier chunks wrote), then splices the view row back — decode
        steps interleave between chunks, so a long prompt never stalls
        co-resident decodes longer than one chunk. One compile per
        CHUNK bucket, never per prompt: seed, true prompt length, and
        the prompt bucket all ride in as traced scalars. Every splice
        is computed from the TRUE final (length, Pb) — re-running a
        chunk is idempotent — and the tok0 lane is CLAMPED into the
        chunk, so only the final chunk's tok0 is meaningful (the host
        ignores the rest). Stale previous-occupant K/V past the chunk
        end is causal-masked until a later chunk or decode write
        replaces it, and the eos-padded tail of the final chunk lands
        inside the [length, Pb) hole the bias row masks forever."""
        import jax
        import jax.numpy as jnp

        from ..nn.layer.transformer import MultiHeadAttention as MHA
        from ..ops import attention as A

        eng = self.eng
        fm = eng._fm
        fm_cross = eng._fm_cross
        L = eng._pool_len
        spec = bool(eng.spec_k)
        ck = ("cjoin", Cb)
        neg = eng._neg

        def cjoin_fn(params, buffers, cparams, cbuffers, state, slot,
                     chunk, seed, length, pb, memory, *rest):
            eng.trace_counts[ck] += 1  # one per trace = one compile
            if spec:
                (hist_row,), ad = rest[:1], rest[1:]
            else:
                hist_row, ad = None, rest
            static1, _ = fm_cross.apply(cparams, cbuffers, None,
                                        memory, training=False)
            kpos = jnp.arange(L, dtype=jnp.int32)
            hole = (kpos[None, :] >= length[:, None]) & \
                (kpos[None, :] < pb)
            bias_row = jnp.where(hole, jnp.float32(neg),
                                 jnp.float32(0.0))           # [1, L]
            # batch-1 view of the slot's rows: the verify-scope write
            # lands the chunk K/V at [seed, seed + Cb) and the causal
            # read sees the earlier chunks already in the row
            inc = [MHA.StaticKVCache(
                jax.lax.dynamic_slice_in_dim(c.k, slot, 1, axis=0),
                jax.lax.dynamic_slice_in_dim(c.v, slot, 1, axis=0),
                seed.reshape(1)) for c in state["inc"]]
            posn = seed + jnp.arange(Cb, dtype=jnp.int32)[None]
            with A.kv_verify_scope(), eng._lora_ctx(ad):
                (lg, inc2), _ = fm.apply(
                    params, buffers, None, chunk, posn, memory,
                    training=False, tgt_mask=bias_row,
                    memory_mask=None, inc=inc, static_kv=static1,
                    prefill=False)
            # the LAST REAL prompt position sits at chunk lane
            # (length - 1 - seed) on the final chunk only; clamp keeps
            # mid-chunk dispatches in-bounds (their tok0 is discarded)
            lane = jnp.clip(length - 1 - seed, 0, Cb - 1)
            last = jnp.take_along_axis(lg, lane[:, None, None],
                                       axis=1)[:, 0]
            tok0 = last.argmax(-1).astype(jnp.int32)[0]
            new_inc = [MHA.static_kv_splice(pool, slot, c.k, c.v, pb)
                       for pool, c in zip(state["inc"], inc2)]
            new_static = [(MHA.splice_rows(pk, slot, sk),
                           MHA.splice_rows(pv, slot, sv))
                          for (pk, pv), (sk, sv) in zip(state["static"],
                                                        static1)]
            out = dict(
                state,
                tok=jax.lax.dynamic_update_slice(
                    state["tok"], tok0[None], (slot,)),
                bias=MHA.splice_rows(state["bias"], slot, bias_row),
                mem=MHA.splice_rows(state["mem"], slot, memory),
                inc=new_inc,
                static=new_static)
            if spec:
                out["hist"] = MHA.splice_rows(state["hist"], slot,
                                              hist_row)
                out["plen"] = jax.lax.dynamic_update_slice(
                    state["plen"], length.astype(jnp.int32), (slot,))
                out["pbk"] = jax.lax.dynamic_update_slice(
                    state["pbk"], pb.reshape(1).astype(jnp.int32),
                    (slot,))
            return out, tok0

        return cjoin_fn

    # ---- the plain batched decode step ----
    def step_body(self, key):
        import jax.numpy as jnp

        from ..nn.layer.transformer import MultiHeadAttention as MHA

        eng = self.eng
        fm = eng._fm

        def step_fn(params, buffers, state, *rest):
            eng.trace_counts[key] += 1  # one per trace = one compile
            *ad, active = rest          # ad = (ids, banks) | ()
            inc = state["inc"]
            posn = inc[0].index[:, None]  # per-SLOT written counts
            with eng._lora_ctx(ad):
                (lg, inc2), _ = fm.apply(
                    params, buffers, None, state["tok"][:, None], posn,
                    state["mem"], training=False,
                    tgt_mask=state["bias"], memory_mask=None, inc=inc,
                    static_kv=state["static"], prefill=False)
            nxt = lg[:, 0].argmax(-1).astype(jnp.int32)
            nxt = jnp.where(active, nxt, state["tok"])
            # inactive slots must not creep their write index: their
            # (masked, garbage) write this step gets overwritten before
            # it can ever become visible, but the index itself must
            # stay put so an idle slot never marches toward max_len
            inc2 = [MHA.StaticKVCache(
                c.k, c.v, jnp.where(active, c.index, old.index))
                for c, old in zip(inc2, inc)]
            return dict(state, tok=nxt, inc=inc2), nxt

        return step_fn

    # ---- the speculative verify step (draft acceptance + rollback) ----
    def spec_step_body(self, vkey):
        import jax.numpy as jnp

        from ..nn.layer.transformer import MultiHeadAttention as MHA
        from ..ops import attention as A
        from ..text import speculative as SP
        from ..text.decode import greedy_accept

        eng = self.eng
        fm = eng._fm
        k = eng.spec_k

        def sstep_fn(params, buffers, state, *rest):
            eng.trace_counts[vkey] += 1  # one per trace = one compile
            *ad, drafts, active, spec_on, k_eff = rest
            inc = state["inc"]
            idx0 = inc[0].index
            # a spec=False slot's drafts are forced unmatched (-1 never
            # equals a vocab token), so it accepts exactly one oracle
            # token per step; lanes past the adaptive effective k are
            # force-rejected the same way — shrinking/regrowing k NEVER
            # changes a shape, so it never retraces
            lane = jnp.arange(k - 1, dtype=jnp.int32)[None, :]
            live = spec_on[:, None] & (lane < k_eff - 1)
            drafts = jnp.where(live, drafts, -1)
            fed = jnp.concatenate([state["tok"][:, None], drafts], 1)
            posn = idx0[:, None] + jnp.arange(k, dtype=jnp.int32)[None]
            with A.kv_verify_scope(), eng._lora_ctx(ad):
                (lg, inc2), _ = fm.apply(
                    params, buffers, None, fed, posn, state["mem"],
                    training=False, tgt_mask=state["bias"],
                    memory_mask=None, inc=inc,
                    static_kv=state["static"], prefill=False)
            preds = lg.argmax(-1).astype(jnp.int32)
            n_match, emit = greedy_accept(drafts, preds)
            n_emit = jnp.where(active, n_match + 1, 0).astype(jnp.int32)
            # acceptance rollback on active rows, index pin on the rest
            # (the same inactive-slot contract as the plain step)
            new_idx = SP.rollback_index(inc2[0].index, k, n_match,
                                        active)
            inc3 = [MHA.StaticKVCache(c.k, c.v, new_idx) for c in inc2]
            corr = jnp.take_along_axis(preds, n_match[:, None],
                                       axis=1)[:, 0]
            nxt = jnp.where(active, corr, state["tok"])
            new_state = dict(
                state, tok=nxt, inc=inc3,
                hist=SP.write_hist(state["hist"], fed, idx0))
            return new_state, (emit, n_emit)

        return sstep_fn


class PagedLayout(CacheLayout):
    """The global fixed-size page pool with host-owned indirection:
    write indices and the page table ride in as traced inputs every
    step, so mapping/rollback are pure host index arithmetic."""

    def join_key(self, Pb):
        return ("pjoin", Pb)

    def step_key(self):
        return ("pstep",) + self.eng._pool_key

    def spec_step_key(self):
        return ("pverify",) + self.eng._pool_key

    def row_index(self):
        import jax.numpy as jnp

        return jnp.asarray(self.eng._index.astype(np.int32))

    def map_step_pages(self, active, width):
        from .paging import OutOfPages

        eng = self.eng
        psz = eng.page_size
        now = eng.clock()
        # map the page(s) the next `width` write positions need; under
        # oversubscription a dry pool evicts the starved slot with its
        # partial tokens (the pool itself keeps serving). Speculative
        # steps write the FULL fixed-k block (force-rejected tail
        # included), so every page the block touches must be mapped.
        # Pending slots (mid chunked-prefill) are skipped: their index
        # sits mid-PROMPT, the pages there are the chunk programs' to
        # map, and a dry pool must never OOM-evict a half-prefilled
        # slot on a decode step it does not even participate in.
        for s, r in enumerate(list(eng.slots)):
            if r is None or s in eng._pending:
                continue
            i0 = int(eng._index[s])
            for pi in range(i0 // psz, (i0 + width - 1) // psz + 1):
                if eng._table[s, pi] < 0:
                    try:
                        eng._table[s, pi] = eng._alloc_pages(1)[0]
                    except OutOfPages as e:
                        eng._evict_oom(s, e, now)
                        break
        return np.asarray(
            [r is not None and s not in eng._pending
             for s, r in enumerate(eng.slots)], bool)

    def step_extra_args(self):
        import jax.numpy as jnp

        eng = self.eng
        return (eng._device_table(),
                jnp.asarray(eng._index.astype(np.int32)))

    def advance_rows(self, n_emit):
        self.eng._index += np.asarray(n_emit, np.int64).astype(
            self.eng._index.dtype)

    # ---- state ----
    def build_state(self, memory):
        import jax.numpy as jnp

        eng = self.eng
        decoder = eng._net.decoder
        M, Dm = memory.shape
        dtype = jnp.asarray(np.asarray(memory)).dtype
        S, L = eng.num_slots, eng._pool_len
        paged = []
        for layer in decoder.layers:
            c = layer.self_attn.gen_paged_cache(
                eng.num_pages, eng.page_size, S, eng.max_pages,
                dtype, eng.kv_dtype)
            paged.append({"k": c.k, "v": c.v, "ks": c.k_scale,
                          "vs": c.v_scale})
        static = []
        for layer in decoder.layers:
            z = jnp.zeros((S, layer.cross_attn.num_heads, M,
                           layer.cross_attn.head_dim), dtype)
            static.append((z, z))
        state = {
            "tok": jnp.zeros((S,), jnp.int32),
            "bias": jnp.zeros((S, L), jnp.float32),
            "mem": jnp.zeros((S, M, Dm), dtype),
            "static": static,
            "paged": paged,
        }
        if eng.spec_k:
            state["hist"] = jnp.zeros((S, L), jnp.int32)
            state["plen"] = jnp.zeros((S,), jnp.int32)
            state["pbk"] = jnp.zeros((S,), jnp.int32)
        return self.distinct_leaves(state)

    def pool_key(self, memory):
        import jax.numpy as jnp

        eng = self.eng
        M, Dm = memory.shape
        dtype = jnp.asarray(np.asarray(memory)).dtype
        return (eng.num_slots, eng._pool_len, M, Dm, str(dtype),
                eng.page_size, eng.num_pages, str(eng.kv_dtype)) + \
            ((("spec", eng.spec_k, eng.spec_ngram),)
             if eng.spec_k else ()) + eng._adapter_pool_key()

    # ---- the paged join program (prefill into pages) ----
    def join_body(self, Pb):
        import jax
        import jax.numpy as jnp

        from ..nn.layer.transformer import MultiHeadAttention as MHA
        from . import paging as PG

        eng = self.eng
        fm = eng._fm
        decoder = eng._net.decoder
        L = eng._pool_len
        spec = bool(eng.spec_k)
        ck = self.join_key(Pb)
        neg = eng._neg

        def join_fn(params, buffers, state, slot, prompt, length,
                    memory, page_ids, *ad):
            eng.trace_counts[ck] += 1  # one per trace = one compile
            kpos = jnp.arange(L, dtype=jnp.int32)
            hole = (kpos[None, :] >= length[:, None]) & \
                (kpos[None, :] < jnp.int32(Pb))
            bias_row = jnp.where(hole, jnp.float32(neg),
                                 jnp.float32(0.0))           # [1, L]
            positions = jnp.arange(Pb, dtype=jnp.int32)[None]
            inc0 = [layer.self_attn.gen_cache(
                None, max_length=Pb, batch_size=1, dtype=memory.dtype)
                for layer in decoder.layers]
            with eng._lora_ctx(ad):
                (lg, inc1, static1), _ = fm.apply(
                    params, buffers, None, prompt, positions, memory,
                    training=False, tgt_mask=bias_row[:, :Pb],
                    memory_mask=None, inc=inc0, prefill=True)
            last = jnp.take_along_axis(
                lg, (length - 1)[:, None, None], axis=1)[:, 0]
            tok0 = last.argmax(-1).astype(jnp.int32)[0]
            new_paged = []
            for pc, c in zip(state["paged"], inc1):
                cache = PG.PagedKVCache(pc["k"], pc["v"], pc["ks"],
                                        pc["vs"], None, None)
                cache = MHA.paged_prompt_splice(cache, page_ids,
                                                c.k, c.v)
                new_paged.append({"k": cache.k, "v": cache.v,
                                  "ks": cache.k_scale,
                                  "vs": cache.v_scale})
            new_static = [(MHA.splice_rows(pk, slot, sk),
                           MHA.splice_rows(pv, slot, sv))
                          for (pk, pv), (sk, sv) in zip(state["static"],
                                                        static1)]
            new_state = {
                "tok": jax.lax.dynamic_update_slice(
                    state["tok"], tok0[None], (slot,)),
                "bias": MHA.splice_rows(state["bias"], slot, bias_row),
                "mem": MHA.splice_rows(state["mem"], slot, memory),
                "static": new_static,
                "paged": new_paged,
            }
            if spec:
                new_state = self._spec_join_rows(
                    jnp, MHA, jax, state, new_state, prompt, length,
                    Pb, slot, L)
            return new_state, tok0

        return join_fn

    # ---- the prefix-attach program (zero-prefill shared join) ----
    def attach_body(self):
        import jax
        import jax.numpy as jnp

        from ..nn.layer.transformer import MultiHeadAttention as MHA

        eng = self.eng
        fm_cross = eng._fm_cross
        L = eng._pool_len
        spec = bool(eng.spec_k)
        ck = ("attach",)
        neg = eng._neg

        def attach_fn(cparams, cbuffers, state, slot, tok0, length,
                      pb, memory, *spec_rows):
            eng.trace_counts[ck] += 1
            static1, _ = fm_cross.apply(cparams, cbuffers, None,
                                        memory, training=False)
            kpos = jnp.arange(L, dtype=jnp.int32)
            hole = (kpos[None, :] >= length[:, None]) & \
                (kpos[None, :] < pb)                 # pb traced: one
            #                                          compile, all
            #                                          buckets
            bias_row = jnp.where(hole, jnp.float32(neg),
                                 jnp.float32(0.0))
            new_static = [(MHA.splice_rows(pk, slot, sk),
                           MHA.splice_rows(pv, slot, sv))
                          for (pk, pv), (sk, sv) in zip(state["static"],
                                                        static1)]
            out = dict(
                state,
                tok=jax.lax.dynamic_update_slice(
                    state["tok"], tok0[None], (slot,)),
                bias=MHA.splice_rows(state["bias"], slot, bias_row),
                mem=MHA.splice_rows(state["mem"], slot, memory),
                static=new_static)
            if spec:
                # the prompt tokens ride in pre-padded to the full
                # pool length, so the attach program stays ONE compile
                # for every bucket (pb is already traced)
                (hist_row,) = spec_rows
                out["hist"] = MHA.splice_rows(state["hist"], slot,
                                              hist_row)
                out["plen"] = jax.lax.dynamic_update_slice(
                    state["plen"], length.astype(jnp.int32), (slot,))
                out["pbk"] = jax.lax.dynamic_update_slice(
                    state["pbk"], pb.reshape(1).astype(jnp.int32),
                    (slot,))
            return out

        return attach_fn

    def cow_body(self):
        from . import paging as PG

        eng = self.eng
        ck = ("cow",)

        def cow_fn(state, src, dst):
            eng.trace_counts[ck] += 1
            new_paged = []
            for pc in state["paged"]:
                k, ks = PG.copy_page(pc["k"], pc["ks"], src, dst)
                v, vs = PG.copy_page(pc["v"], pc["vs"], src, dst)
                new_paged.append({"k": k, "v": v, "ks": ks, "vs": vs})
            return dict(state, paged=new_paged)

        return cow_fn

    # ---- the partial-attach program (radix hit: tail-only prefill) ----
    def pattach_body(self, Mb, Tb):
        """Prefill ONLY a prompt's divergent tail, seeded by trie-
        matched pages: the Tb-bucketed tail runs as ONE verify-mode
        block through the page pool itself — `write_tokens` lands the
        tail K/V at the seed boundary through a WIDTH-CLIPPED table row
        ([1, Mb + pages_for(Tb)]) and `paged_verify_attention` reads
        the matched seed K/V back through the same row, so attention
        cost scales with the HIT size, not the full pool. One compile
        per (matched-pages bucket, tail bucket) pair: seed length,
        slot, and true prompt length are traced scalars, so hit depth
        never retraces. Rides the same decode-sharding scope and LoRA
        context as the verify step, so sharded / spec / adapter cells
        inherit it unchanged."""
        import jax
        import jax.numpy as jnp

        from ..nn.layer.transformer import MultiHeadAttention as MHA
        from ..ops import attention as A
        from . import paging as PG

        eng = self.eng
        fm = eng._fm
        fm_cross = eng._fm_cross
        L = eng._pool_len
        psz = eng.page_size
        W = min(eng.max_pages, int(Mb) + PG.pages_for(Tb, psz))
        spec = bool(eng.spec_k)
        ck = ("pattach", Mb, Tb)
        neg = eng._neg

        def pattach_fn(params, buffers, cparams, cbuffers, state, slot,
                       trow, tail, seed_len, length, pb, memory, *rest):
            eng.trace_counts[ck] += 1  # one per trace = one compile
            if spec:
                (hist_row,), ad = rest[:1], rest[1:]
            else:
                hist_row, ad = None, rest
            static1, _ = fm_cross.apply(cparams, cbuffers, None,
                                        memory, training=False)
            kpos = jnp.arange(L, dtype=jnp.int32)
            hole = (kpos[None, :] >= length[:, None]) & \
                (kpos[None, :] < pb)
            bias_row = jnp.where(hole, jnp.float32(neg),
                                 jnp.float32(0.0))           # [1, L]
            # batch-1 paged view through the clipped table row: the
            # verify-scope write lands tail K/V at positions
            # [seed_len, seed_len + Tb) and the verify read gathers
            # only the W mapped pages (bias clipped to match)
            inc = [PG.PagedKVCache(pc["k"], pc["v"], pc["ks"],
                                   pc["vs"], trow, seed_len.reshape(1))
                   for pc in state["paged"]]
            posn = seed_len + jnp.arange(Tb, dtype=jnp.int32)[None]
            with A.kv_verify_scope(), eng._lora_ctx(ad):
                (lg, inc2), _ = fm.apply(
                    params, buffers, None, tail, posn, memory,
                    training=False, tgt_mask=bias_row[:, :W * psz],
                    memory_mask=None, inc=inc, static_kv=static1,
                    prefill=False)
            # token 0 conditions on the LAST REAL prompt position,
            # which sits at tail lane (length - 1 - seed_len)
            last = jnp.take_along_axis(
                lg, (length - 1 - seed_len)[:, None, None],
                axis=1)[:, 0]
            tok0 = last.argmax(-1).astype(jnp.int32)[0]
            new_paged = [{"k": c.k, "v": c.v, "ks": c.k_scale,
                          "vs": c.v_scale} for c in inc2]
            new_static = [(MHA.splice_rows(pk, slot, sk),
                           MHA.splice_rows(pv, slot, sv))
                          for (pk, pv), (sk, sv) in zip(state["static"],
                                                        static1)]
            out = dict(
                state,
                tok=jax.lax.dynamic_update_slice(
                    state["tok"], tok0[None], (slot,)),
                bias=MHA.splice_rows(state["bias"], slot, bias_row),
                mem=MHA.splice_rows(state["mem"], slot, memory),
                static=new_static,
                paged=new_paged)
            if spec:
                out["hist"] = MHA.splice_rows(state["hist"], slot,
                                              hist_row)
                out["plen"] = jax.lax.dynamic_update_slice(
                    state["plen"], length.astype(jnp.int32), (slot,))
                out["pbk"] = jax.lax.dynamic_update_slice(
                    state["pbk"], pb.reshape(1).astype(jnp.int32),
                    (slot,))
            return out, tok0

        return pattach_fn

    # ---- the chunked-prefill program (verify-mode chunk append) ----
    def pcjoin_body(self, Mb, Cb):
        """Prefill ONE Cb-token chunk of a prompt into the slot's
        pages: like `pattach_body` the chunk runs as a verify-mode
        block through a WIDTH-CLIPPED table row ([1, Mb +
        pages_for(Cb)]) — `write_tokens` lands the chunk K/V at the
        seed boundary and the verify read gathers only the pages the
        chunk can see, so attention cost scales with the SEED, not the
        pool. One compile per (seed-pages bucket, chunk bucket) pair,
        never per prompt: seed, slot, true length, and bucket are
        traced scalars. The trie-matched seed of a radix PARTIAL hit
        rides the same program (seed pages mapped read-only into the
        clipped row), so a chunk extends the matched node chunk by
        chunk. tok0's lane is CLAMPED into the chunk: only the final
        chunk's value is read by the host; every splice is computed
        from the TRUE final (length, Pb), so chunks are idempotent."""
        import jax
        import jax.numpy as jnp

        from ..nn.layer.transformer import MultiHeadAttention as MHA
        from ..ops import attention as A
        from . import paging as PG

        eng = self.eng
        fm = eng._fm
        fm_cross = eng._fm_cross
        L = eng._pool_len
        psz = eng.page_size
        W = min(eng.max_pages, int(Mb) + PG.pages_for(Cb, psz))
        spec = bool(eng.spec_k)
        ck = ("pcjoin", Mb, Cb)
        neg = eng._neg

        def pcjoin_fn(params, buffers, cparams, cbuffers, state, slot,
                      trow, chunk, seed, length, pb, memory, *rest):
            eng.trace_counts[ck] += 1  # one per trace = one compile
            if spec:
                (hist_row,), ad = rest[:1], rest[1:]
            else:
                hist_row, ad = None, rest
            static1, _ = fm_cross.apply(cparams, cbuffers, None,
                                        memory, training=False)
            kpos = jnp.arange(L, dtype=jnp.int32)
            hole = (kpos[None, :] >= length[:, None]) & \
                (kpos[None, :] < pb)
            bias_row = jnp.where(hole, jnp.float32(neg),
                                 jnp.float32(0.0))           # [1, L]
            inc = [PG.PagedKVCache(pc["k"], pc["v"], pc["ks"],
                                   pc["vs"], trow, seed.reshape(1))
                   for pc in state["paged"]]
            posn = seed + jnp.arange(Cb, dtype=jnp.int32)[None]
            with A.kv_verify_scope(), eng._lora_ctx(ad):
                (lg, inc2), _ = fm.apply(
                    params, buffers, None, chunk, posn, memory,
                    training=False, tgt_mask=bias_row[:, :W * psz],
                    memory_mask=None, inc=inc, static_kv=static1,
                    prefill=False)
            # the LAST REAL prompt position sits at chunk lane
            # (length - 1 - seed) on the final chunk only; clamp keeps
            # mid-chunk dispatches in-bounds (their tok0 is discarded)
            lane = jnp.clip(length - 1 - seed, 0, Cb - 1)
            last = jnp.take_along_axis(lg, lane[:, None, None],
                                       axis=1)[:, 0]
            tok0 = last.argmax(-1).astype(jnp.int32)[0]
            new_paged = [{"k": c.k, "v": c.v, "ks": c.k_scale,
                          "vs": c.v_scale} for c in inc2]
            new_static = [(MHA.splice_rows(pk, slot, sk),
                           MHA.splice_rows(pv, slot, sv))
                          for (pk, pv), (sk, sv) in zip(state["static"],
                                                        static1)]
            out = dict(
                state,
                tok=jax.lax.dynamic_update_slice(
                    state["tok"], tok0[None], (slot,)),
                bias=MHA.splice_rows(state["bias"], slot, bias_row),
                mem=MHA.splice_rows(state["mem"], slot, memory),
                static=new_static,
                paged=new_paged)
            if spec:
                out["hist"] = MHA.splice_rows(state["hist"], slot,
                                              hist_row)
                out["plen"] = jax.lax.dynamic_update_slice(
                    state["plen"], length.astype(jnp.int32), (slot,))
                out["pbk"] = jax.lax.dynamic_update_slice(
                    state["pbk"], pb.reshape(1).astype(jnp.int32),
                    (slot,))
            return out, tok0

        return pcjoin_fn

    # ---- the plain batched decode step (through the page table) ----
    def step_body(self, ck):
        import jax.numpy as jnp

        from . import paging as PG

        eng = self.eng
        fm = eng._fm

        def step_fn(params, buffers, state, table, index, *rest):
            eng.trace_counts[ck] += 1  # one per trace = one compile
            *ad, active = rest          # ad = (ids, banks) | ()
            inc = [PG.PagedKVCache(pc["k"], pc["v"], pc["ks"],
                                   pc["vs"], table, index)
                   for pc in state["paged"]]
            posn = index[:, None]
            with eng._lora_ctx(ad):
                (lg, inc2), _ = fm.apply(
                    params, buffers, None, state["tok"][:, None], posn,
                    state["mem"], training=False,
                    tgt_mask=state["bias"], memory_mask=None, inc=inc,
                    static_kv=state["static"], prefill=False)
            nxt = lg[:, 0].argmax(-1).astype(jnp.int32)
            nxt = jnp.where(active, nxt, state["tok"])
            new_paged = [{"k": c.k, "v": c.v, "ks": c.k_scale,
                          "vs": c.v_scale} for c in inc2]
            return dict(state, tok=nxt, paged=new_paged), nxt

        return step_fn

    # ---- the paged speculative verify step ----
    def spec_step_body(self, vkey):
        import jax.numpy as jnp

        from ..ops import attention as A
        from ..text import speculative as SP
        from ..text.decode import greedy_accept

        eng = self.eng
        fm = eng._fm
        k = eng.spec_k

        def pverify_fn(params, buffers, state, table, index, *rest):
            eng.trace_counts[vkey] += 1  # one per trace = one compile
            from . import paging as PG

            *ad, drafts, active, spec_on, k_eff = rest
            # force-reject the opted-out rows and the lanes past the
            # adaptive effective k (-1 never equals a vocab token): k
            # changes ride the SAME fixed-k compiled program
            lane = jnp.arange(k - 1, dtype=jnp.int32)[None, :]
            live = spec_on[:, None] & (lane < k_eff - 1)
            drafts = jnp.where(live, drafts, -1)
            fed = jnp.concatenate([state["tok"][:, None], drafts], 1)
            posn = index[:, None] + jnp.arange(k, dtype=jnp.int32)[None]
            inc = [PG.PagedKVCache(pc["k"], pc["v"], pc["ks"],
                                   pc["vs"], table, index)
                   for pc in state["paged"]]
            with A.kv_verify_scope(), eng._lora_ctx(ad):
                (lg, inc2), _ = fm.apply(
                    params, buffers, None, fed, posn, state["mem"],
                    training=False, tgt_mask=state["bias"],
                    memory_mask=None, inc=inc,
                    static_kv=state["static"], prefill=False)
            preds = lg.argmax(-1).astype(jnp.int32)
            n_match, emit = greedy_accept(drafts, preds)
            n_emit = jnp.where(active, n_match + 1, 0).astype(jnp.int32)
            corr = jnp.take_along_axis(preds, n_match[:, None],
                                       axis=1)[:, 0]
            nxt = jnp.where(active, corr, state["tok"])
            # rollback is pure index arithmetic and the index is HOST-
            # owned (a traced input, not a carry): the stepper adds
            # n_emit per row; rejected tokens sit masked behind it and
            # their already-mapped pages are simply rewritten next
            # round — no page frees on reject
            new_paged = [{"k": c.k, "v": c.v, "ks": c.k_scale,
                          "vs": c.v_scale} for c in inc2]
            new_state = dict(
                state, tok=nxt, paged=new_paged,
                hist=SP.write_hist(state["hist"], fed, index))
            return new_state, (emit, n_emit)

        return pverify_fn


# --------------------------------------------------------------------------
# placements: how a body becomes a compiled program
# --------------------------------------------------------------------------

class SinglePlacement:
    """Plain `jax.jit` with the engine's shared donation declaration —
    the single-chip build path every engine used before placement was
    an axis. The declaration now spans the WHOLE program matrix (the
    step family AND the join family), so every body's pool carry is a
    slot-local in-place update, never a whole-pool copy; the engine's
    guarded-retry path owns the failure semantics the donation
    sharpens (see engine._DONATED_KINDS)."""

    def __init__(self, eng):
        self.eng = eng

    def build(self, key, body, has_aux=True):
        import jax

        return jax.jit(body,
                       donate_argnums=self.eng._donate_argnums(key))


class ShardedPlacement:
    """Mesh-annotated builds: the SAME single-chip body traced under
    the decode-kernel sharding scope, every returned pool carry pinned
    to the dp slot layout, donation per the shared declaration. Also
    owns the pool-state placement (device_put onto the decode mesh)."""

    def __init__(self, eng):
        self.eng = eng

    def _decode_specs(self):
        ns = self.eng._ns_pool
        return {"q": ns, "kv": ns, "pages": ns, "out": ns}

    def constrain_state(self, state):
        """Pin PartitionSpec('dp') on every pool carry (slot-leading
        leaves; the paged page/scale arrays shard their page axis the
        same way), replicating nothing implicitly — the every-carry
        contract."""
        import jax

        from ..nn.layer.transformer import MultiHeadAttention as MHA

        c = lambda x: jax.lax.with_sharding_constraint(  # noqa: E731
            x, self.eng._ns_pool)
        out = dict(state)
        for k in ("tok", "bias", "mem", "hist", "plen", "pbk"):
            if k in out:
                out[k] = c(out[k])
        if "inc" in out:
            out["inc"] = [MHA.StaticKVCache(c(cc.k), c(cc.v),
                                            c(cc.index))
                          for cc in out["inc"]]
        if "static" in out:
            out["static"] = [(c(sk), c(sv)) for sk, sv in out["static"]]
        if "paged" in out:
            out["paged"] = [
                {"k": c(pc["k"]), "v": c(pc["v"]),
                 "ks": None if pc["ks"] is None else c(pc["ks"]),
                 "vs": None if pc["vs"] is None else c(pc["vs"])}
                for pc in out["paged"]]
        return out

    def build(self, key, body, has_aux=True):
        """jit a single-chip engine body with the sharded annotations:
        decode kernels constrained via `decode_shardings`, every
        returned carry pinned to the pool layout, the step-family
        state carry donated per the shared `_donate_argnums`
        declaration (same donation audit as the single-chip builds)."""
        import jax

        from ..ops import attention as A

        specs = self._decode_specs()

        def fn(*args):
            with A.decode_shardings(specs):
                out = body(*args)
            if has_aux:
                st, aux = out
                return self.constrain_state(st), aux
            return self.constrain_state(out)

        return jax.jit(fn, donate_argnums=self.eng._donate_argnums(key))

    def place_state(self, state):
        """Lay the freshly-built pool state out on the decode mesh:
        slot-leading leaves shard over dp (the KV pool is REBUILT with
        `gen_cache`'s sharded constructors so the zeros never
        materialize on one device)."""
        import jax

        eng = self.eng
        L, S = eng._pool_len, eng.num_slots
        dtype = state["mem"].dtype
        decoder = eng._net.decoder
        ns = eng._ns_pool
        out = dict(state)
        for k in ("tok", "bias", "mem", "hist", "plen", "pbk"):
            if k in state:
                out[k] = jax.device_put(state[k], ns)
        out["static"] = [
            (jax.device_put(sk, ns), jax.device_put(sv, ns))
            for sk, sv in state["static"]]
        if "inc" in state:
            out["inc"] = [layer.self_attn.gen_cache(
                None, max_length=L, batch_size=S, dtype=dtype,
                kv_sharding=ns, index_sharding=ns)
                for layer in decoder.layers]
        if "paged" in state:
            # pad the page-row count to a dp multiple so the page axis
            # lays out evenly; rows past the trash row (num_pages) are
            # never referenced by any table entry — pure padding
            rows = eng.num_pages + 1
            padded = -(-rows // eng._pool_dp) * eng._pool_dp
            paged = []
            for layer in decoder.layers:
                cc = layer.self_attn.gen_paged_cache(
                    padded - 1, eng.page_size, S, eng.max_pages,
                    dtype, eng.kv_dtype, page_sharding=ns)
                paged.append({"k": cc.k, "v": cc.v, "ks": cc.k_scale,
                              "vs": cc.v_scale})
            out["paged"] = paged
        return CacheLayout.distinct_leaves(out)


# --------------------------------------------------------------------------
# steppers: the per-iteration decode dispatch
# --------------------------------------------------------------------------

class PlainStepper:
    """One token per slot per iteration: ONE batched program dispatch
    over the active mask."""

    def __init__(self, eng):
        self.eng = eng

    def decode(self, active):
        import jax.numpy as jnp

        eng = self.eng
        lay = eng.layout
        active = lay.map_step_pages(active, 1)
        if not active.any():
            return np.zeros((eng.num_slots,), np.int64)
        key = lay.step_key()
        fn = eng._program(key, lambda: eng._build_step(key))
        eng._state, toks = fn(eng._params(), eng._buffers(),
                              eng._state, *lay.step_extra_args(),
                              *eng._adapter_args(),
                              jnp.asarray(active))
        lay.advance_rows(active.astype(np.int64))
        return np.asarray(toks)


class SpecStepper:
    """Draft-verify: two dispatches deliver up to k tokens per slot,
    plus the adaptive effective-k controller — batch-wide, driven by
    the acceptance-rate gauge with hysteresis. `k_eff` rides into the
    fixed-k verify program as a traced scalar (lanes past it are
    force-rejected in-program), so shrinking or regrowing k NEVER
    retraces; the retrace-sentinel soaks hold this with adaptation
    exercised."""

    def __init__(self, eng):
        self.eng = eng
        self.k_eff = eng.spec_k
        self.k_shrink_events = 0
        self.k_grow_events = 0
        self._ema = None
        self._low_rounds = 0
        self._high_rounds = 0

    def _adapt(self, on_count, accepted):
        """Hysteresis: the acceptance-rate EMA must sit below/above the
        band for `spec_adapt_patience` consecutive rounds before k
        shrinks/regrows one step — a single unlucky round never
        thrashes the ladder."""
        eng = self.eng
        if not eng.spec_adapt or not on_count:
            return
        lanes = on_count * max(1, self.k_eff - 1)
        rate = accepted / lanes
        a = eng.spec_adapt_alpha
        self._ema = rate if self._ema is None else \
            (1 - a) * self._ema + a * rate
        if self._ema < eng.spec_adapt_low and self.k_eff > 2:
            self._low_rounds += 1
            self._high_rounds = 0
            if self._low_rounds >= eng.spec_adapt_patience:
                self.k_eff -= 1
                self.k_shrink_events += 1
                self._low_rounds = 0
                self._ema = None   # fresh window at the new k
        elif self._ema > eng.spec_adapt_high and \
                self.k_eff < eng.spec_k:
            self._high_rounds += 1
            self._low_rounds = 0
            if self._high_rounds >= eng.spec_adapt_patience:
                self.k_eff += 1
                self.k_grow_events += 1
                self._high_rounds = 0
                self._ema = None
        else:
            self._low_rounds = self._high_rounds = 0

    def decode(self, active):
        import jax
        import jax.numpy as jnp

        eng = self.eng
        lay = eng.layout
        # the verify write is the FULL fixed-k block (force-rejected
        # tail included), so the paged pool maps every page it touches
        active = lay.map_step_pages(active, eng.spec_k)
        if not active.any():
            S, k = eng.num_slots, eng.spec_k
            return (np.zeros((S, k), np.int64), np.zeros((S,), np.int64))
        spec_on = np.asarray(
            [r is not None and getattr(r, "spec", True)
             for r in eng.slots], bool)
        st = eng._state
        dkey = lay.draft_key()
        fn = eng._program(dkey, lambda: eng._build_draft(dkey))
        t0 = time.perf_counter()
        drafts = fn(st["hist"], st["tok"], st["plen"], st["pbk"],
                    lay.row_index())
        jax.block_until_ready(drafts)
        t1 = time.perf_counter()
        vkey = lay.spec_step_key()
        fn = eng._program(vkey, lambda: eng._build_spec_step(vkey))
        eng._state, (emit, n_emit) = fn(
            eng._params(), eng._buffers(), eng._state,
            *lay.step_extra_args(), *eng._adapter_args(), drafts,
            jnp.asarray(active), jnp.asarray(spec_on),
            jnp.int32(self.k_eff))
        emit = np.asarray(emit)
        n_emit = np.asarray(n_emit)
        t2 = time.perf_counter()
        lay.advance_rows(n_emit)
        on = active & spec_on
        on_count = int(on.sum())
        proposed = on_count * (self.k_eff - 1)
        accepted = int(np.maximum(n_emit[on] - 1, 0).sum()) \
            if on_count else 0
        self._adapt(on_count, accepted)
        eng.metrics.record_spec_step(
            int(active.sum()), proposed, accepted, t1 - t0, t2 - t1,
            k_eff=self.k_eff, variant=eng._pool_variant(),
            k_shrinks=self.k_shrink_events,
            k_grows=self.k_grow_events)
        from ..profiler import trace as _trace

        if _trace._SESSION is not None:
            from . import tracing as _rt

            _rt.on_spec_step(t0, t1, t2, int(active.sum()), proposed,
                             accepted)
        return emit, n_emit
