"""Paged KV-cache subsystem for the serving pool.

The dense serving pool (engine.py) preallocates one contiguous
[S, H, max_len, D] K/V region per layer, so every slot pays for the
worst-case `max_len` whether its request uses 9 tokens or 900, and
identical system prompts are re-prefilled for every request. This
module replaces the per-slot rows with a **global pool of fixed-size
pages** plus an int32 indirection:

  * pages live in static-shape arrays `[n_pages + 1, H, page_size, D]`
    per layer (row `n_pages` is the TRASH page — inactive slots' masked
    decode writes land there, never on live data);
  * each slot owns an int32 `page_table[S, max_pages]` row mapping its
    logical block i to a physical page (host-side `-1` = unmapped,
    clipped to the trash row before it reaches the device);
  * `PageAllocator` hands pages out of a free list with refcounts, so
    several slots can map the SAME physical page read-only (shared
    prompt prefixes) and a page returns to the free list exactly when
    its last reference drops;
  * `PrefixCache` keys fully-prefilled prompt pages on the prompt's
    token hash (+ the cross-attention memory digest — the decoder's
    self-attention K/V depend on it through the cross-attn residual
    stream), so a request repeating a known prompt maps the cached
    pages with ZERO prefill FLOPs; the page a joiner will decode-write
    into is copied first (copy-on-write), so cached pages are
    immutable;
  * pages store K/V in fp32 / bf16 / int8 behind the engine's
    `kv_dtype=` knob; int8 pages carry a per-(page, head) f32 scale
    (symmetric, amax/127) that grows monotonically — a decode write
    whose token outranges the page rescales the existing int8 payload
    in place — and is applied at read time (in-kernel on TPU, in the
    gather fallback elsewhere).

Everything here is either pure host bookkeeping (allocator, prefix
cache, page tables as numpy) or pure jnp array math safe inside jit
(quantize / scatter / gather / copy). Shapes stay static for any pool
config: the page table is a traced int32 input, so joining, evicting,
and decode never retrace — the same trick the split-K decode kernel
uses for its traced written-token counts.
"""
from __future__ import annotations

import collections
import hashlib

import numpy as np

__all__ = ["OutOfPages", "PageAllocator", "PrefixCache",
           "RadixPrefixCache", "PagedKVCache",
           "pages_for", "resolve_kv_dtype", "quantize_chunks",
           "chunk_prompt", "write_prompt_pages", "write_token",
           "write_tokens", "copy_page", "gather_pages"]

_QMAX = 127.0


class OutOfPages(RuntimeError):
    """The page pool cannot serve an allocation: backpressure (the
    scheduler keeps the request queued until pages free up) or, when it
    strikes mid-decode under oversubscription, a victim eviction."""


#: the decode-engine paged cache: per-layer page arrays + the shared
#: per-slot indirection. Leaves are raw jax arrays (valid jit inputs /
#: scan carries); `k_scale`/`v_scale` are None unless the pages are
#: int8. `table` is the [S, max_pages] int32 page table (trash-clipped)
#: and `index` the per-slot written-token count — both shipped fresh
#: from the host each step, so page mapping changes never retrace.
PagedKVCache = collections.namedtuple(
    "PagedKVCache", ["k", "v", "k_scale", "v_scale", "table", "index"])


def pages_for(n_tokens, page_size):
    """Pages needed to hold `n_tokens` cache positions."""
    return -(-int(n_tokens) // int(page_size))


def resolve_kv_dtype(kv_dtype, compute_dtype):
    """The engine's `kv_dtype=` knob -> (storage jnp dtype, quantized?).
    None keeps the compute dtype (bit-exact paging); "bf16" stores
    bfloat16; "int8" stores symmetric int8 with per-(page, head)
    scales."""
    import jax.numpy as jnp

    if kv_dtype is None:
        return jnp.dtype(compute_dtype), False
    name = str(kv_dtype).lower()
    if name in ("int8", "i1"):
        return jnp.dtype(jnp.int8), True
    if name in ("bf16", "bfloat16"):
        return jnp.dtype(jnp.bfloat16), False
    if name in ("f4", "f32", "float32"):
        return jnp.dtype(jnp.float32), False
    return jnp.dtype(kv_dtype), False


# --------------------------------------------------------------------------
# host side: allocator + prefix cache
# --------------------------------------------------------------------------

class PageAllocator:
    """Free-list + refcount bookkeeping over `n_pages` physical pages.
    Host-side only — it never touches device arrays; the engine turns
    its decisions into page-table entries. `alloc` raises `OutOfPages`
    without partial effects; refcounts let shared prompt pages outlive
    any single slot."""

    def __init__(self, n_pages, page_size):
        if n_pages < 1 or page_size < 1:
            raise ValueError("n_pages and page_size must be >= 1")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        # pop() takes from the end: keep ids ascending for readability
        self._free = list(range(self.n_pages - 1, -1, -1))
        self.refcount = np.zeros(self.n_pages, np.int32)

    @property
    def pages_free(self):
        return len(self._free)

    @property
    def pages_in_use(self):
        return self.n_pages - len(self._free)

    def alloc(self, n):
        """Allocate `n` pages (refcount 1 each) or raise OutOfPages
        with NO pages taken."""
        n = int(n)
        if n > len(self._free):
            raise OutOfPages(
                f"need {n} pages, {len(self._free)} free of "
                f"{self.n_pages}")
        pages = [self._free.pop() for _ in range(n)]
        self.refcount[pages] = 1
        return pages

    def incref(self, pages):
        for p in pages:
            if self.refcount[p] <= 0:
                raise RuntimeError(f"incref on free page {p}")
            self.refcount[p] += 1

    def decref(self, pages):
        """Drop one reference per page; pages reaching zero return to
        the free list (double-free raises — the invariant tests lean on
        this)."""
        for p in pages:
            p = int(p)
            if self.refcount[p] <= 0:
                raise RuntimeError(f"decref on free page {p}")
            self.refcount[p] -= 1
            if self.refcount[p] == 0:
                self._free.append(p)

    def check(self):
        """Invariants: free + referenced partitions the pool exactly;
        raises on any violation (used by the soak test and the chaos
        leak check)."""
        free = set(self._free)
        if len(free) != len(self._free):
            raise AssertionError("duplicate pages on the free list")
        held = {p for p in range(self.n_pages) if self.refcount[p] > 0}
        if free & held:
            raise AssertionError(f"pages both free and held: "
                                 f"{sorted(free & held)}")
        if free | held != set(range(self.n_pages)):
            raise AssertionError("leaked pages: neither free nor held: "
                                 f"{sorted(set(range(self.n_pages)) - free - held)}")
        if (self.refcount < 0).any():
            raise AssertionError("negative refcount")
        return True


class PrefixCache:
    """Host-side map from (prompt tokens, memory digest) to the
    immutable pages a previous join prefilled for that prompt, plus the
    prefill's first greedy token. Whole-prompt granularity: a hit means
    the ENTIRE padded prompt block [0, Pb) is served by shared pages
    and the join runs zero prefill FLOPs. LRU-bounded: inserting past
    `capacity` (or an explicit `reclaim`) drops the oldest entries,
    releasing the cache's page references."""

    def __init__(self, allocator, capacity=64):
        self.allocator = allocator
        self.capacity = int(capacity)
        self._entries = collections.OrderedDict()
        self.hits = 0
        self.misses = 0

    @staticmethod
    def key_of(prompt, memory):
        prompt = np.asarray(prompt)
        mem = b"" if memory is None else np.ascontiguousarray(memory)
        digest = hashlib.sha1()
        digest.update(np.ascontiguousarray(prompt.astype(np.int64)))
        if memory is not None:
            digest.update(str(mem.dtype).encode())
            digest.update(str(mem.shape).encode())
            digest.update(mem)
        # the digest alone would admit hash collisions across prompts;
        # carrying the token tuple keeps lookups exact
        return (tuple(int(t) for t in prompt.ravel()),
                digest.hexdigest())

    def __len__(self):
        return len(self._entries)

    def peek(self, key):
        """Like lookup, but no hit/miss accounting and no MRU move —
        the admission gate's headroom estimate uses it."""
        return self._entries.get(key)

    def lookup(self, key):
        """Entry dict {pages, tok0, n_prompt, Pb} or None. A hit moves
        the entry to MRU; the CALLER increfs the pages it maps."""
        e = self._entries.get(key)
        if e is None:
            self.misses += 1
            return None
        self._entries.move_to_end(key)
        self.hits += 1
        if not isinstance(e["tok0"], int):
            # inserted as the producing join's TRACED scalar (the
            # submit path never blocks on it); the first hit — always
            # long after that dispatch retired — canonicalizes it
            e["tok0"] = int(e["tok0"])
        return e

    def insert(self, key, pages, tok0, n_prompt, Pb):
        """Adopt `pages` (already refcounted by their owner): the cache
        takes its own reference so they survive the owner's eviction.
        `tok0` may be a still-traced device scalar — stored raw and
        resolved lazily at the first hit, keeping the producing join's
        submit path sync-free."""
        if key in self._entries:
            # a re-inserted prefix is HOT: refresh its LRU position so
            # it isn't evicted ahead of genuinely colder entries
            self._entries.move_to_end(key)
            return
        self.allocator.incref(pages)
        self._entries[key] = {"pages": list(pages), "tok0": tok0,
                              "n_prompt": int(n_prompt), "Pb": int(Pb)}
        while len(self._entries) > self.capacity:
            self._drop_lru()

    def _drop_lru(self):
        _, e = self._entries.popitem(last=False)
        self.allocator.decref(e["pages"])

    def reclaim(self, n_needed):
        """Drop LRU entries until the allocator has `n_needed` free
        pages or the cache is empty. Returns True on success. (Entries
        whose pages are still mapped by live slots free nothing yet —
        the refcount keeps them alive — so keep dropping.)"""
        while self.allocator.pages_free < n_needed and self._entries:
            self._drop_lru()
        return self.allocator.pages_free >= n_needed

    def flush(self):
        while self._entries:
            self._drop_lru()

    @property
    def hit_rate(self):
        n = self.hits + self.misses
        return (self.hits / n) if n else 0.0


class _RadixNode:
    """One FULL page of prompt tokens in the radix trie. The edge from
    the parent is the page's `page_size`-token run; `page` is the
    physical page holding its K/V (the trie owns one reference).
    `terminals` hang completed prompts off the node: the sub-page tail
    tokens + prompt length key the pages past the last full page (the
    partial last page and any hole pages up to the prompt bucket)."""

    __slots__ = ("tokens", "page", "parent", "children", "terminals",
                 "tick")

    def __init__(self, tokens, page, parent):
        self.tokens = tokens          # page_size-tuple (None at roots)
        self.page = page              # physical page id (None at roots)
        self.parent = parent
        self.children = {}            # {page-token-tuple: _RadixNode}
        self.terminals = {}           # {(tail-tuple, P0): entry dict}
        self.tick = 0


class RadixPrefixCache:
    """Host-side token trie over prefilled prompt pages: edges are
    page-granular token runs, so two prompts sharing a long preamble
    share the preamble's PHYSICAL pages even when their tails differ.

    `lookup` returns the longest-prefix match:

      * a WHOLE hit (full pages + a terminal whose tail tokens and
        prompt length match exactly) maps every cached page with zero
        prefill FLOPs — same contract as the flat `PrefixCache`;
      * a PARTIAL hit returns the matched full pages plus, when the
        divergence falls mid-page, a copy-on-write source page and the
        in-page match length `j` — the engine copies that page and
        prefills ONLY the divergent tail (the `pattach` program
        family), seeded by the matched K/V.

    Tenancy: trees are scoped by (memory digest, tenant key). Requests
    with no adapter share one base subtree ACROSS logical tenants —
    LoRA perturbs K/V from token 0, so only base-model traffic is
    safely shareable — while adapter traffic is keyed by
    (adapter name, generation); a generation bump (adapter
    re-register) orphans the stale subtree lazily on next touch, or
    eagerly via `drop_tenant`.

    Eviction is leaf-first LRU over terminals and BARE leaf nodes (no
    children, no terminals): interior nodes keep serving partial
    matches until everything under them has aged out, and every drop
    releases exactly the references the trie took, so
    `PageAllocator.check()` stays clean under chaos."""

    def __init__(self, allocator, capacity=64, page_size=None,
                 mid_page="round_down"):
        if mid_page not in ("round_down", "cow"):
            raise ValueError(f"mid_page={mid_page!r}: expected "
                             f"'round_down' or 'cow'")
        self.allocator = allocator
        self.capacity = int(capacity)
        self.page_size = int(page_size if page_size is not None
                             else allocator.page_size)
        # mid-page match policy: a match ending INSIDE a page can be
        # served by COW-copying the partially-matching page ("cow") or
        # by rounding the match DOWN to the page boundary and
        # re-prefilling the whole partial page with the divergent tail
        # ("round_down"). The copy costs a page write + an extra
        # dispatch and saves < page_size prefill tokens — on CPU it
        # measurably LOSES (~0.7x TTFT at depth 40/psz 16), so
        # round_down is the default; `rounded_down` counts the
        # decisions so the policy stays measurable.
        self.mid_page = mid_page
        self._roots = {}              # {(mem digest, tenant): _RadixNode}
        self._tenant_gen = {}         # {adapter name: last-seen gen}
        self._tick = 0
        self._n_nodes = 0
        self._n_terminals = 0
        self._n_pages = 0             # pages referenced by the trie
        self.whole_hits = 0
        self.partial_hits = 0
        self.misses = 0
        self.rounded_down = 0         # mid-page matches truncated

    # -- keys ------------------------------------------------------------

    @staticmethod
    def mem_digest(memory):
        """Cross-attention memory digest: decoder self-attn K/V depend
        on the memory through the cross-attn residual stream, so pages
        are only shareable within one memory scope."""
        if memory is None:
            return ""
        mem = np.ascontiguousarray(memory)
        digest = hashlib.sha1()
        digest.update(str(mem.dtype).encode())
        digest.update(str(mem.shape).encode())
        digest.update(mem)
        return digest.hexdigest()

    def _touch(self):
        self._tick += 1
        return self._tick

    def _root_for(self, memory, tenant, create):
        """Scope root, handling tenant-generation invalidation: a
        stale-generation subtree is dropped before the fresh one is
        touched (tenant = None for base traffic, (name, gen) for
        adapter traffic)."""
        if tenant is not None:
            name, gen = tenant
            old = self._tenant_gen.get(name)
            if old is not None and old != gen:
                self.drop_tenant(name)
            self._tenant_gen[name] = gen
        key = (self.mem_digest(memory), tenant)
        root = self._roots.get(key)
        if root is None and create:
            root = _RadixNode(None, None, None)
            self._roots[key] = root
        return root

    # -- lookup ----------------------------------------------------------

    def _walk(self, root, tokens, P0):
        """Longest run of full-page children matching `tokens[:P0]`.
        Returns (node, path) where path is the list of matched nodes
        (so pages AND parents are recoverable)."""
        psz = self.page_size
        node, path = root, []
        n_full = int(P0) // psz
        for i in range(n_full):
            child = node.children.get(tuple(tokens[i * psz:(i + 1) * psz]))
            if child is None:
                break
            node = child
            path.append(child)
        return node, path

    def _best_partial(self, node, tokens, P0, m):
        """Best mid-page extension below `node` (which matched `m` full
        pages): the longest common prefix between the remaining tokens
        and any child edge or terminal tail hanging here, capped so at
        least one divergent tail token remains for the partial attach.
        Returns (j, cow_src_page)."""
        psz = self.page_size
        rem = tuple(tokens[m * psz:P0])
        limit = min(psz - 1, len(rem) - 1)
        best_j, best_src = 0, None
        if limit <= 0:
            return best_j, best_src

        def common(a, b):
            n = 0
            for x, y in zip(a, b):
                if x != y:
                    break
                n += 1
            return n

        for et, child in node.children.items():
            j = min(common(rem, et), limit)
            if j > best_j:
                best_j, best_src = j, child.page
        for (tail, _p0), ent in node.terminals.items():
            if ent["pages"]:
                j = min(common(rem, tail), limit)
                if j > best_j:
                    best_j, best_src = j, ent["pages"][0]
        return best_j, best_src

    def _match(self, memory, tenant, tokens, P0, Pb, allow_partial,
               mutate):
        psz = self.page_size
        tokens = tuple(int(t) for t in tokens)[:int(P0)]
        if mutate:
            root = self._root_for(memory, tenant, create=False)
        else:
            # peek: read-only, even for generation bookkeeping
            if tenant is not None:
                name, gen = tenant
                old = self._tenant_gen.get(name)
                if old is not None and old != gen:
                    return None
            root = self._roots.get((self.mem_digest(memory), tenant))
        if root is None:
            return None
        node, path = self._walk(root, tokens, P0)
        m = len(path)
        n_full = P0 // psz
        if m == n_full:
            ent = node.terminals.get((tokens[n_full * psz:P0], P0))
            if ent is not None and ent["Pb"] == int(Pb):
                if mutate:
                    t = self._touch()
                    for n in path:
                        n.tick = t
                    ent["tick"] = t
                    if not isinstance(ent["tok0"], int):
                        # stored as the producing join's traced scalar
                        # (deferred sync); the first hit canonicalizes
                        ent["tok0"] = int(ent["tok0"])
                return ("whole", {
                    "pages": [n.page for n in path] + list(ent["pages"]),
                    "tok0": ent["tok0"], "n_prompt": ent["n_prompt"],
                    "Pb": ent["Pb"]})
        if not allow_partial:
            return None
        if m and m * psz == P0:
            # every real token sits in matched full pages but no
            # terminal completes the prompt: back off one page so the
            # attach has a tail to prefill (the dropped page re-emerges
            # as the COW source with j = page_size - 1)
            node = path.pop().parent
            m -= 1
        j, cow_src = self._best_partial(node, tokens, P0, m)
        if j and self.mid_page == "round_down":
            # mid-page policy: drop the sub-page extension and attach
            # from the page boundary — the pattach tail re-prefills
            # the j matched tokens along with the divergent remainder,
            # which beats paying a COW page copy + extra dispatch for
            # them (see __init__; "cow" preserves the old behavior)
            if mutate:
                self.rounded_down += 1
            j, cow_src = 0, None
        if m == 0 and j == 0:
            return None
        if mutate:
            t = self._touch()
            for n in path:
                n.tick = t
        return ("partial", {
            "pages": [n.page for n in path], "j": int(j),
            "cow_src": cow_src, "seed_len": m * psz + int(j)})

    def lookup(self, tokens, P0, Pb, memory=None, tenant=None,
               allow_partial=True):
        """Longest-prefix match for `tokens[:P0]` in the (memory,
        tenant) scope. Returns None, ("whole", entry) or ("partial",
        {pages, j, cow_src, seed_len}). The CALLER increfs any pages
        it maps; matched nodes move to MRU."""
        res = self._match(memory, tenant, tokens, P0, Pb, allow_partial,
                          mutate=True)
        if res is None:
            self.misses += 1
        elif res[0] == "whole":
            self.whole_hits += 1
        else:
            self.partial_hits += 1
        return res

    def peek(self, tokens, P0, Pb, memory=None, tenant=None,
             allow_partial=True):
        """Like lookup, but side-effect free (no accounting, no MRU
        move, no generation invalidation) — the admission gate's
        headroom estimate uses it."""
        return self._match(memory, tenant, tokens, P0, Pb,
                           allow_partial, mutate=False)

    # -- insert ----------------------------------------------------------

    def insert(self, tokens, P0, Pb, memory, tenant, pages, tok0):
        """Extend the trie with a completed prompt's pages (already
        refcounted by their slot; the trie takes its own references).
        Full pages become (or refresh) trie nodes one by one — a
        partial-hit join re-walks its matched prefix and only adopts
        the pages it actually created — and the sub-page tail plus any
        hole pages up to the prompt bucket land in a terminal."""
        psz = self.page_size
        tokens = tuple(int(t) for t in tokens)[:int(P0)]
        P0, Pb = int(P0), int(Pb)
        root = self._root_for(memory, tenant, create=True)
        n_full = P0 // psz
        t = self._touch()
        node = root
        for i in range(n_full):
            et = tokens[i * psz:(i + 1) * psz]
            child = node.children.get(et)
            if child is None:
                page = int(pages[i])
                self.allocator.incref([page])
                child = _RadixNode(et, page, node)
                node.children[et] = child
                self._n_nodes += 1
                self._n_pages += 1
            child.tick = t
            node = child
        tkey = (tokens[n_full * psz:P0], P0)
        ent = node.terminals.get(tkey)
        if ent is not None:
            ent["tick"] = t               # hot terminal: refresh LRU
            return
        tail = [int(p) for p in pages[n_full:]]
        self.allocator.incref(tail)
        # tok0 may still be the producing join's traced scalar: store
        # it raw (the submit path never blocks on it) — the first
        # whole hit canonicalizes it to a host int
        node.terminals[tkey] = {"pages": tail, "tok0": tok0,
                                "n_prompt": P0, "Pb": Pb, "tick": t}
        self._n_terminals += 1
        self._n_pages += len(tail)
        while self._n_terminals > self.capacity:
            if not self._evict_one():
                break

    def insert_prefix(self, tokens, memory, tenant, pages):
        """Extend the trie with FULL PAGES only — no terminal: the
        chunked-prefill path calls this after every completed chunk,
        so the pages a long prompt has prefilled SO FAR are already
        partial-matchable (and survive the slot's failure) before the
        final chunk lands the terminal via `insert`. `tokens` must be
        a page-multiple prefix; extra tokens past `len(pages) *
        page_size` are ignored. Existing nodes are refreshed, new ones
        take their own page reference — identical adoption semantics
        to `insert`'s full-page walk."""
        psz = self.page_size
        n_full = min(len(tokens) // psz, len(pages))
        if n_full == 0:
            return
        tokens = tuple(int(t) for t in tokens)[:n_full * psz]
        root = self._root_for(memory, tenant, create=True)
        t = self._touch()
        node = root
        for i in range(n_full):
            et = tokens[i * psz:(i + 1) * psz]
            child = node.children.get(et)
            if child is None:
                page = int(pages[i])
                self.allocator.incref([page])
                child = _RadixNode(et, page, node)
                node.children[et] = child
                self._n_nodes += 1
                self._n_pages += 1
            child.tick = t
            node = child

    # -- eviction --------------------------------------------------------

    def _iter_nodes(self):
        stack = [(key, root) for key, root in self._roots.items()]
        while stack:
            key, node = stack.pop()
            yield key, node
            for child in node.children.values():
                stack.append((key, child))

    def _evict_one(self):
        """Drop the least-recently-used evictable item: a terminal, or
        a BARE leaf node (no children, no terminals). Interior nodes
        are never dropped while anything hangs below them — they still
        serve partial matches — but become bare (and evictable) as
        their subtrees age out. Returns False when nothing is left."""
        best = None                   # (tick, kind, ...)
        for key, node in self._iter_nodes():
            for tkey, ent in node.terminals.items():
                if best is None or ent["tick"] < best[0]:
                    best = (ent["tick"], "terminal", node, tkey)
            if (node.parent is not None and not node.children
                    and not node.terminals):
                if best is None or node.tick < best[0]:
                    best = (node.tick, "node", node, key)
        if best is None:
            return False
        if best[1] == "terminal":
            _, _, node, tkey = best
            ent = node.terminals.pop(tkey)
            self.allocator.decref(ent["pages"])
            self._n_terminals -= 1
            self._n_pages -= len(ent["pages"])
        else:
            _, _, node, key = best
            del node.parent.children[node.tokens]
            self.allocator.decref([node.page])
            self._n_nodes -= 1
            self._n_pages -= 1
            parent = node.parent
            if (parent.parent is None and not parent.children
                    and not parent.terminals):
                self._roots.pop(key, None)
        return True

    def reclaim(self, n_needed):
        """Evict leaf-first LRU until the allocator has `n_needed`
        free pages or the trie is exhausted. Returns True on success.
        (Items whose pages are still mapped by live slots free nothing
        yet — the refcount keeps them alive — so keep evicting.)"""
        while self.allocator.pages_free < n_needed:
            if not self._evict_one():
                break
        return self.allocator.pages_free >= n_needed

    def _drop_subtree(self, node):
        for child in list(node.children.values()):
            self._drop_subtree(child)
        for ent in node.terminals.values():
            self.allocator.decref(ent["pages"])
            self._n_terminals -= 1
            self._n_pages -= len(ent["pages"])
        node.terminals.clear()
        node.children.clear()
        if node.page is not None:
            self.allocator.decref([node.page])
            self._n_nodes -= 1
            self._n_pages -= 1

    def drop_tenant(self, name):
        """Release every subtree keyed to adapter `name` (any
        generation) — the eager path of generation invalidation."""
        for key in [k for k in self._roots
                    if k[1] is not None and k[1][0] == name]:
            self._drop_subtree(self._roots.pop(key))
        self._tenant_gen.pop(name, None)

    def flush(self):
        for key in list(self._roots):
            self._drop_subtree(self._roots.pop(key))

    # -- introspection ---------------------------------------------------

    def stats(self):
        """Gauges for the metrics snapshot: trie size in nodes (full
        prompt pages on edges), terminals, and total pages referenced
        (node pages + terminal tails)."""
        return {"nodes": self._n_nodes, "terminals": self._n_terminals,
                "pages": self._n_pages, "scopes": len(self._roots),
                "rounded_down": self.rounded_down}

    def __len__(self):
        return self._n_terminals

    # flat-cache-compatible accounting, so dashboards keyed on the old
    # PrefixCache surface keep working
    @property
    def hits(self):
        return self.whole_hits + self.partial_hits

    @property
    def hit_rate(self):
        n = self.hits + self.misses
        return (self.hits / n) if n else 0.0


# --------------------------------------------------------------------------
# device side: pure jnp page math (safe under jit; shapes static)
# --------------------------------------------------------------------------

def quantize_chunks(chunks, storage_dtype, quantized):
    """[N, H, page_size, D] compute-dtype chunks -> (stored, scale).
    int8: symmetric per-(page, head) amax/127 scale (1.0 for all-zero
    pages so dequant never divides by zero); other dtypes: plain cast,
    scale None."""
    import jax.numpy as jnp

    if not quantized:
        return chunks.astype(storage_dtype), None
    amax = jnp.max(jnp.abs(chunks.astype(jnp.float32)), axis=(2, 3),
                   keepdims=True)                     # [N, H, 1, 1]
    scale = jnp.where(amax > 0, amax / _QMAX, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(chunks.astype(jnp.float32) / scale),
                 -_QMAX, _QMAX).astype(jnp.int8)
    return q, scale


def chunk_prompt(kv, page_size):
    """A prefilled [1, H, P, D] K or V block -> [n_pages, H, page_size,
    D] page chunks (tail zero-padded to the page boundary)."""
    import jax.numpy as jnp

    _, H, P, D = kv.shape
    n_pp = pages_for(P, page_size)
    pad = n_pp * page_size - P
    x = kv[0]
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros((H, pad, D), x.dtype)], axis=1)
    return jnp.transpose(
        x.reshape(H, n_pp, page_size, D), (1, 0, 2, 3))


def write_prompt_pages(pages, scales, page_ids, kv, quantized):
    """Scatter a prefilled [1, H, P, D] block into `pages` at the
    (traced int32 [n_pages]) `page_ids`. Returns (pages, scales)."""
    page_size = pages.shape[2]
    chunks = chunk_prompt(kv, page_size)
    stored, sc = quantize_chunks(chunks, pages.dtype, quantized)
    pages = pages.at[page_ids].set(stored)
    if quantized:
        scales = scales.at[page_ids].set(sc)
    return pages, scales


def write_token(pages, scales, table, index, tok):
    """The decode write: slot s's token K or V ([S, H, D]) lands at
    logical position index[s] — physical page table[s, index[s] //
    page_size], offset index[s] % page_size. Slots whose table entry
    points at the trash row write garbage there harmlessly (the engine
    maps every ACTIVE slot's write page before the step). int8 pages
    whose scale the new token outranges are rescaled in place (the
    per-page scale only ever grows)."""
    import jax.numpy as jnp

    page_size = pages.shape[2]
    S = tok.shape[0]
    pid = jnp.take_along_axis(
        table, (index // page_size)[:, None], axis=1)[:, 0]   # [S]
    off = index % page_size
    if scales is None:
        return pages.at[pid, :, off, :].set(tok.astype(pages.dtype)), \
            None
    # gather the S target pages, grow their scales to cover the new
    # token, rescale the existing int8 payload, write, scatter back
    pg = pages[pid].astype(jnp.float32)              # [S, H, psz, D]
    s_old = scales[pid]                              # [S, H, 1, 1]
    t32 = tok.astype(jnp.float32)
    amax = jnp.max(jnp.abs(t32), axis=-1,
                   keepdims=True)[..., None]         # [S, H, 1, 1]
    s_new = jnp.maximum(s_old, amax / _QMAX)
    s_new = jnp.where(s_new > 0, s_new, 1.0)
    factor = s_old / s_new                           # <= 1; exact 1.0
    #                                                  when no growth
    pg = jnp.clip(jnp.round(pg * factor), -_QMAX, _QMAX)
    qt = jnp.clip(jnp.round(t32 / s_new[..., 0]), -_QMAX, _QMAX)
    pg = pg.at[jnp.arange(S), :, off, :].set(qt)
    return (pages.at[pid].set(pg.astype(jnp.int8)),
            scales.at[pid].set(s_new))


def write_tokens(pages, scales, table, index, toks):
    """The k-wide decode write (speculative verify): slot s's T tokens
    ([S, H, T, D]) land at logical positions index[s] .. index[s] +
    T - 1, crossing page boundaries wherever they fall — position j
    resolves its OWN physical page through the table, so a block that
    straddles two (or more) pages scatters into each. Rides
    `write_token`'s math position by position (T is a static trace
    constant), so int8 pages inherit the grow-only scale rescale
    exactly: a later token that outranges the page re-rescales the
    payload the earlier tokens just wrote. Rejected speculative tokens
    need no undo — the caller rolls the per-slot index back and the
    masked positions are rewritten by the next round's fixed-T write
    before any query can see them."""
    import jax.numpy as jnp

    T = toks.shape[2]
    index = jnp.asarray(index, jnp.int32)
    for j in range(T):
        pages, scales = write_token(pages, scales, table,
                                    index + jnp.int32(j),
                                    toks[:, :, j, :])
    return pages, scales


def copy_page(pages, scales, src, dst):
    """Copy-on-write: duplicate physical page `src` into `dst` (traced
    int32 scalars) so a joiner can decode-write without touching the
    shared original."""
    pages = pages.at[dst].set(pages[src])
    if scales is not None:
        scales = scales.at[dst].set(scales[src])
    return pages, scales


def gather_pages(pages, scales, table, compute_dtype):
    """Dense [S, H, max_pages * page_size, D] logical view of each
    slot's cache, dequantized — the XLA fallback read path (the pallas
    kernel reads pages in place through the scalar-prefetched table
    instead). Unmapped (trash-clipped) table entries gather garbage
    that the written-length mask hides."""
    from ..ops.attention import paged_gather_kv

    return paged_gather_kv(pages, scales, table, compute_dtype)
