"""Paged KV-cache subsystem for the serving pool.

The dense serving pool (engine.py) preallocates one contiguous
[S, H, max_len, D] K/V region per layer, so every slot pays for the
worst-case `max_len` whether its request uses 9 tokens or 900, and
identical system prompts are re-prefilled for every request. This
module replaces the per-slot rows with a **global pool of fixed-size
pages** plus an int32 indirection:

  * pages live in static-shape arrays `[n_pages + 1, H, page_size, D]`
    per layer (row `n_pages` is the TRASH page — inactive slots' masked
    decode writes land there, never on live data);
  * each slot owns an int32 `page_table[S, max_pages]` row mapping its
    logical block i to a physical page (host-side `-1` = unmapped,
    clipped to the trash row before it reaches the device);
  * `PageAllocator` hands pages out of a free list with refcounts, so
    several slots can map the SAME physical page read-only (shared
    prompt prefixes) and a page returns to the free list exactly when
    its last reference drops;
  * `PrefixCache` keys fully-prefilled prompt pages on the prompt's
    token hash (+ the cross-attention memory digest — the decoder's
    self-attention K/V depend on it through the cross-attn residual
    stream), so a request repeating a known prompt maps the cached
    pages with ZERO prefill FLOPs; the page a joiner will decode-write
    into is copied first (copy-on-write), so cached pages are
    immutable;
  * pages store K/V in fp32 / bf16 / int8 behind the engine's
    `kv_dtype=` knob; int8 pages carry a per-(page, head) f32 scale
    (symmetric, amax/127) that grows monotonically — a decode write
    whose token outranges the page rescales the existing int8 payload
    in place — and is applied at read time (in-kernel on TPU, in the
    gather fallback elsewhere).

Everything here is either pure host bookkeeping (allocator, prefix
cache, page tables as numpy) or pure jnp array math safe inside jit
(quantize / scatter / gather / copy). Shapes stay static for any pool
config: the page table is a traced int32 input, so joining, evicting,
and decode never retrace — the same trick the split-K decode kernel
uses for its traced written-token counts.
"""
from __future__ import annotations

import collections
import hashlib

import numpy as np

__all__ = ["OutOfPages", "PageAllocator", "PrefixCache", "PagedKVCache",
           "pages_for", "resolve_kv_dtype", "quantize_chunks",
           "chunk_prompt", "write_prompt_pages", "write_token",
           "write_tokens", "copy_page", "gather_pages"]

_QMAX = 127.0


class OutOfPages(RuntimeError):
    """The page pool cannot serve an allocation: backpressure (the
    scheduler keeps the request queued until pages free up) or, when it
    strikes mid-decode under oversubscription, a victim eviction."""


#: the decode-engine paged cache: per-layer page arrays + the shared
#: per-slot indirection. Leaves are raw jax arrays (valid jit inputs /
#: scan carries); `k_scale`/`v_scale` are None unless the pages are
#: int8. `table` is the [S, max_pages] int32 page table (trash-clipped)
#: and `index` the per-slot written-token count — both shipped fresh
#: from the host each step, so page mapping changes never retrace.
PagedKVCache = collections.namedtuple(
    "PagedKVCache", ["k", "v", "k_scale", "v_scale", "table", "index"])


def pages_for(n_tokens, page_size):
    """Pages needed to hold `n_tokens` cache positions."""
    return -(-int(n_tokens) // int(page_size))


def resolve_kv_dtype(kv_dtype, compute_dtype):
    """The engine's `kv_dtype=` knob -> (storage jnp dtype, quantized?).
    None keeps the compute dtype (bit-exact paging); "bf16" stores
    bfloat16; "int8" stores symmetric int8 with per-(page, head)
    scales."""
    import jax.numpy as jnp

    if kv_dtype is None:
        return jnp.dtype(compute_dtype), False
    name = str(kv_dtype).lower()
    if name in ("int8", "i1"):
        return jnp.dtype(jnp.int8), True
    if name in ("bf16", "bfloat16"):
        return jnp.dtype(jnp.bfloat16), False
    if name in ("f4", "f32", "float32"):
        return jnp.dtype(jnp.float32), False
    return jnp.dtype(kv_dtype), False


# --------------------------------------------------------------------------
# host side: allocator + prefix cache
# --------------------------------------------------------------------------

class PageAllocator:
    """Free-list + refcount bookkeeping over `n_pages` physical pages.
    Host-side only — it never touches device arrays; the engine turns
    its decisions into page-table entries. `alloc` raises `OutOfPages`
    without partial effects; refcounts let shared prompt pages outlive
    any single slot."""

    def __init__(self, n_pages, page_size):
        if n_pages < 1 or page_size < 1:
            raise ValueError("n_pages and page_size must be >= 1")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        # pop() takes from the end: keep ids ascending for readability
        self._free = list(range(self.n_pages - 1, -1, -1))
        self.refcount = np.zeros(self.n_pages, np.int32)

    @property
    def pages_free(self):
        return len(self._free)

    @property
    def pages_in_use(self):
        return self.n_pages - len(self._free)

    def alloc(self, n):
        """Allocate `n` pages (refcount 1 each) or raise OutOfPages
        with NO pages taken."""
        n = int(n)
        if n > len(self._free):
            raise OutOfPages(
                f"need {n} pages, {len(self._free)} free of "
                f"{self.n_pages}")
        pages = [self._free.pop() for _ in range(n)]
        self.refcount[pages] = 1
        return pages

    def incref(self, pages):
        for p in pages:
            if self.refcount[p] <= 0:
                raise RuntimeError(f"incref on free page {p}")
            self.refcount[p] += 1

    def decref(self, pages):
        """Drop one reference per page; pages reaching zero return to
        the free list (double-free raises — the invariant tests lean on
        this)."""
        for p in pages:
            p = int(p)
            if self.refcount[p] <= 0:
                raise RuntimeError(f"decref on free page {p}")
            self.refcount[p] -= 1
            if self.refcount[p] == 0:
                self._free.append(p)

    def check(self):
        """Invariants: free + referenced partitions the pool exactly;
        raises on any violation (used by the soak test and the chaos
        leak check)."""
        free = set(self._free)
        if len(free) != len(self._free):
            raise AssertionError("duplicate pages on the free list")
        held = {p for p in range(self.n_pages) if self.refcount[p] > 0}
        if free & held:
            raise AssertionError(f"pages both free and held: "
                                 f"{sorted(free & held)}")
        if free | held != set(range(self.n_pages)):
            raise AssertionError("leaked pages: neither free nor held: "
                                 f"{sorted(set(range(self.n_pages)) - free - held)}")
        if (self.refcount < 0).any():
            raise AssertionError("negative refcount")
        return True


class PrefixCache:
    """Host-side map from (prompt tokens, memory digest) to the
    immutable pages a previous join prefilled for that prompt, plus the
    prefill's first greedy token. Whole-prompt granularity: a hit means
    the ENTIRE padded prompt block [0, Pb) is served by shared pages
    and the join runs zero prefill FLOPs. LRU-bounded: inserting past
    `capacity` (or an explicit `reclaim`) drops the oldest entries,
    releasing the cache's page references."""

    def __init__(self, allocator, capacity=64):
        self.allocator = allocator
        self.capacity = int(capacity)
        self._entries = collections.OrderedDict()
        self.hits = 0
        self.misses = 0

    @staticmethod
    def key_of(prompt, memory):
        prompt = np.asarray(prompt)
        mem = b"" if memory is None else np.ascontiguousarray(memory)
        digest = hashlib.sha1()
        digest.update(np.ascontiguousarray(prompt.astype(np.int64)))
        if memory is not None:
            digest.update(str(mem.dtype).encode())
            digest.update(str(mem.shape).encode())
            digest.update(mem)
        # the digest alone would admit hash collisions across prompts;
        # carrying the token tuple keeps lookups exact
        return (tuple(int(t) for t in prompt.ravel()),
                digest.hexdigest())

    def __len__(self):
        return len(self._entries)

    def peek(self, key):
        """Like lookup, but no hit/miss accounting and no MRU move —
        the admission gate's headroom estimate uses it."""
        return self._entries.get(key)

    def lookup(self, key):
        """Entry dict {pages, tok0, n_prompt, Pb} or None. A hit moves
        the entry to MRU; the CALLER increfs the pages it maps."""
        e = self._entries.get(key)
        if e is None:
            self.misses += 1
            return None
        self._entries.move_to_end(key)
        self.hits += 1
        return e

    def insert(self, key, pages, tok0, n_prompt, Pb):
        """Adopt `pages` (already refcounted by their owner): the cache
        takes its own reference so they survive the owner's eviction."""
        if key in self._entries:
            return
        self.allocator.incref(pages)
        self._entries[key] = {"pages": list(pages), "tok0": int(tok0),
                              "n_prompt": int(n_prompt), "Pb": int(Pb)}
        while len(self._entries) > self.capacity:
            self._drop_lru()

    def _drop_lru(self):
        _, e = self._entries.popitem(last=False)
        self.allocator.decref(e["pages"])

    def reclaim(self, n_needed):
        """Drop LRU entries until the allocator has `n_needed` free
        pages or the cache is empty. Returns True on success. (Entries
        whose pages are still mapped by live slots free nothing yet —
        the refcount keeps them alive — so keep dropping.)"""
        while self.allocator.pages_free < n_needed and self._entries:
            self._drop_lru()
        return self.allocator.pages_free >= n_needed

    def flush(self):
        while self._entries:
            self._drop_lru()

    @property
    def hit_rate(self):
        n = self.hits + self.misses
        return (self.hits / n) if n else 0.0


# --------------------------------------------------------------------------
# device side: pure jnp page math (safe under jit; shapes static)
# --------------------------------------------------------------------------

def quantize_chunks(chunks, storage_dtype, quantized):
    """[N, H, page_size, D] compute-dtype chunks -> (stored, scale).
    int8: symmetric per-(page, head) amax/127 scale (1.0 for all-zero
    pages so dequant never divides by zero); other dtypes: plain cast,
    scale None."""
    import jax.numpy as jnp

    if not quantized:
        return chunks.astype(storage_dtype), None
    amax = jnp.max(jnp.abs(chunks.astype(jnp.float32)), axis=(2, 3),
                   keepdims=True)                     # [N, H, 1, 1]
    scale = jnp.where(amax > 0, amax / _QMAX, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(chunks.astype(jnp.float32) / scale),
                 -_QMAX, _QMAX).astype(jnp.int8)
    return q, scale


def chunk_prompt(kv, page_size):
    """A prefilled [1, H, P, D] K or V block -> [n_pages, H, page_size,
    D] page chunks (tail zero-padded to the page boundary)."""
    import jax.numpy as jnp

    _, H, P, D = kv.shape
    n_pp = pages_for(P, page_size)
    pad = n_pp * page_size - P
    x = kv[0]
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros((H, pad, D), x.dtype)], axis=1)
    return jnp.transpose(
        x.reshape(H, n_pp, page_size, D), (1, 0, 2, 3))


def write_prompt_pages(pages, scales, page_ids, kv, quantized):
    """Scatter a prefilled [1, H, P, D] block into `pages` at the
    (traced int32 [n_pages]) `page_ids`. Returns (pages, scales)."""
    page_size = pages.shape[2]
    chunks = chunk_prompt(kv, page_size)
    stored, sc = quantize_chunks(chunks, pages.dtype, quantized)
    pages = pages.at[page_ids].set(stored)
    if quantized:
        scales = scales.at[page_ids].set(sc)
    return pages, scales


def write_token(pages, scales, table, index, tok):
    """The decode write: slot s's token K or V ([S, H, D]) lands at
    logical position index[s] — physical page table[s, index[s] //
    page_size], offset index[s] % page_size. Slots whose table entry
    points at the trash row write garbage there harmlessly (the engine
    maps every ACTIVE slot's write page before the step). int8 pages
    whose scale the new token outranges are rescaled in place (the
    per-page scale only ever grows)."""
    import jax.numpy as jnp

    page_size = pages.shape[2]
    S = tok.shape[0]
    pid = jnp.take_along_axis(
        table, (index // page_size)[:, None], axis=1)[:, 0]   # [S]
    off = index % page_size
    if scales is None:
        return pages.at[pid, :, off, :].set(tok.astype(pages.dtype)), \
            None
    # gather the S target pages, grow their scales to cover the new
    # token, rescale the existing int8 payload, write, scatter back
    pg = pages[pid].astype(jnp.float32)              # [S, H, psz, D]
    s_old = scales[pid]                              # [S, H, 1, 1]
    t32 = tok.astype(jnp.float32)
    amax = jnp.max(jnp.abs(t32), axis=-1,
                   keepdims=True)[..., None]         # [S, H, 1, 1]
    s_new = jnp.maximum(s_old, amax / _QMAX)
    s_new = jnp.where(s_new > 0, s_new, 1.0)
    factor = s_old / s_new                           # <= 1; exact 1.0
    #                                                  when no growth
    pg = jnp.clip(jnp.round(pg * factor), -_QMAX, _QMAX)
    qt = jnp.clip(jnp.round(t32 / s_new[..., 0]), -_QMAX, _QMAX)
    pg = pg.at[jnp.arange(S), :, off, :].set(qt)
    return (pages.at[pid].set(pg.astype(jnp.int8)),
            scales.at[pid].set(s_new))


def write_tokens(pages, scales, table, index, toks):
    """The k-wide decode write (speculative verify): slot s's T tokens
    ([S, H, T, D]) land at logical positions index[s] .. index[s] +
    T - 1, crossing page boundaries wherever they fall — position j
    resolves its OWN physical page through the table, so a block that
    straddles two (or more) pages scatters into each. Rides
    `write_token`'s math position by position (T is a static trace
    constant), so int8 pages inherit the grow-only scale rescale
    exactly: a later token that outranges the page re-rescales the
    payload the earlier tokens just wrote. Rejected speculative tokens
    need no undo — the caller rolls the per-slot index back and the
    masked positions are rewritten by the next round's fixed-T write
    before any query can see them."""
    import jax.numpy as jnp

    T = toks.shape[2]
    index = jnp.asarray(index, jnp.int32)
    for j in range(T):
        pages, scales = write_token(pages, scales, table,
                                    index + jnp.int32(j),
                                    toks[:, :, j, :])
    return pages, scales


def copy_page(pages, scales, src, dst):
    """Copy-on-write: duplicate physical page `src` into `dst` (traced
    int32 scalars) so a joiner can decode-write without touching the
    shared original."""
    pages = pages.at[dst].set(pages[src])
    if scales is not None:
        scales = scales.at[dst].set(scales[src])
    return pages, scales


def gather_pages(pages, scales, table, compute_dtype):
    """Dense [S, H, max_pages * page_size, D] logical view of each
    slot's cache, dequantized — the XLA fallback read path (the pallas
    kernel reads pages in place through the scalar-prefetched table
    instead). Unmapped (trash-clipped) table entries gather garbage
    that the written-length mask hides."""
    from ..ops.attention import paged_gather_kv

    return paged_gather_kv(pages, scales, table, compute_dtype)
