"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's
capabilities (reference: lisong2019/Paddle), built on JAX/XLA/Pallas.

Public surface mirrors paddle 2.0 (python/paddle/__init__.py in the
reference): tensor functions at top level, `nn`, `optimizer`, `static`,
`vision`, `distributed`, `metric`, `hapi`-style `Model`, plus the 1.x
`fluid` namespace for static-graph programs.
"""

__version__ = "0.1.0"

# paddle semantics: int64 labels / float64 tensors are first-class
# (framework.proto VarType has INT64/FP64); jax needs x64 opted in.
import jax as _jax

_jax.config.update("jax_enable_x64", True)

# RNG implementation: paddle's generator contract (generator.h) promises a
# seeded, reproducible stream, not a particular bit sequence. On TPU the
# counter-based threefry lowering costs ~25% of a dropout-heavy train step;
# XLA's native RngBitGenerator ("rbg") is the TPU-idiomatic generator and
# measured 1.34x end-to-end on the ERNIE fine-tune bench. Overridable via
# PT_PRNG_IMPL (threefry2x32 | rbg | unsafe_rbg).
import os as _os

_jax.config.update("jax_default_prng_impl",
                   _os.environ.get("PT_PRNG_IMPL", "rbg"))

# dtypes
from .core.dtypes import (bfloat16, bool_, complex64, complex128,  # noqa
                          float16, float32, float64, get_default_dtype, int8,
                          int16, int32, int64, set_default_dtype, uint8)
# places
from .core.place import (CPUPlace, CUDAPinnedPlace, CUDAPlace, TPUPlace,  # noqa
                         XPUPlace, get_device, is_compiled_with_cuda,
                         is_compiled_with_tpu, is_compiled_with_xpu,
                         set_device)
# tensor + autograd
from .core.tensor import Tensor, to_tensor  # noqa: F401
from .core.autograd import enable_grad, is_grad_enabled, no_grad  # noqa
from .core.random import seed  # noqa: F401

# functional surface (paddle.add, paddle.matmul, ...)
from .tensor import *  # noqa: F401,F403
from .tensor import ops as _tensor_ops

# subpackages (imported lazily-ish; these are light)
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import metric  # noqa: F401
from . import io  # noqa: F401
from . import static  # noqa: F401
from . import vision  # noqa: F401
from . import text  # noqa: F401
from . import distributed  # noqa: F401
from . import amp  # noqa: F401
from . import autograd  # noqa: F401
from . import jit  # noqa: F401
from . import device  # noqa: F401
from . import utils  # noqa: F401
from . import distribution  # noqa: F401
from . import parallel  # noqa: F401
from . import regularizer  # noqa: F401
from . import profiler  # noqa: F401
from . import sparse  # noqa: F401
from . import incubate  # noqa: F401
from . import inference  # noqa: F401
from . import slim  # noqa: F401
from . import dataset  # noqa: F401

from .io.serialization import load, save  # noqa: F401
from .hapi.model import Model, summary  # noqa: F401
from .hapi import callbacks  # noqa: F401
from .nn.layer.layers import ParamAttr  # noqa: F401
from .utils.flags import get_flags, set_flags  # noqa: F401
from .framework import disable_static, enable_static, in_dynamic_mode  # noqa
from .tensor.ops import rand, randn, randint, randperm, uniform, normal  # noqa

# fluid 1.x namespace
from . import fluid  # noqa: F401


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False):
    """paddle.grad parity (imperative/partial_grad_engine.cc:29): grads of
    outputs w.r.t. arbitrary inputs (leaf or intermediate) in one reverse
    pass, leaving every tensor's `.grad` untouched. With
    create_graph=True the backward pass itself is recorded on the tape
    (each vjp re-expressed as jax.vjp over the node's primals), so the
    returned grads are differentiable — double grad, the GAN
    gradient-penalty pattern (imperative double-grad parity)."""
    from .core import autograd as _ag

    outs = list(outputs) if isinstance(outputs, (list, tuple)) else [outputs]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    gos = None
    if grad_outputs is not None:
        gos = list(grad_outputs) if isinstance(
            grad_outputs, (list, tuple)) else [grad_outputs]
        if len(gos) != len(outs):
            raise ValueError(
                f"the length of grad_outputs ({len(gos)}) must equal the "
                f"length of outputs ({len(outs)})")
    # paddle semantics: retain_graph defaults to create_graph
    retain = bool(retain_graph) if retain_graph is not None \
        else bool(create_graph)
    return _ag.partial_grad(outs, list(ins), gos, retain_graph=retain,
                            allow_unused=allow_unused,
                            create_graph=create_graph)
