"""paddle.static 2.0 namespace (reference: python/paddle/static/)."""
from __future__ import annotations

import numpy as np

from ..core.dtypes import convert_dtype
from ..fluid import CompiledProgram  # noqa: F401
from ..fluid.backward import append_backward, gradients  # noqa: F401
from ..fluid.executor import Executor, Scope, global_scope, scope_guard  # noqa
from ..fluid.framework import (Program, Variable,  # noqa: F401
                               default_main_program,
                               default_startup_program, device_guard,
                               name_scope, program_guard)
from ..fluid.io import (load_inference_model, save_inference_model,  # noqa
                        load_persistables, save_persistables)
from ..fluid.layers.tensor import data as _fluid_data
from . import nn  # noqa: F401


def data(name, shape, dtype="float32", lod_level=0):
    """paddle.static.data: no implicit batch dim."""
    return _fluid_data(name, shape, dtype, lod_level,
                       append_batch_size=False)


class InputSpec:
    """paddle.static.InputSpec parity."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = list(shape)
        self.dtype = convert_dtype(dtype)
        self.name = name

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, " \
               f"name={self.name})"


def save(program, model_path, protocol=4):
    import pickle

    from ..fluid.io import _collect_persistables

    vals = _collect_persistables(program, global_scope())
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(vals, f, protocol=protocol)
    with open(model_path + ".pdmodel", "wb") as f:
        f.write(program.desc_bytes())


def load(program, model_path, executor=None, var_list=None):
    import pickle

    from ..fluid.io import _restore

    with open(model_path + ".pdparams", "rb") as f:
        vals = pickle.load(f)
    _restore(vals, global_scope())
