"""paddle.static.nn: static layer sugar (reference python/paddle/static/nn)."""
from ...fluid.layers.nn import (batch_norm, conv2d, embedding, fc,  # noqa
                                layer_norm)
from ...fluid.layers.nn import pool2d  # noqa: F401
