"""paddle.incubate: experimental surface (reference: fluid/incubate/)."""
from . import checkpoint  # noqa: F401
