"""User-side PS feed-file authoring API (reference parity:
python/paddle/fluid/incubate/data_generator/__init__.py:1 —
DataGenerator / MultiSlotDataGenerator / MultiSlotStringDataGenerator).

The reference's generators print MultiSlot text lines to stdout so a
Hadoop/shell pipeline shards them into trainer feed files; the same
protocol works here (our native datafeed reads the identical
`<n> v1..vn` text wire — csrc/ptcore/datafeed.cc). TPU-native extra:
`write_to_file(..., binary=True)` emits the PTMB1 binary wire
(fluid/dataset.write_multislot_binary) — ~3x smaller and parse-free.
"""
from __future__ import annotations

import sys


class DataGenerator:
    """Subclass and implement `generate_sample(line)` returning a
    generator of samples, each `[(slot_name, [values...]), ...]`;
    optionally `generate_batch(samples)` for batch-level rewrites."""

    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32
        self._line_limit = None

    def _set_line_limit(self, line_limit):
        if not isinstance(line_limit, int) or line_limit < 1:
            raise ValueError("line_limit must be a positive int")
        self._line_limit = line_limit

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    # -- drive ----------------------------------------------------------
    def run_from_memory(self, out=None):
        """Generate from generate_sample(None) and write the wire lines
        to `out` (default stdout, the reference pipeline contract)."""
        out = out or sys.stdout
        batch_samples = []
        for sample in self._iter_source(None):
            batch_samples.append(sample)
            if len(batch_samples) == self.batch_size_:
                self._flush_batch(batch_samples, out)
                batch_samples = []
        if batch_samples:
            self._flush_batch(batch_samples, out)

    def run_from_stdin(self, inp=None, out=None):
        """One input line -> generate_sample(line) samples -> wire
        lines (the hadoop-streaming mapper contract)."""
        inp = inp or sys.stdin
        out = out or sys.stdout
        batch_samples = []
        for n, line in enumerate(inp, 1):
            if self._line_limit and n > self._line_limit:
                break
            for sample in self._iter_source(line):
                batch_samples.append(sample)
                if len(batch_samples) == self.batch_size_:
                    self._flush_batch(batch_samples, out)
                    batch_samples = []
        if batch_samples:
            self._flush_batch(batch_samples, out)

    def write_to_file(self, path, lines=None, binary=False,
                      slot_types=None):
        """TPU-native convenience: materialize the generated samples as
        a feed FILE (text MultiSlot, or PTMB1 when binary=True) that
        fluid.dataset / the dataset-engine trainer ingests directly.
        generate_batch applies per batch_size_ chunk, same as the
        stdout drivers."""
        records = []
        batch = []

        def flush():
            for s in self._apply_batch(batch):
                self._gen_str(s)  # validates + learns proto_info
                records.append([vals for _, vals in s])
            batch.clear()

        src = lines if lines is not None else [None]
        for line in src:
            for sample in self._iter_source(line):
                batch.append(sample)
                if len(batch) == self.batch_size_:
                    flush()
        flush()
        if binary:
            from ...fluid.dataset import write_multislot_binary

            types = slot_types or [
                t for _, t in (self._proto_info or [])]
            write_multislot_binary(path, records, types)
        else:
            with open(path, "w") as f:
                for rec in records:
                    f.write(" ".join(
                        f"{len(v)} " + " ".join(str(x) for x in v)
                        for v in rec) + "\n")
        return len(records)

    # -- internals ------------------------------------------------------
    def _iter_source(self, line):
        """Raw samples from generate_sample (batch hooks apply later,
        per batch_size_ chunk — the reference DataGenerator protocol)."""
        it = self.generate_sample(line)
        if it is None:
            raise ValueError("generate_sample returned None")
        gen = it() if callable(it) else it
        for sample in gen:
            if sample is not None:
                yield sample

    def _apply_batch(self, samples):
        post = self.generate_batch(list(samples))
        return (post() if callable(post) else post)

    def _flush_batch(self, samples, out):
        for s in self._apply_batch(samples):
            out.write(self._gen_str(s))

    def generate_sample(self, line):
        raise NotImplementedError(
            "implement generate_sample(line) -> generator of "
            "[(name, [values...]), ...]")

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    def _gen_str(self, line):
        raise NotImplementedError(
            "use MultiSlotDataGenerator or MultiSlotStringDataGenerator")


class MultiSlotDataGenerator(DataGenerator):
    """Numeric slots: sample = [(name, [int-or-float...]), ...]; wire
    line = `<n> v1..vn` per slot, space-joined (data_feed.cc
    MultiSlotDataFeed)."""

    def _gen_str(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of generate_sample must be list/tuple of "
                "(name, [values...]); got %r" % type(line))
        if self._proto_info is None:
            self._proto_info = []
            for name, elements in line:
                if not isinstance(name, str):
                    raise ValueError("slot name must be str")
                if not isinstance(elements, list) or not elements:
                    raise ValueError(
                        f"slot {name}: elements must be a non-empty "
                        f"list (pad in generate_sample)")
                is_f = any(isinstance(e, float) for e in elements)
                self._proto_info.append(
                    (name, "float32" if is_f else "int64"))
        else:
            if len(line) != len(self._proto_info):
                raise ValueError(
                    f"every sample must emit {len(self._proto_info)} "
                    f"slots, got {len(line)}")
            for (name, elements), (want, ftype) in zip(
                    line, self._proto_info):
                if name != want:
                    raise ValueError(
                        f"slot order changed: expected {want}, "
                        f"got {name}")
                if not elements:
                    raise ValueError(f"slot {name}: empty elements")
        parts = []
        for _, elements in line:
            parts.append(str(len(elements)))
            parts.extend(str(e) for e in elements)
        return " ".join(parts) + "\n"


class MultiSlotStringDataGenerator(DataGenerator):
    """Pre-stringified slots: sample = [(name, ["1", "2"]), ...] —
    fastest path when upstream already has strings."""

    def _gen_str(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of generate_sample must be list/tuple")
        parts = []
        for _, elements in line:
            parts.append(str(len(elements)))
            parts.extend(elements)
        return " ".join(parts) + "\n"
