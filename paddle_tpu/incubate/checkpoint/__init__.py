"""Auto-checkpoint for elastic restart.

Reference parity: fluid/incubate/checkpoint/auto_checkpoint.py
(AutoCheckpointChecker :71, TrainEpochRange :265 — wraps the epoch loop,
snapshots state, resumes after reschedule). TPU-native: orbax-style local /
GCS checkpoint dir, env-driven like the reference (PADDLE_JOB_ID,
PADDLE_CHECKPOINT_DIR).
"""
from __future__ import annotations

import json
import os

from ...io.serialization import load, save


class AutoCheckpointChecker:
    def __init__(self):
        self.job_id = os.environ.get("PADDLE_JOB_ID", "")
        self.ckpt_dir = os.environ.get("PADDLE_CHECKPOINT_DIR", "")

    def valid(self):
        return bool(self.job_id and self.ckpt_dir)


class TrainEpochRange:
    """for epoch in TrainEpochRange(n, name).get(): ... — resumes from the
    last completed epoch after a restart."""

    def __init__(self, max_epoch_num, name, checkpoint_inter=None,
                 save_checkpoint_fn=None, load_checkpoint_fn=None,
                 ps_communicator=None):
        """ps_communicator: a distributed.ps.Communicator — when given,
        every checkpoint also snapshots the PSERVER tables (dense +
        sparse embedding shards, checkpoint_notify_op.cc:66 role) and a
        restart restores them, so a CTR job resumes with its embedding
        table instead of a re-initialized one."""
        self._max = max_epoch_num
        self._name = name
        self._checker = AutoCheckpointChecker()
        self._save_fn = save_checkpoint_fn
        self._load_fn = load_checkpoint_fn
        self._ps_comm = ps_communicator
        self._start = 0
        if self._checker.valid():
            meta = self._meta_path()
            if os.path.exists(meta):
                try:
                    with open(meta) as f:
                        state = json.load(f)
                except (OSError, ValueError):
                    # torn/corrupt meta (killed mid-write before this
                    # file became atomic): start fresh rather than die
                    import warnings

                    warnings.warn(
                        f"auto-checkpoint meta {meta!r} unreadable; "
                        f"restarting from epoch 0")
                    state = {}
                self._start = state.get("epoch", -1) + 1
                if self._load_fn and state.get("payload"):
                    self._load_fn(state["payload"])
                if self._ps_comm is not None and state.get("ps_dir"):
                    self._ps_comm.checkpoint_notify(state["ps_dir"],
                                                    load=True)

    def _meta_path(self):
        return os.path.join(self._checker.ckpt_dir,
                            f"{self._checker.job_id}_{self._name}.json")

    def get(self):
        for epoch in range(self._start, self._max):
            yield epoch
            self.save_checkpoint(epoch)

    def save_checkpoint(self, epoch):
        if not self._checker.valid():
            return
        os.makedirs(self._checker.ckpt_dir, exist_ok=True)
        payload = None
        if self._save_fn:
            payload = os.path.join(
                self._checker.ckpt_dir,
                f"{self._checker.job_id}_{self._name}_e{epoch}.pdparams")
            self._save_fn(payload)
        ps_dir = None
        if self._ps_comm is not None:
            # per-epoch dir: a crash between the snapshot and the meta
            # write must leave the PREVIOUS epoch's snapshot intact (an
            # in-place overwrite would double-apply an epoch on resume)
            ps_dir = os.path.join(
                self._checker.ckpt_dir,
                f"{self._checker.job_id}_{self._name}_ps_e{epoch}")
            os.makedirs(ps_dir, exist_ok=True)
            self._ps_comm.checkpoint_notify(ps_dir)
        # atomic meta publish: tmp + os.replace, so a kill mid-write
        # leaves the previous epoch's meta intact instead of torn JSON
        meta = self._meta_path()
        tmp = meta + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"epoch": epoch, "payload": payload,
                       "ps_dir": ps_dir}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, meta)
        if ps_dir is not None:
            # GC snapshots older than the one the meta now points at
            import glob as _glob
            import shutil

            pat = os.path.join(
                self._checker.ckpt_dir,
                f"{self._checker.job_id}_{self._name}_ps_e*")
            for d in _glob.glob(pat):
                if d != ps_dir:
                    shutil.rmtree(d, ignore_errors=True)
