"""Auto-checkpoint for elastic restart.

Reference parity: fluid/incubate/checkpoint/auto_checkpoint.py
(AutoCheckpointChecker :71, TrainEpochRange :265 — wraps the epoch loop,
snapshots state, resumes after reschedule). TPU-native: orbax-style local /
GCS checkpoint dir, env-driven like the reference (PADDLE_JOB_ID,
PADDLE_CHECKPOINT_DIR).
"""
from __future__ import annotations

import json
import os

from ...io.serialization import load, save


class AutoCheckpointChecker:
    def __init__(self):
        self.job_id = os.environ.get("PADDLE_JOB_ID", "")
        self.ckpt_dir = os.environ.get("PADDLE_CHECKPOINT_DIR", "")

    def valid(self):
        return bool(self.job_id and self.ckpt_dir)


class TrainEpochRange:
    """for epoch in TrainEpochRange(n, name).get(): ... — resumes from the
    last completed epoch after a restart."""

    def __init__(self, max_epoch_num, name, checkpoint_inter=None,
                 save_checkpoint_fn=None, load_checkpoint_fn=None):
        self._max = max_epoch_num
        self._name = name
        self._checker = AutoCheckpointChecker()
        self._save_fn = save_checkpoint_fn
        self._load_fn = load_checkpoint_fn
        self._start = 0
        if self._checker.valid():
            meta = self._meta_path()
            if os.path.exists(meta):
                with open(meta) as f:
                    state = json.load(f)
                self._start = state.get("epoch", -1) + 1
                if self._load_fn and state.get("payload"):
                    self._load_fn(state["payload"])

    def _meta_path(self):
        return os.path.join(self._checker.ckpt_dir,
                            f"{self._checker.job_id}_{self._name}.json")

    def get(self):
        for epoch in range(self._start, self._max):
            yield epoch
            self.save_checkpoint(epoch)

    def save_checkpoint(self, epoch):
        if not self._checker.valid():
            return
        os.makedirs(self._checker.ckpt_dir, exist_ok=True)
        payload = None
        if self._save_fn:
            payload = os.path.join(
                self._checker.ckpt_dir,
                f"{self._checker.job_id}_{self._name}_e{epoch}.pdparams")
            self._save_fn(payload)
        with open(self._meta_path(), "w") as f:
            json.dump({"epoch": epoch, "payload": payload}, f)
