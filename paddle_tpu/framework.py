"""Execution-mode switch: paddle.enable_static/disable_static parity
(fluid/framework.py dygraph guards — reference runs dygraph OFF by default in
1.x; 2.0 runs dygraph ON by default, which we follow)."""
from __future__ import annotations

_static_mode = [False]


def enable_static():
    _static_mode[0] = True


def disable_static():
    _static_mode[0] = False


def in_dynamic_mode() -> bool:
    return not _static_mode[0]


def in_dygraph_mode() -> bool:
    return not _static_mode[0]
