"""Pure (pytree) optimizer rules for jitted SPMD train steps.

Reference parity: operators/optimizers/*.cc kernels (sgd_op.cc, momentum_op.cc,
adam_op.cc, lamb_op.cc) — the same update math as paddle_tpu.optimizer's eager
classes, but expressed as init/update over whole parameter pytrees so a
`pjit`ed train step can fuse every parameter update into one XLA program and
shard optimizer state alongside the parameters (ZeRO-style when the rules'
state inherits the param sharding).

API (optax-shaped, by design — the TPU-idiomatic form):
    tx = adam(lr=1e-3)
    state = tx.init(params)
    new_params, new_state = tx.update(params, grads, state)
"""
from __future__ import annotations

import collections
from typing import Any, Callable, NamedTuple


class Transform(NamedTuple):
    init: Callable[[Any], Any]
    update: Callable[..., Any]  # (params, grads, state, **extra)


def _map(fn, *trees):
    import jax

    return jax.tree_util.tree_map(fn, *trees)


def _unzip(out):
    """out is a pytree whose leaves are tuples (rule outputs); returns
    pick(i) -> the pytree of each tuple slot."""
    import jax

    is_tup = lambda t: isinstance(t, tuple)  # noqa: E731
    return lambda i: jax.tree_util.tree_map(lambda t: t[i], out,
                                            is_leaf=is_tup)


def _resolve_lr(lr, count):
    if callable(lr):
        return lr(count)
    return lr


def _cast_lr(lrv, p):
    """Keep traced (array) learning rates from promoting low-precision
    params; python-float lrs stay weakly typed."""
    if hasattr(lrv, "astype"):
        return lrv.astype(p.dtype)
    return lrv


class ScaleState(NamedTuple):
    count: Any


def sgd(learning_rate=0.01, weight_decay=0.0):
    """sgd_op.cc parity: p -= lr * (g + wd*p)."""

    def init(params):
        import jax.numpy as jnp

        return ScaleState(count=jnp.zeros((), jnp.int32))

    def update(params, grads, state):
        lrv = _resolve_lr(learning_rate, state.count)

        new_params = _map(
            lambda p, g: p - _cast_lr(lrv, p) * (
                g.astype(p.dtype) + weight_decay * p),
            params, grads)
        return new_params, ScaleState(count=state.count + 1)

    return Transform(init, update)


class MomentumState(NamedTuple):
    count: Any
    velocity: Any


def momentum(learning_rate=0.01, mu=0.9, weight_decay=0.0,
             use_nesterov=False):
    """momentum_op.cc parity."""

    def init(params):
        import jax.numpy as jnp

        return MomentumState(
            count=jnp.zeros((), jnp.int32),
            velocity=_map(lambda p: jnp.zeros_like(p), params))

    def update(params, grads, state):
        lrv = _resolve_lr(learning_rate, state.count)

        def one(p, g, v):
            lr_p = _cast_lr(lrv, p)
            g = g.astype(p.dtype) + weight_decay * p
            v_new = mu * v + g
            p_new = p - lr_p * (g + mu * v_new) if use_nesterov \
                else p - lr_p * v_new
            return p_new, v_new

        import jax

        out = _map(one, params, grads, state.velocity)
        new_params = jax.tree_util.tree_map(
            lambda t: t[0], out, is_leaf=lambda t: isinstance(t, tuple))
        new_vel = jax.tree_util.tree_map(
            lambda t: t[1], out, is_leaf=lambda t: isinstance(t, tuple))
        return new_params, MomentumState(state.count + 1, new_vel)

    return Transform(init, update)


class AdamState(NamedTuple):
    count: Any
    m: Any
    v: Any


def adam(learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
         weight_decay=0.0, decoupled=False, decay_mask=None):
    """adam_op.cc / AdamW parity. `decay_mask(name_or_path)->bool` limits
    decoupled decay (AdamW's apply_decay_param_fun)."""

    def init(params):
        import jax.numpy as jnp

        return AdamState(
            count=jnp.zeros((), jnp.int32),
            m=_map(lambda p: jnp.zeros_like(p), params),
            v=_map(lambda p: jnp.zeros_like(p), params))

    def update(params, grads, state):
        import jax
        import jax.numpy as jnp

        t = state.count + 1
        lrv = _resolve_lr(learning_rate, state.count)
        tf = t.astype(jnp.float32)
        c1 = 1.0 - beta1 ** tf
        c2 = 1.0 - beta2 ** tf

        masks = None
        if decay_mask is not None:
            flat, treedef = jax.tree_util.tree_flatten_with_path(params)
            masks = jax.tree_util.tree_unflatten(
                treedef,
                [1.0 if decay_mask(jax.tree_util.keystr(kp)) else 0.0
                 for kp, _ in flat])

        def one(p, g, m, v, dm=1.0):
            g = g.astype(p.dtype)
            wd_c = 0.0 if decoupled else weight_decay * dm
            wd_d = weight_decay * dm if decoupled else 0.0
            g = g + wd_c * p
            m_new = beta1 * m + (1 - beta1) * g
            v_new = beta2 * v + (1 - beta2) * (g * g)
            mhat = m_new / c1.astype(p.dtype)
            vhat = v_new / c2.astype(p.dtype)
            upd = mhat / (jnp.sqrt(vhat) + epsilon) + wd_d * p
            return p - _cast_lr(lrv, p) * upd, m_new, v_new

        if masks is None:
            out = _map(one, params, grads, state.m, state.v)
        else:
            out = _map(one, params, grads, state.m, state.v, masks)
        is_tup = lambda t_: isinstance(t_, tuple)  # noqa: E731
        pick = lambda i: jax.tree_util.tree_map(  # noqa: E731
            lambda t_: t_[i], out, is_leaf=is_tup)
        return pick(0), AdamState(t, pick(1), pick(2))

    return Transform(init, update)


def adamw(learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
          weight_decay=0.01, decay_mask=None):
    return adam(learning_rate, beta1, beta2, epsilon, weight_decay,
                decoupled=True, decay_mask=decay_mask)


def lamb(learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-6,
         weight_decay=0.01):
    """lamb_op.cc parity: adam moments + layerwise trust ratio."""

    base = adam(1.0, beta1, beta2, epsilon, 0.0)

    def init(params):
        return base.init(params)

    def update(params, grads, state):
        import jax.numpy as jnp

        t = state.count + 1
        lrv = _resolve_lr(learning_rate, state.count)
        tf = t.astype(jnp.float32)
        c1 = 1.0 - beta1 ** tf
        c2 = 1.0 - beta2 ** tf

        def one(p, g, m, v):
            g = g.astype(p.dtype)
            m_new = beta1 * m + (1 - beta1) * g
            v_new = beta2 * v + (1 - beta2) * (g * g)
            mhat = m_new / c1.astype(p.dtype)
            vhat = v_new / c2.astype(p.dtype)
            r = mhat / (jnp.sqrt(vhat) + epsilon) + weight_decay * p
            w_norm = jnp.sqrt((p.astype(jnp.float32) ** 2).sum())
            r_norm = jnp.sqrt((r.astype(jnp.float32) ** 2).sum())
            trust = jnp.where((w_norm > 0) & (r_norm > 0),
                              w_norm / r_norm, 1.0).astype(p.dtype)
            return p - _cast_lr(lrv, p) * trust * r, m_new, v_new

        import jax

        out = _map(one, params, grads, state.m, state.v)
        is_tup = lambda t_: isinstance(t_, tuple)  # noqa: E731
        pick = lambda i: jax.tree_util.tree_map(  # noqa: E731
            lambda t_: t_[i], out, is_leaf=is_tup)
        return pick(0), AdamState(t, pick(1), pick(2))

    return Transform(init, update)


class AdamaxState(NamedTuple):
    count: Any
    m: Any
    u: Any


def adamax(learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8):
    """adamax_op.cc parity: adam moments with an infinity-norm second
    moment (no weight decay — the eager rule has none either)."""

    def init(params):
        import jax.numpy as jnp

        return AdamaxState(
            count=jnp.zeros((), jnp.int32),
            m=_map(lambda p: jnp.zeros_like(p), params),
            u=_map(lambda p: jnp.zeros_like(p), params))

    def update(params, grads, state):
        import jax.numpy as jnp

        t = state.count + 1
        lrv = _resolve_lr(learning_rate, state.count)
        c1 = 1.0 - beta1 ** t.astype(jnp.float32)

        def one(p, g, m, u):
            g = g.astype(p.dtype)
            m_new = beta1 * m + (1 - beta1) * g
            u_new = jnp.maximum(beta2 * u, jnp.abs(g))
            p_new = p - _cast_lr(lrv, p) / c1.astype(p.dtype) * m_new / (
                u_new + epsilon)
            return p_new, m_new, u_new

        pick = _unzip(_map(one, params, grads, state.m, state.u))
        return pick(0), AdamaxState(t, pick(1), pick(2))

    return Transform(init, update)


class AdagradState(NamedTuple):
    count: Any
    moment: Any


def adagrad(learning_rate=0.001, epsilon=1e-6):
    """adagrad_op.cc parity: acc += g*g; p -= lr * g / (sqrt(acc)+eps)."""

    def init(params):
        import jax.numpy as jnp

        return AdagradState(
            count=jnp.zeros((), jnp.int32),
            moment=_map(lambda p: jnp.zeros_like(p), params))

    def update(params, grads, state):
        import jax.numpy as jnp

        lrv = _resolve_lr(learning_rate, state.count)

        def one(p, g, acc):
            g = g.astype(p.dtype)
            acc_new = acc + g * g
            return (p - _cast_lr(lrv, p) * g / (jnp.sqrt(acc_new) +
                                                epsilon), acc_new)

        pick = _unzip(_map(one, params, grads, state.moment))
        return pick(0), AdagradState(state.count + 1, pick(1))

    return Transform(init, update)


class AdadeltaState(NamedTuple):
    count: Any
    avg_sq_grad: Any
    avg_sq_upd: Any


def adadelta(learning_rate=0.001, epsilon=1e-6, rho=0.95):
    """adadelta_op.cc parity."""

    def init(params):
        import jax.numpy as jnp

        return AdadeltaState(
            count=jnp.zeros((), jnp.int32),
            avg_sq_grad=_map(lambda p: jnp.zeros_like(p), params),
            avg_sq_upd=_map(lambda p: jnp.zeros_like(p), params))

    def update(params, grads, state):
        import jax.numpy as jnp

        lrv = _resolve_lr(learning_rate, state.count)

        def one(p, g, eg, eu):
            g = g.astype(p.dtype)
            eg_new = rho * eg + (1 - rho) * g * g
            upd = jnp.sqrt(eu + epsilon) / jnp.sqrt(eg_new + epsilon) * g
            eu_new = rho * eu + (1 - rho) * upd * upd
            return p - _cast_lr(lrv, p) * upd, eg_new, eu_new

        pick = _unzip(_map(one, params, grads, state.avg_sq_grad,
                           state.avg_sq_upd))
        return pick(0), AdadeltaState(state.count + 1, pick(1), pick(2))

    return Transform(init, update)


class RmspropState(NamedTuple):
    count: Any
    mean_square: Any
    mean_grad: Any
    momentum: Any


def rmsprop(learning_rate=0.001, rho=0.95, epsilon=1e-6, momentum=0.0,
            centered=False):
    """rmsprop_op.cc parity (lr folded into the momentum accumulator)."""

    def init(params):
        import jax.numpy as jnp

        return RmspropState(
            count=jnp.zeros((), jnp.int32),
            mean_square=_map(lambda p: jnp.zeros_like(p), params),
            mean_grad=_map(lambda p: jnp.zeros_like(p), params),
            momentum=_map(lambda p: jnp.zeros_like(p), params))

    def update(params, grads, state):
        import jax.numpy as jnp

        lrv = _resolve_lr(learning_rate, state.count)

        def one(p, g, ms, mg, mom):
            g = g.astype(p.dtype)
            ms_new = rho * ms + (1 - rho) * g * g
            if centered:
                mg_new = rho * mg + (1 - rho) * g
                denom = jnp.sqrt(ms_new - mg_new * mg_new + epsilon)
            else:
                mg_new = mg
                denom = jnp.sqrt(ms_new + epsilon)
            mom_new = momentum * mom + _cast_lr(lrv, p) * g / denom
            return p - mom_new, ms_new, mg_new, mom_new

        pick = _unzip(_map(one, params, grads, state.mean_square,
                           state.mean_grad, state.momentum))
        return pick(0), RmspropState(state.count + 1, pick(1), pick(2),
                                     pick(3))

    return Transform(init, update)


def clip_by_global_norm(tx: Transform, max_norm: float) -> Transform:
    """ClipGradByGlobalNorm composed into a pure rule (clip_op parity)."""

    def init(params):
        return tx.init(params)

    def update(params, grads, state):
        import jax
        import jax.numpy as jnp

        leaves = jax.tree_util.tree_leaves(grads)
        gnorm = jnp.sqrt(sum((g.astype(jnp.float32) ** 2).sum()
                             for g in leaves))
        scale = jnp.minimum(1.0, max_norm / jnp.maximum(gnorm, 1e-12))
        grads = _map(lambda g: (g * scale).astype(g.dtype), grads)
        return tx.update(params, grads, state)

    return Transform(init, update)


def from_eager(opt) -> Transform:
    """Build the pure Transform matching an eager paddle_tpu.optimizer
    instance (so hapi/fleet can accept paddle-style optimizer objects and
    still run a fully jitted SPMD step). Carries over: the LR schedule
    (on-device via get_lr_traced, frozen with a warning when the schedule is
    host-driven e.g. ReduceOnPlateau), global-norm grad clipping, weight
    decay, and AdamW's apply_decay_param_fun exclusion mask."""
    import warnings

    from . import (SGD, Adam, AdamW, Lamb, Momentum)
    from .lr import LRScheduler

    lr = opt._lr

    if isinstance(lr, LRScheduler):
        sched = lr
        if type(sched).traceable():
            lrv = sched.get_lr_traced
        else:
            warnings.warn(
                f"{type(sched).__name__} has no traced form; the SPMD step "
                f"freezes its current lr={float(sched())}")
            lrv = float(sched())
    else:
        lrv = float(lr)

    def _wd_of(v):
        if v is None:
            return 0.0
        if hasattr(v, "_coeff"):  # fluid regularizer (L2Decay)
            return float(v._coeff)
        return float(v)

    wd = _wd_of(getattr(opt, "_weight_decay", None))

    # AdamW's per-parameter decay exclusion: the mask fn receives the
    # flattened param-tree key string (contains the state_dict name).
    decay_mask = None
    fn = getattr(opt, "_apply_decay_param_fun", None)
    if fn is not None:
        decay_mask = lambda keypath: bool(fn(keypath))  # noqa: E731

    if isinstance(opt, AdamW):
        tx = adamw(lrv, opt._beta1, opt._beta2, opt._eps, wd,
                   decay_mask=decay_mask)
    elif isinstance(opt, Adam):
        tx = adam(lrv, opt._beta1, opt._beta2, opt._eps, wd)
    elif isinstance(opt, Momentum):
        tx = momentum(lrv, opt._momentum, wd, opt._use_nesterov)
    elif isinstance(opt, Lamb):
        tx = lamb(lrv, opt._beta1, opt._beta2, opt._eps, wd)
    elif isinstance(opt, SGD):
        tx = sgd(lrv, wd)
    else:
        tx = sgd(lrv, wd)

    clip = getattr(opt, "_grad_clip", None)
    if clip is not None:
        from ..nn import ClipGradByGlobalNorm

        if isinstance(clip, ClipGradByGlobalNorm):
            tx = clip_by_global_norm(tx, float(clip.clip_norm))
        else:
            warnings.warn(
                f"grad_clip {type(clip).__name__} not representable in the "
                f"SPMD step; only ClipGradByGlobalNorm is carried over")
    return tx


class LarsState(NamedTuple):
    count: Any
    velocity: Any


def lars_momentum(learning_rate=0.01, mu=0.9, lars_coeff=0.001,
                  lars_weight_decay=5e-4, epsilon=1e-9):
    """lars_momentum_op.cc parity: layer-wise adaptive rate scaling.
    local_lr = lr * coeff * ||p|| / (||g|| + wd*||p|| + eps),
    v = mu*v + local_lr*(g + wd*p); p -= v."""

    def init(params):
        import jax.numpy as jnp

        return LarsState(
            count=jnp.zeros((), jnp.int32),
            velocity=_map(jnp.zeros_like, params))

    def update(params, grads, state):
        import jax.numpy as jnp

        lrv = _resolve_lr(learning_rate, state.count)

        def one(p, g, v):
            pn = jnp.linalg.norm(p.astype(jnp.float32))
            gn = jnp.linalg.norm(g.astype(jnp.float32))
            local = lrv * lars_coeff * pn / (
                gn + lars_weight_decay * pn + epsilon)
            local = jnp.where(pn > 0, local, lrv)
            nv = mu * v + local.astype(p.dtype) * (
                g + lars_weight_decay * p)
            return p - nv, nv

        import jax

        out = jax.tree_util.tree_map(one, params, grads, state.velocity)
        leaf = lambda x: isinstance(x, tuple)  # noqa: E731
        new_p = jax.tree_util.tree_map(lambda o: o[0], out, is_leaf=leaf)
        new_v = jax.tree_util.tree_map(lambda o: o[1], out, is_leaf=leaf)
        return new_p, LarsState(state.count + 1, new_v)

    return Transform(init, update)


class DgcState(NamedTuple):
    inner: Any
    residual: Any  # error-feedback accumulator (momentum correction)


def dgc(tx: Transform, sparsity=0.99, rampup_begin_step=0):
    """Deep Gradient Compression (details/sparse_all_reduce_op_handle.cc +
    DGCMomentumOptimizer capability): keep only the top-(1-sparsity)
    magnitude entries of each grad, accumulate the rest locally
    (error feedback), then run the inner rule on the sparsified grad.
    On TPU the sparsified grad still rides the dense XLA all-reduce (ICI
    bandwidth is the non-issue; the capability kept is the accuracy
    behavior of DGC's momentum correction)."""

    def init(params):
        import jax.numpy as jnp

        return DgcState(inner=tx.init(params),
                        residual=_map(jnp.zeros_like, params))

    def update(params, grads, state, **kw):
        import jax.numpy as jnp

        def compress(g, r):
            acc = g + r
            flat = jnp.abs(acc).reshape(-1)
            k = max(1, int(flat.size * (1.0 - sparsity)))
            thresh = jax.lax.top_k(flat, k)[0][-1]
            mask = jnp.abs(acc) >= thresh
            sent = jnp.where(mask, acc, 0)
            return sent, acc - sent

        import jax

        out = jax.tree_util.tree_map(compress, grads, state.residual)
        leaf = lambda x: isinstance(x, tuple)  # noqa: E731
        sent = jax.tree_util.tree_map(lambda o: o[0], out, is_leaf=leaf)
        resid = jax.tree_util.tree_map(lambda o: o[1], out, is_leaf=leaf)
        new_p, new_inner = tx.update(params, sent, state.inner, **kw)
        return new_p, DgcState(new_inner, resid)

    return Transform(init, update)
