"""Optimizers: paddle.optimizer parity.

Reference parity: python/paddle/optimizer/ (new-style Adam/AdamW/...) and
operators/optimizers/*.cc kernels (sgd_op, momentum_op, adam_op, lamb_op,
lars_momentum_op). TPU-native design: `step()` runs ONE fused jitted XLA
computation over the whole dense parameter bag (optimizer/fused.py —
grad cast, global-norm clip, per-param lr multipliers, weight decay and
the rule all inside a single donated dispatch); sparse (SelectedRows)
grads and unsupported configurations fall back to the per-param jitted
rules below. The same pure rules are reused by the static-graph
optimizer ops (fluid/optimizer.py) via optimizer/functional.py.
"""
from __future__ import annotations

import functools

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Parameter
from ..sparse import SelectedRows
from . import functional
from . import lr as lr_sched
from .lr import LRScheduler

__all__ = [
    "Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad",
    "Adadelta", "RMSProp", "Lamb", "lr",
]

lr = lr_sched


def _jnp():
    import jax.numpy as jnp

    return jnp


@functools.lru_cache(maxsize=None)
def _jitted(fn):
    import jax

    return jax.jit(fn)


def _instance_jit(obj, name, make_fn):
    """Cache a jitted update rule on the optimizer instance so repeated
    steps hit the XLA compile cache instead of retracing."""
    cached = obj.__dict__.get(name)
    if cached is None:
        import jax

        cached = jax.jit(make_fn())
        obj.__dict__[name] = cached
    return cached


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        self._lr = learning_rate
        self._parameters = list(parameters) if parameters is not None else []
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._accumulators = {}  # id(param) -> {slot: jax array}
        self._step_count = 0

    # -------------- lr --------------
    def get_lr(self):
        if isinstance(self._lr, LRScheduler):
            return float(self._lr())
        return float(self._lr)

    def set_lr(self, value):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError(
                "optimizer's learning rate can't be LRScheduler when "
                "invoke this API, because this will lead to conflict")
        self._lr = float(value)

    def _lr_for(self, p):
        base = self.get_lr()
        mult = getattr(p, "optimize_attr", None)
        if mult:
            base = base * mult.get("learning_rate", 1.0)
        return base

    # -------------- state --------------
    def _slots(self, p, names_and_inits):
        key = id(p)
        if key not in self._accumulators:
            jnp = _jnp()
            self._accumulators[key] = {
                name: (jnp.zeros_like(p._data) if init == "zeros_like"
                       else jnp.zeros(init[0], init[1]))
                for name, init in names_and_inits.items()}
        return self._accumulators[key]

    def state_dict(self):
        out = {"_step_count": self._step_count}
        for i, p in enumerate(self._parameters):
            slots = self._accumulators.get(id(p))
            if slots:
                for k, v in slots.items():
                    out[f"{p.name or i}__{k}"] = Tensor._wrap(v)
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        return out

    def set_state_dict(self, state):
        self._step_count = state.get("_step_count", 0)
        # ordered distinct prefixes as saved (dict order = param order)
        saved_prefixes = []
        for k in state:
            if not isinstance(k, str) or "__" not in k or \
                    k in ("_step_count", "LR_Scheduler"):
                continue
            pre = k.rsplit("__", 1)[0]
            if pre not in saved_prefixes:
                saved_prefixes.append(pre)
        for i, p in enumerate(self._parameters):
            prefix = f"{p.name or i}__"
            if not any(isinstance(k, str) and k.startswith(prefix)
                       for k in state) and i < len(saved_prefixes):
                # positional fallback: auto-generated param names are a
                # process-global counter, so a checkpoint restored into
                # a freshly built model (fit(resume=...) after a crash)
                # numbers its params differently — slot i still maps to
                # the i-th saved param
                prefix = saved_prefixes[i] + "__"
            for k in list(state.keys()):
                if isinstance(k, str) and k.startswith(prefix):
                    slot = k[len(prefix):]
                    v = state[k]
                    arr = v._data if isinstance(v, Tensor) else _jnp().asarray(
                        np.asarray(v))
                    self._accumulators.setdefault(id(p), {})[slot] = arr
        if "LR_Scheduler" in state and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(state["LR_Scheduler"])

    set_dict = set_state_dict

    # -------------- step --------------
    # Fused-path protocol: concrete rules declare their slot names (in
    # accumulator order), the functional state tuple holding them, and a
    # factory for the matching pure Transform. optimizer/fused.py drives
    # the whole dense update through ONE donated jitted dispatch.
    _fused_slots = ()
    _fused_state_cls = None

    def _fused_tx(self, lrv, wd):
        raise NotImplementedError

    def _fused_wd(self, p):
        return self._decay_value(p)

    def _mp_enabled(self, p):
        """multi_precision master-weight path for low-precision params."""
        if not getattr(self, "_multi_precision", False):
            return False
        jnp = _jnp()
        return p._data.dtype in (jnp.bfloat16, jnp.float16)

    def _rule_slot_spec(self, p):
        """Accumulator spec for the rule's slots; fp32 when the param
        trains against an fp32 master weight."""
        if self._mp_enabled(p):
            jnp = _jnp()
            shape = tuple(p._data.shape)
            return {n: (shape, jnp.float32) for n in self._fused_slots}
        return {n: "zeros_like" for n in self._fused_slots}

    def _mp_master(self, p, slots):
        """The fp32 master weight the rule updates (None when the param
        trains in its own dtype)."""
        if not self._mp_enabled(p):
            return None
        m = slots.get("master_weight")
        if m is None:
            m = slots["master_weight"] = p._data.astype(_jnp().float32)
        return m

    def _writeback(self, p, slots, new_p):
        if self._mp_enabled(p) and "master_weight" in slots:
            slots["master_weight"] = new_p
            p._data = new_p.astype(p._data.dtype)
        else:
            p._data = new_p

    def _collect(self):
        """Split this step's (param, grad) pairs into (dense, sparse)."""
        dense, sparse = [], []
        for p in self._parameters:
            if p.stop_gradient or not getattr(p, "trainable", True):
                continue
            g = p.grad
            if g is None:
                continue
            (sparse if isinstance(g, SelectedRows) else dense).append(
                (p, g))
        return dense, sparse

    def _decay_value(self, p):
        wd = self._weight_decay
        if wd is None:
            return 0.0
        if hasattr(wd, "_coeff"):  # fluid regularizer object
            return float(wd._coeff)
        return float(wd)

    def step(self):
        self._step_count += 1
        dense, sparse = self._collect()
        if self._grad_clip is not None and sparse:
            raise NotImplementedError(
                "grad_clip over sparse (SelectedRows) gradients is not "
                "supported; clip densely or drop the clip")
        if dense:
            from . import fused as _fused

            # duplicate param objects must not donate one buffer twice
            if _fused.supported(self) and \
                    len({id(p) for p, _ in dense}) == len(dense):
                _fused.apply(self, dense)
            else:
                pg = self._grad_clip(dense) \
                    if self._grad_clip is not None else dense
                for p, g in pg:
                    self._update_param(p, g)
        for p, g in sparse:
            if self._decay_value(p):
                raise ValueError(
                    "weight_decay/regularization is not supported with "
                    "sparse (SelectedRows) gradients — reference "
                    "lookup_table is_sparse=True has the same "
                    "restriction")
            self._update_param_sparse(p, g)

    def _update_param(self, p, g):
        raise NotImplementedError

    def _update_param_sparse(self, p, g):
        raise NotImplementedError(
            f"{type(self).__name__} has no sparse (SelectedRows) update "
            f"rule; use SGD/Momentum/Adam for sparse embedding training")

    @property
    def _parameter_list(self):
        return self._parameters

    def clear_grad(self, set_to_zero=True):
        for p in self._parameters:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        """dygraph convenience: backward already done by the user or here."""
        if loss._node is not None and all(
                p.grad is None for p in self._parameters
                if not p.stop_gradient):
            loss.backward()
        self.step()
        return None, None


# -------------------- concrete rules --------------------

def _sgd_rule(p, g, lrv, wd):
    return p - lrv * (g + wd * p)


def _momentum_rule(p, g, vel, lrv, mu, wd, use_nesterov):
    g = g + wd * p
    v_new = mu * vel + g
    if use_nesterov:
        p_new = p - lrv * (g + mu * v_new)
    else:
        p_new = p - lrv * v_new
    return p_new, v_new


def _adam_rule(p, g, m, v, lrv, b1, b2, eps, t, wd, decoupled):
    """Branch-free so `wd` can be a traced scalar under jit: coupled decay
    adds wd*p to the grad, decoupled (AdamW) adds it to the update."""
    jnp = _jnp()
    wd_c = 0.0 if decoupled else wd
    wd_d = wd if decoupled else 0.0
    g = g + wd_c * p
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * (g * g)
    mhat = m_new / (1 - b1 ** t)
    vhat = v_new / (1 - b2 ** t)
    upd = mhat / (jnp.sqrt(vhat) + eps) + wd_d * p
    return p - lrv * upd, m_new, v_new


def _lamb_rule(p, g, m, v, lrv, b1, b2, eps, t, wd):
    jnp = _jnp()
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * (g * g)
    mhat = m_new / (1 - b1 ** t)
    vhat = v_new / (1 - b2 ** t)
    r = mhat / (jnp.sqrt(vhat) + eps) + wd * p
    w_norm = jnp.sqrt((p * p).sum())
    r_norm = jnp.sqrt((r * r).sum())
    trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
    return p - lrv * trust * r, m_new, v_new


# -------------------- sparse (SelectedRows) rules --------------------
# Reference: the SelectedRows kernels of sgd_op.h / momentum_op.h /
# adam_op.h — moments decay over all rows, the gradient contributes only
# its rows, and lazy_mode restricts the whole update to touched rows.

def _sgd_sparse_rule(p, rows, vals, lrv):
    return p.at[rows].add(-(lrv * vals).astype(p.dtype), mode="drop")


def _momentum_sparse_rule(p, rows, vals, vel, lrv, mu, use_nesterov):
    v_new = (mu * vel).at[rows].add(vals, mode="drop")
    if use_nesterov:
        # p -= lr * (g + mu*v_new): dense mu*v_new part + sparse g part
        p_new = (p - lrv * mu * v_new).at[rows].add(
            -(lrv * vals).astype(p.dtype), mode="drop")
    else:
        p_new = p - lrv * v_new
    return p_new.astype(p.dtype), v_new


def _adam_sparse_rule(p, rows, vals, m, v, lrv, b1, b2, eps, t):
    """Non-lazy sparse adam (adam_op.h SparseAdamFunctor, mode='global'):
    moments decay everywhere, grad adds on its rows, every row's param
    moves by the bias-corrected moment."""
    jnp = _jnp()
    m_new = (b1 * m).at[rows].add((1 - b1) * vals, mode="drop")
    v_new = (b2 * v).at[rows].add((1 - b2) * vals * vals, mode="drop")
    mhat = m_new / (1 - b1 ** t)
    vhat = v_new / (1 - b2 ** t)
    return (p - lrv * mhat / (jnp.sqrt(vhat) + eps)).astype(p.dtype), \
        m_new, v_new


def _adam_sparse_lazy_rule(p, rows, vals, m, v, lrv, b1, b2, eps, t):
    """lazy_mode=True: only touched rows update (rows pre-merged)."""
    jnp = _jnp()
    m_r = b1 * m[rows] + (1 - b1) * vals
    v_r = b2 * v[rows] + (1 - b2) * vals * vals
    mhat = m_r / (1 - b1 ** t)
    vhat = v_r / (1 - b2 ** t)
    upd = -(lrv * mhat / (jnp.sqrt(vhat) + eps)).astype(p.dtype)
    return (p.at[rows].add(upd, mode="drop"),
            m.at[rows].set(m_r, mode="drop"),
            v.at[rows].set(v_r, mode="drop"))


class SGD(Optimizer):
    _fused_slots = ()
    _fused_state_cls = functional.ScaleState

    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)

    def _fused_tx(self, lrv, wd):
        return functional.sgd(lrv, wd)

    def _update_param(self, p, g):
        fn = _jitted(_sgd_rule)
        p._data = fn(p._data, g._data.astype(p._data.dtype),
                     self._lr_for(p), self._decay_value(p))

    def _update_param_sparse(self, p, g):
        fn = _jitted(_sgd_sparse_rule)
        p._data = fn(p._data, g.rows, g.values.astype(p._data.dtype),
                     self._lr_for(p))


class Momentum(Optimizer):
    _fused_slots = ("velocity",)
    _fused_state_cls = functional.MomentumState

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = momentum
        self._use_nesterov = use_nesterov
        self._multi_precision = bool(multi_precision)

    def _fused_tx(self, lrv, wd):
        return functional.momentum(lrv, self._momentum, wd,
                                   self._use_nesterov)

    def _update_param(self, p, g):
        slots = self._slots(p, self._rule_slot_spec(p))
        master = self._mp_master(p, slots)
        base = master if master is not None else p._data
        fn = _instance_jit(self, "_jit_rule", lambda: functools.partial(
            _momentum_rule, use_nesterov=self._use_nesterov))
        new_p, slots["velocity"] = fn(
            base, g._data.astype(base.dtype), slots["velocity"],
            self._lr_for(p), self._momentum, self._decay_value(p))
        self._writeback(p, slots, new_p)

    def _update_param_sparse(self, p, g):
        if self._mp_enabled(p):
            raise NotImplementedError(
                "multi_precision is not supported with sparse "
                "(SelectedRows) gradients")
        slots = self._slots(p, {"velocity": "zeros_like"})
        fn = _instance_jit(self, "_jit_sparse", lambda: functools.partial(
            _momentum_sparse_rule, use_nesterov=self._use_nesterov))
        p._data, slots["velocity"] = fn(
            p._data, g.rows, g.values.astype(p._data.dtype),
            slots["velocity"], self._lr_for(p), self._momentum)


class Adam(Optimizer):
    _decoupled_wd = False
    _fused_slots = ("moment1", "moment2")
    _fused_state_cls = functional.AdamState

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._lazy_mode = bool(lazy_mode)
        self._multi_precision = bool(multi_precision)

    def _fused_tx(self, lrv, wd):
        return functional.adam(lrv, self._beta1, self._beta2, self._eps,
                               wd, decoupled=self._decoupled_wd)

    def _update_param(self, p, g):
        slots = self._slots(p, self._rule_slot_spec(p))
        master = self._mp_master(p, slots)
        base = master if master is not None else p._data
        fn = _instance_jit(self, "_jit_rule", lambda: functools.partial(
            _adam_rule, decoupled=self._decoupled_wd))
        new_p, slots["moment1"], slots["moment2"] = fn(
            base, g._data.astype(base.dtype), slots["moment1"],
            slots["moment2"], self._lr_for(p), self._beta1, self._beta2,
            self._eps, float(self._step_count), self._decay_value(p))
        self._writeback(p, slots, new_p)

    def _update_param_sparse(self, p, g):
        if self._mp_enabled(p):
            raise NotImplementedError(
                "multi_precision is not supported with sparse "
                "(SelectedRows) gradients")
        slots = self._slots(p, {"moment1": "zeros_like",
                                "moment2": "zeros_like"})
        # adam is non-linear in g (g*g): duplicate rows MUST merge first
        # (adam_op.h runs scatter::MergeAdd before the sparse functor);
        # shape_stable keeps the row count static so the jitted rule
        # compiles once, not once per distinct nnz
        g = g.merge(shape_stable=True)
        rule = _adam_sparse_lazy_rule if self._lazy_mode else \
            _adam_sparse_rule
        fn = _jitted(rule)
        p._data, slots["moment1"], slots["moment2"] = fn(
            p._data, g.rows, g.values.astype(p._data.dtype),
            slots["moment1"], slots["moment2"], self._lr_for(p),
            self._beta1, self._beta2, self._eps, float(self._step_count))


class AdamW(Adam):
    _decoupled_wd = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 apply_decay_param_fun=None, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode=lazy_mode,
                         multi_precision=multi_precision)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decay_value(self, p):
        if self._apply_decay_param_fun is not None and \
                not self._apply_decay_param_fun(p.name):
            return 0.0
        return super()._decay_value(p)


class Adamax(Optimizer):
    _fused_slots = ("moment", "inf_norm")
    _fused_state_cls = functional.AdamaxState

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _fused_tx(self, lrv, wd):
        # the per-param adamax rule applies no weight decay; keep parity
        return functional.adamax(lrv, self._beta1, self._beta2, self._eps)

    def _update_param(self, p, g):
        jnp = _jnp()
        slots = self._slots(p, {"moment": "zeros_like",
                                "inf_norm": "zeros_like"})

        def rule(pp, gg, m, u, lrv, t):
            m_new = self._beta1 * m + (1 - self._beta1) * gg
            u_new = jnp.maximum(self._beta2 * u, jnp.abs(gg))
            p_new = pp - lrv / (1 - self._beta1 ** t) * m_new / (
                u_new + self._eps)
            return p_new, m_new, u_new

        fn = _instance_jit(self, "_jit_rule", lambda: rule)
        p._data, slots["moment"], slots["inf_norm"] = fn(
            p._data, g._data.astype(p._data.dtype), slots["moment"],
            slots["inf_norm"], self._lr_for(p), float(self._step_count))


class Adagrad(Optimizer):
    _fused_slots = ("moment",)
    _fused_state_cls = functional.AdagradState

    def __init__(self, learning_rate=0.001, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _fused_tx(self, lrv, wd):
        return functional.adagrad(lrv, self._eps)

    def _update_param(self, p, g):
        jnp = _jnp()
        slots = self._slots(p, {"moment": "zeros_like"})

        def rule(pp, gg, acc, lrv):
            acc_new = acc + gg * gg
            return pp - lrv * gg / (jnp.sqrt(acc_new) + self._eps), acc_new

        fn = _instance_jit(self, "_jit_rule", lambda: rule)
        p._data, slots["moment"] = fn(
            p._data, g._data.astype(p._data.dtype), slots["moment"],
            self._lr_for(p))


class Adadelta(Optimizer):
    _fused_slots = ("avg_sq_grad", "avg_sq_upd")
    _fused_state_cls = functional.AdadeltaState

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._eps, self._rho = epsilon, rho

    def _fused_tx(self, lrv, wd):
        return functional.adadelta(lrv, self._eps, self._rho)

    def _update_param(self, p, g):
        jnp = _jnp()
        slots = self._slots(p, {"avg_sq_grad": "zeros_like",
                                "avg_sq_upd": "zeros_like"})

        def rule(pp, gg, eg, eu, lrv):
            eg_new = self._rho * eg + (1 - self._rho) * gg * gg
            upd = jnp.sqrt(eu + self._eps) / jnp.sqrt(
                eg_new + self._eps) * gg
            eu_new = self._rho * eu + (1 - self._rho) * upd * upd
            return pp - lrv * upd, eg_new, eu_new

        fn = _instance_jit(self, "_jit_rule", lambda: rule)
        p._data, slots["avg_sq_grad"], slots["avg_sq_upd"] = fn(
            p._data, g._data.astype(p._data.dtype), slots["avg_sq_grad"],
            slots["avg_sq_upd"], self._lr_for(p))


class RMSProp(Optimizer):
    _fused_slots = ("mean_square", "mean_grad", "momentum")
    _fused_state_cls = functional.RmspropState

    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6,
                 momentum=0.0, centered=False, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._eps = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _fused_tx(self, lrv, wd):
        return functional.rmsprop(lrv, self._rho, self._eps,
                                  self._momentum, self._centered)

    def _update_param(self, p, g):
        jnp = _jnp()
        slots = self._slots(p, {"mean_square": "zeros_like",
                                "mean_grad": "zeros_like",
                                "momentum": "zeros_like"})

        def rule(pp, gg, ms, mg, mom, lrv):
            ms_new = self._rho * ms + (1 - self._rho) * gg * gg
            if self._centered:
                mg_new = self._rho * mg + (1 - self._rho) * gg
                denom = jnp.sqrt(ms_new - mg_new * mg_new + self._eps)
            else:
                mg_new = mg
                denom = jnp.sqrt(ms_new + self._eps)
            mom_new = self._momentum * mom + lrv * gg / denom
            return pp - mom_new, ms_new, mg_new, mom_new

        fn = _instance_jit(self, "_jit_rule", lambda: rule)
        (p._data, slots["mean_square"], slots["mean_grad"],
         slots["momentum"]) = fn(
            p._data, g._data.astype(p._data.dtype), slots["mean_square"],
            slots["mean_grad"], slots["momentum"], self._lr_for(p))


class Lamb(Optimizer):
    _fused_slots = ("moment1", "moment2")
    _fused_state_cls = functional.AdamState

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None):
        super().__init__(learning_rate, parameters, lamb_weight_decay,
                         grad_clip)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _fused_tx(self, lrv, wd):
        return functional.lamb(lrv, self._beta1, self._beta2, self._eps,
                               wd)

    def _fused_wd(self, p):
        if self._exclude_fn is not None and self._exclude_fn(p):
            return 0.0
        return self._decay_value(p)

    def _update_param(self, p, g):
        slots = self._slots(p, {"moment1": "zeros_like",
                                "moment2": "zeros_like"})
        wd = self._decay_value(p)
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        fn = _jitted(_lamb_rule)
        p._data, slots["moment1"], slots["moment2"] = fn(
            p._data, g._data.astype(p._data.dtype), slots["moment1"],
            slots["moment2"], self._lr_for(p), self._beta1, self._beta2,
            self._eps, float(self._step_count), wd)
