"""Fused whole-model optimizer step: ONE donated XLA dispatch per step.

The eager per-param path launches one jitted call per tensor (plus N+1
eager reductions when global-norm clipping is on) — for a transformer-
sized model that is hundreds of tiny device round-trips per `opt.step()`
where the math itself is microseconds. This driver collects every dense
`(param, grad, slots)` into one pytree and runs the entire update —
grad cast, global-norm clip, per-param lr multipliers (`optimize_attr`),
weight decay, fp32 master weights (`multi_precision`), and the rule —
inside a single `jax.jit` call with `donate_argnums` on params+slots, so
buffers alias across steps and XLA fuses the whole sweep.

The body reuses the pure `Transform` rules of optimizer/functional.py:
params are grouped by (lr multiplier, weight decay) — both static per
parameter — each group runs one Transform.update over its sub-pytree,
and `functional.clip_by_global_norm` wraps the combined transform so the
clip norm accumulates over ALL dense grads in fp32, exactly like the
legacy `nn.ClipGradByGlobalNorm`.

The jitted step is cached per optimizer instance, keyed on the dense
parameter-set signature (shape/dtype/grad-dtype/lr-mult/wd/mp per param
+ clip norm); lr and the step count are fed as traced scalars so LR
schedules never retrace. Donation means the OLD param/slot buffers are
invalidated after `step()` — `p._data` is rebound to the new arrays, but
raw `jax.Array` references taken before the step must not be reused.
"""
from __future__ import annotations

import numpy as np

from . import functional as F


def supported(opt):
    """True when `opt`'s dense update can run on the fused path."""
    if opt.__dict__.get("_use_fused") is False or not _enabled():
        return False
    if getattr(type(opt), "_fused_state_cls", None) is None:
        return False
    # a subclass overriding the per-param rule opts out implicitly
    from . import (SGD, Adadelta, Adagrad, Adam, Adamax, Lamb, Momentum,
                   RMSProp)

    impl = type(opt)._update_param
    if not any(impl is c._update_param for c in
               (SGD, Momentum, Adam, Adamax, Adagrad, Adadelta, RMSProp,
                Lamb)):
        return False
    clip = opt._grad_clip
    if clip is not None:
        from ..nn import ClipGradByGlobalNorm

        if type(clip) is not ClipGradByGlobalNorm:
            return False
    return True


def _enabled():
    import os

    return os.environ.get("PADDLE_TPU_FUSED_OPT", "1") != "0"


def intended_donation():
    """(params, slots) argnums the fused step donates by CONTRACT.
    `_build` skips the annotation only where the backend cannot alias
    buffers (a capability gap, not a policy change); the static
    analyzer's donation audit (PTA102) checks against this declaration
    so a CPU-run audit doesn't punish the backend gate."""
    return (0, 2)


def _low_precision(dtype):
    import jax.numpy as jnp

    return dtype in (jnp.bfloat16, jnp.float16)


def apply(opt, dense_pg):
    """Run one fused update over the dense (param, grad) list, clip
    included. Slots live in `opt._accumulators` exactly as on the
    per-param path, so state_dict round-trips and the two paths can be
    switched freely between steps."""
    import jax.numpy as jnp

    slot_names = opt._fused_slots
    specs = []
    slot_lists = []
    for p, g in dense_pg:
        mult = 1.0
        oa = getattr(p, "optimize_attr", None)
        if oa:
            mult = float(oa.get("learning_rate", 1.0))
        wd = float(opt._fused_wd(p))
        mp = opt._mp_enabled(p)
        slots = opt._slots(p, opt._rule_slot_spec(p))
        vals = [slots[n] for n in slot_names]
        if mp:
            master = slots.get("master_weight")
            if master is None:
                master = slots["master_weight"] = p._data.astype(
                    jnp.float32)
            vals.append(master)
        slot_lists.append(tuple(vals))
        specs.append((tuple(p._data.shape), str(p._data.dtype),
                      str(g._data.dtype), mult, wd, mp))
    clip = opt._grad_clip
    clip_norm = float(clip.clip_norm) if clip is not None else None
    key = (tuple(specs), clip_norm)
    cache = opt.__dict__.setdefault("_fused_cache", {})
    fn = cache.get(key)
    if fn is None:
        fn = cache[key] = _build(opt, specs, clip_norm)
    new_params, new_slots = fn(
        tuple(p._data for p, _ in dense_pg),
        tuple(g._data for _, g in dense_pg),
        tuple(slot_lists),
        np.float32(opt.get_lr()),
        np.int32(opt._step_count - 1))
    for (p, _), spec, arr, svals in zip(dense_pg, specs, new_params,
                                        new_slots):
        p._data = arr
        slots = opt._accumulators[id(p)]
        for n, v in zip(slot_names, svals):
            slots[n] = v
        if spec[5]:
            slots["master_weight"] = svals[len(slot_names)]


def _build(opt, specs, clip_norm):
    """Trace one pure function over the whole dense parameter bag.

    specs: per-param statics (shape, dtype, grad dtype, lr mult, wd, mp).
    Returns a jitted fn (params, grads, slots, lr, count) ->
    (new_params, new_slots) with params+slots donated (devices that
    support aliasing reuse the buffers in place; CPU ignores donation, so
    it is skipped there to avoid warning spam).
    """
    import jax

    n_state = len(opt._fused_slots)
    groups = {}
    for i, (_, _, _, mult, wd, _) in enumerate(specs):
        groups.setdefault((mult, wd), []).append(i)
    glist = sorted(groups.items())

    def fused(params, grads, slots, lrv, count):
        def update(ptree, gtree, _state):
            new_p = {}
            new_slots = {i: None for i in range(len(specs))}
            for (mult, wd), idxs in glist:
                tx = opt._fused_tx(lrv * mult, wd)
                sub_p = {str(i): ptree[str(i)] for i in idxs}
                sub_g = {str(i): gtree[str(i)] for i in idxs}
                trees = tuple({str(i): slots[i][k] for i in idxs}
                              for k in range(n_state))
                out_p, out_st = tx.update(
                    sub_p, sub_g, opt._fused_state_cls(count, *trees))
                new_p.update(out_p)
                for i in idxs:
                    new_slots[i] = tuple(out_st[k + 1][str(i)]
                                         for k in range(n_state))
            return new_p, new_slots

        tx_all = F.Transform(lambda _: None, update)
        if clip_norm is not None:
            tx_all = F.clip_by_global_norm(tx_all, clip_norm)
        # multi_precision params feed their fp32 master into the rule
        ptree = {str(i): (slots[i][n_state] if specs[i][5] else params[i])
                 for i in range(len(specs))}
        gtree = {str(i): g for i, g in enumerate(grads)}
        new_p, new_slots = tx_all.update(ptree, gtree, None)
        outs_p, outs_s = [], []
        for i in range(len(specs)):
            if specs[i][5]:
                outs_p.append(new_p[str(i)].astype(params[i].dtype))
                outs_s.append(new_slots[i] + (new_p[str(i)],))
            else:
                outs_p.append(new_p[str(i)])
                outs_s.append(new_slots[i])
        return tuple(outs_p), tuple(outs_s)

    donate = () if jax.default_backend() == "cpu" else \
        intended_donation()
    return jax.jit(fused, donate_argnums=donate)
