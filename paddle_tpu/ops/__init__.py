from . import kernels  # noqa: F401
from . import detection  # noqa: F401
from . import detection_train  # noqa: F401
