"""Training-side detection ops: proposal generation, target assignment,
FPN routing, hard-example mining (operators/detection/ training family,
re-designed TPU-first).

Reference parity targets:
  generate_proposals_op.cc:81, rpn_target_assign_op.cc:36 (+ the
  retinanet variant at :612), distribute_fpn_proposals_op.cc:24,
  collect_fpn_proposals_op.cc:29, generate_proposal_labels_op.cc:43,
  generate_mask_labels_op.cc, target_assign_op.cc:24,
  mine_hard_examples_op.cc:268, matrix_nms_op.cc:87.

TPU-native contract (same as ops/detection.py): every output is STATIC
shape. Variable-length results come back as fixed buffers padded with -1
(indices) or 0 (values) plus a valid count; "sampling" is a top-k over
masked random keys inside jit instead of reservoir sampling over
std::vector. Batch = vmap or a Python loop over a handful of images at
trace time, never data-dependent shapes.
"""
from __future__ import annotations

import numpy as np

from .detection import iou_matrix, nms


def _jnp():
    import jax.numpy as jnp

    return jnp


_BBOX_CLIP = float(np.log(1000.0 / 16.0))


def _encode_rowwise(targets, priors, box_normalized=False, weights=None):
    """Row-wise center-size encode: target[i] against prior[i] -> [N,4]
    (the Faster-RCNN delta form of box_coder_op.h with axis-aligned
    rows; the library box_coder's encode path is the pairwise [N,M,4]
    SSD form)."""
    jnp = _jnp()
    off = 0.0 if box_normalized else 1.0
    pw = priors[:, 2] - priors[:, 0] + off
    ph = priors[:, 3] - priors[:, 1] + off
    pcx = priors[:, 0] + pw * 0.5
    pcy = priors[:, 1] + ph * 0.5
    tw = targets[:, 2] - targets[:, 0] + off
    th = targets[:, 3] - targets[:, 1] + off
    tcx = targets[:, 0] + tw * 0.5
    tcy = targets[:, 1] + th * 0.5
    out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph,
                     jnp.log(jnp.clip(tw / pw, 1e-10, None)),
                     jnp.log(jnp.clip(th / ph, 1e-10, None))], axis=1)
    if weights is not None:
        w = jnp.asarray(weights, out.dtype)
        out = out / (w if w.ndim == 2 else w[None, :])
    return out


def _rand_keys(key, shape):
    """Uniform tie-break keys for sampling; deterministic arange when no
    PRNG key is supplied (use_random=False parity)."""
    import jax

    jnp = _jnp()
    if key is None:
        return -jnp.arange(np.prod(shape), dtype=jnp.float32).reshape(shape)
    return jax.random.uniform(key, shape)


def _sample_mask(cand_mask, quota, key):
    """Pick up to `quota` True positions from cand_mask (random when key
    given, lowest-index otherwise). Returns (mask, count)."""
    jnp = _jnp()
    n = cand_mask.shape[0]
    quota = jnp.minimum(jnp.asarray(quota, jnp.int32),
                        cand_mask.sum().astype(jnp.int32))
    score = jnp.where(cand_mask, _rand_keys(key, (n,)), -jnp.inf)
    order = jnp.argsort(-score)
    rank = jnp.zeros((n,), jnp.int32).at[order].set(
        jnp.arange(n, dtype=jnp.int32))
    mask = cand_mask & (rank < quota)
    return mask, quota


def decode_proposals(anchors, deltas, variances=None):
    """generate_proposals_op.cc BoxCoder: decode RPN deltas against
    anchors ((x1,y1,x2,y2), +1 pixel widths, exp clipped at
    log(1000/16)). anchors/deltas [N,4] -> proposals [N,4]."""
    jnp = _jnp()
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    acx = anchors[:, 0] + 0.5 * aw
    acy = anchors[:, 1] + 0.5 * ah
    if variances is not None:
        dx, dy = variances[:, 0] * deltas[:, 0], variances[:, 1] * deltas[:, 1]
        dw, dh = variances[:, 2] * deltas[:, 2], variances[:, 3] * deltas[:, 3]
    else:
        dx, dy, dw, dh = (deltas[:, i] for i in range(4))
    cx = dx * aw + acx
    cy = dy * ah + acy
    w = jnp.exp(jnp.minimum(dw, _BBOX_CLIP)) * aw
    h = jnp.exp(jnp.minimum(dh, _BBOX_CLIP)) * ah
    return jnp.stack([cx - w / 2, cy - h / 2,
                      cx + w / 2 - 1, cy + h / 2 - 1], axis=1)


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0):
    """Single image. scores [A*H*W] (objectness), bbox_deltas [A*H*W,4]
    laid out to match `anchors` [A*H*W,4], im_info [3] = (h, w, scale).
    Returns (rois [post_nms_top_n,4] zero-padded, roi_probs
    [post_nms_top_n], num_valid). generate_proposals_op.cc:81."""
    jnp = _jnp()
    n = scores.shape[0]
    k = min(int(pre_nms_top_n), n) if pre_nms_top_n > 0 else n
    top = jnp.argsort(-scores)[:k]
    props = decode_proposals(anchors[top], bbox_deltas[top],
                             None if variances is None else variances[top])
    h, w, scale = im_info[0], im_info[1], im_info[2]
    props = jnp.stack([
        jnp.clip(props[:, 0], 0.0, w - 1),
        jnp.clip(props[:, 1], 0.0, h - 1),
        jnp.clip(props[:, 2], 0.0, w - 1),
        jnp.clip(props[:, 3], 0.0, h - 1)], axis=1)
    # FilterBoxes: min_size at the ORIGINAL image scale, center inside
    ms = jnp.maximum(min_size, 1.0)
    ws = props[:, 2] - props[:, 0] + 1
    hs = props[:, 3] - props[:, 1] + 1
    keep = ((ws - 1) / scale + 1 >= ms) & ((hs - 1) / scale + 1 >= ms) \
        & (props[:, 0] + ws / 2 <= w) & (props[:, 1] + hs / 2 <= h)
    sc = jnp.where(keep, scores[top], -jnp.inf)
    keep_idx, cnt = nms(props, sc, nms_thresh,
                        max_out=int(post_nms_top_n), normalized=False)
    valid = (jnp.arange(int(post_nms_top_n)) < cnt) & (keep_idx >= 0)
    sel = jnp.clip(keep_idx, 0, k - 1)
    # nms emits by score desc, so min-size-filtered (-inf) candidates can
    # only appear AFTER every real one — `real` is a prefix mask; rows
    # past it are zeroed so the padding contract holds
    real = valid & jnp.isfinite(jnp.where(valid, sc[sel], 0.0))
    rois = jnp.where(real[:, None], props[sel], 0.0)
    probs = jnp.where(real, scores[top][sel], 0.0)
    return rois, probs, real.sum().astype(jnp.int32)


def rpn_target_assign(anchors, gt_boxes, is_crowd, im_info, gt_count=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, key=None):
    """Single image anchor→gt assignment (rpn_target_assign_op.cc:36).

    anchors [A,4], gt_boxes [G,4] (zero-padded), is_crowd [G] int,
    im_info [3], gt_count = #valid gt rows (defaults to all G).
    Returns dict with STATIC shapes:
      labels       [A] int32: 1 fg / 0 bg / -1 ignore
      bbox_targets [A,4] encode_center_size targets (zero off-fg)
      bbox_inside_weight [A,4] 1.0 on fg rows
      fg_num, bg_num scalars
    (The reference emits compacted index lists; masks over the full
    anchor set are the static equivalent — gather loc/score indices with
    jnp.nonzero OUTSIDE jit, or consume the masks directly in the loss.)
    """
    jnp = _jnp()
    A = anchors.shape[0]
    G = gt_boxes.shape[0]
    gvalid = jnp.arange(G) < (G if gt_count is None else gt_count)
    gvalid = gvalid & (jnp.asarray(is_crowd) == 0)
    h, w = im_info[0], im_info[1]
    t = rpn_straddle_thresh
    if t >= 0:
        inside = ((anchors[:, 0] >= -t) & (anchors[:, 1] >= -t)
                  & (anchors[:, 2] < w + t) & (anchors[:, 3] < h + t))
    else:
        inside = jnp.ones((A,), bool)
    iou = iou_matrix(anchors, gt_boxes, normalized=True)
    iou = jnp.where(gvalid[None, :], iou, -1.0)
    iou = jnp.where(inside[:, None], iou, -1.0)
    a2g_max = iou.max(axis=1)
    a2g_arg = iou.argmax(axis=1)
    g2a_max = iou.max(axis=0)
    # Detectron rule: anchors hitting a gt's best overlap, or above thresh
    is_best = ((jnp.abs(iou - g2a_max[None, :]) < 1e-5)
               & gvalid[None, :] & (iou > 0)).any(axis=1)
    fg_cand = inside & (is_best | (a2g_max >= rpn_positive_overlap))
    fg_quota = int(rpn_fg_fraction * rpn_batch_size_per_im) \
        if rpn_fg_fraction > 0 and rpn_batch_size_per_im > 0 else A
    import jax

    k1 = k2 = None
    if key is not None:
        k1, k2 = jax.random.split(key)
    fg_mask, fg_num = _sample_mask(fg_cand, fg_quota, k1)
    bg_cand = inside & (a2g_max < rpn_negative_overlap) & ~fg_mask
    bg_quota = rpn_batch_size_per_im - fg_num \
        if rpn_batch_size_per_im > 0 else A
    bg_mask, bg_num = _sample_mask(bg_cand, bg_quota, k2)
    labels = jnp.full((A,), -1, jnp.int32)
    labels = jnp.where(bg_mask, 0, labels)
    labels = jnp.where(fg_mask, 1, labels)
    tgt = _encode_rowwise(gt_boxes[a2g_arg], anchors)
    bbox_targets = jnp.where(fg_mask[:, None], tgt, 0.0)
    inw = jnp.where(fg_mask[:, None],
                    jnp.ones((A, 4), anchors.dtype), 0.0)
    return {"labels": labels, "bbox_targets": bbox_targets,
            "bbox_inside_weight": inw, "fg_num": fg_num, "bg_num": bg_num}


def retinanet_target_assign(anchors, gt_boxes, gt_labels, is_crowd, im_info,
                            gt_count=None, positive_overlap=0.5,
                            negative_overlap=0.4):
    """rpn_target_assign_op.cc:612 variant: every non-ignored anchor is
    used (no sampling), fg labels carry the gt CLASS (1-based), and the
    fg count is returned for focal-loss normalization."""
    jnp = _jnp()
    A = anchors.shape[0]
    G = gt_boxes.shape[0]
    gvalid = jnp.arange(G) < (G if gt_count is None else gt_count)
    gvalid = gvalid & (jnp.asarray(is_crowd) == 0)
    iou = iou_matrix(anchors, gt_boxes, normalized=True)
    iou = jnp.where(gvalid[None, :], iou, -1.0)
    a2g_max = iou.max(axis=1)
    a2g_arg = iou.argmax(axis=1)
    g2a_max = iou.max(axis=0)
    is_best = ((jnp.abs(iou - g2a_max[None, :]) < 1e-5)
               & gvalid[None, :] & (iou > 0)).any(axis=1)
    fg = is_best | (a2g_max >= positive_overlap)
    bg = ~fg & (a2g_max < negative_overlap) & (a2g_max >= 0)
    labels = jnp.full((A,), -1, jnp.int32)
    labels = jnp.where(bg, 0, labels)
    labels = jnp.where(fg, jnp.asarray(gt_labels, jnp.int32)[a2g_arg],
                       labels)
    tgt = _encode_rowwise(gt_boxes[a2g_arg], anchors)
    bbox_targets = jnp.where(fg[:, None], tgt, 0.0)
    inw = jnp.where(fg[:, None], jnp.ones((A, 4), anchors.dtype), 0.0)
    return {"labels": labels, "bbox_targets": bbox_targets,
            "bbox_inside_weight": inw,
            "fg_num": fg.sum().astype(jnp.int32)}


def generate_proposal_labels(rois, roi_count, gt_classes, is_crowd, gt_boxes,
                             im_scale, gt_count=None,
                             batch_size_per_im=512, fg_fraction=0.25,
                             fg_thresh=0.5, bg_thresh_hi=0.5,
                             bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=81, use_gt_as_rois=True, key=None,
                             is_cls_agnostic=False):
    """Single image RoI-head sampling (generate_proposal_labels_op.cc:43).

    rois [R,4] zero-padded with roi_count valid; gt_boxes [G,4] at the
    ORIGINAL scale (scaled by im_scale internally, reference parity);
    gt_classes [G] int (1..class_nums-1). Returns dict of STATIC shapes:
      rois            [B,4]   sampled boxes (B = batch_size_per_im)
      labels_int32    [B]     class id, 0 = background, -1 = pad
      bbox_targets    [B, 4*class_nums] encoded targets in the label's slot
      bbox_inside_weights / bbox_outside_weights same shape
      fg_num, valid_num scalars
    """
    import jax

    jnp = _jnp()
    R, G = rois.shape[0], gt_boxes.shape[0]
    B = int(batch_size_per_im)
    gvalid = jnp.arange(G) < (G if gt_count is None else gt_count)
    # zero-padded gt rows must never match anything: a [0,0,0,0] box has
    # area 1 under the +1-pixel convention and would self-match its own
    # appended roi with IoU 1.0, fabricating foreground samples
    nonzero = ((gt_boxes[:, 2] > gt_boxes[:, 0])
               & (gt_boxes[:, 3] > gt_boxes[:, 1]))
    not_crowd = gvalid & nonzero & (jnp.asarray(is_crowd) == 0)
    gt_scaled = gt_boxes * im_scale
    # candidate set: proposals (+ gt boxes themselves, reference appends)
    if use_gt_as_rois:
        allb = jnp.concatenate([rois, gt_scaled], axis=0)
        bvalid = jnp.concatenate(
            [jnp.arange(R) < roi_count, not_crowd], axis=0)
    else:
        allb = rois
        bvalid = jnp.arange(R) < roi_count
    N = allb.shape[0]
    if N < B:  # fewer candidates than the sampling budget: pad invalid
        pad = B - N
        allb = jnp.concatenate(
            [allb, jnp.zeros((pad, 4), allb.dtype)], axis=0)
        bvalid = jnp.concatenate(
            [bvalid, jnp.zeros((pad,), bool)], axis=0)
        N = B
    iou = iou_matrix(allb, gt_scaled, normalized=False)
    iou = jnp.where(not_crowd[None, :], iou, -1.0)
    iou = jnp.where(bvalid[:, None], iou, -1.0)
    b2g_max = iou.max(axis=1)
    b2g_arg = iou.argmax(axis=1)
    fg_cand = bvalid & (b2g_max >= fg_thresh)
    bg_cand = bvalid & (b2g_max < bg_thresh_hi) & (b2g_max >= bg_thresh_lo)
    k1 = k2 = None
    if key is not None:
        k1, k2 = jax.random.split(key)
    fg_quota = int(np.round(fg_fraction * B))
    fg_mask, fg_num = _sample_mask(fg_cand, fg_quota, k1)
    bg_mask, bg_num = _sample_mask(bg_cand, B - fg_num, k2)
    # order: all fg rows first, then bg (reference concatenates) — build
    # a static gather: rank fg rows 0..fg_num-1, bg rows fg_num..
    skey = jnp.where(fg_mask, 2.0, jnp.where(bg_mask, 1.0, 0.0))
    tie = _rand_keys(None, (N,)) * 1e-9  # stable by index
    order = jnp.argsort(-(skey + tie))
    sel = order[:B]
    sel_fg = fg_mask[sel]
    sel_valid = (fg_mask | bg_mask)[sel]
    out_rois = jnp.where(sel_valid[:, None], allb[sel], 0.0)
    glab = jnp.asarray(gt_classes, jnp.int32)[b2g_arg[sel]]
    labels = jnp.where(sel_fg, glab, jnp.where(sel_valid, 0, -1))
    w = jnp.asarray(bbox_reg_weights, allb.dtype)
    tgt = _encode_rowwise(gt_scaled[b2g_arg[sel]], allb[sel],
                          weights=w)
    # scatter each fg target into its class slot of [B, 4*class_nums]
    C = 1 if is_cls_agnostic else int(class_nums)
    fg_cls = jnp.ones_like(glab) if is_cls_agnostic else glab
    cls = jnp.where(sel_fg, fg_cls, 0)
    bt = jnp.zeros((B, C, 4), allb.dtype)
    rowi = jnp.arange(B)
    bt = bt.at[rowi, jnp.clip(cls, 0, C - 1)].set(
        jnp.where(sel_fg[:, None], tgt, 0.0))
    bt = bt * (cls > 0)[:, None, None]
    inw = jnp.zeros((B, C, 4), allb.dtype).at[
        rowi, jnp.clip(cls, 0, C - 1)].set(
        jnp.where(sel_fg[:, None], 1.0, 0.0)) * (cls > 0)[:, None, None]
    return {"rois": out_rois,
            "labels_int32": labels,
            "bbox_targets": bt.reshape(B, C * 4),
            "bbox_inside_weights": inw.reshape(B, C * 4),
            "bbox_outside_weights": inw.reshape(B, C * 4),
            "fg_num": fg_num, "valid_num": fg_num + bg_num,
            "gt_index": b2g_arg[sel]}


def generate_mask_labels(gt_masks, sampled_rois, sampled_labels, gt_index,
                         resolution=14, num_classes=81):
    """Mask-head targets (generate_mask_labels_op.cc capability, bitmask
    form). gt_masks [G,H,W] {0,1} at the roi coordinate scale;
    sampled_rois [B,4] + sampled_labels [B] + gt_index [B] from
    generate_proposal_labels. Returns mask_targets
    [B, resolution, resolution] in {0,1} (-1 on non-fg rows) — crop each
    roi from its matched gt bitmask with nearest-neighbor sampling at
    bin centers (binary targets make interpolation moot; COCO polygon
    decoding belongs to the data pipeline, not the graph)."""
    jnp = _jnp()
    B = sampled_rois.shape[0]
    res = int(resolution)
    x1, y1, x2, y2 = (sampled_rois[:, i] for i in range(4))
    # sample grid over each roi
    t = (jnp.arange(res) + 0.5) / res
    gx = x1[:, None] + t[None, :] * (x2 - x1 + 1)[:, None]  # [B,res]
    gy = y1[:, None] + t[None, :] * (y2 - y1 + 1)[:, None]
    masks = jnp.asarray(gt_masks)[jnp.asarray(gt_index)]  # [B,H,W]
    H, W = masks.shape[1], masks.shape[2]
    xi = jnp.clip(gx.astype(jnp.int32), 0, W - 1)
    yi = jnp.clip(gy.astype(jnp.int32), 0, H - 1)
    out = masks[jnp.arange(B)[:, None, None], yi[:, :, None],
                xi[:, None, :]]
    fg = jnp.asarray(sampled_labels) > 0
    return jnp.where(fg[:, None, None], out.astype(jnp.float32), -1.0)


def distribute_fpn_proposals(rois, roi_count, min_level=2, max_level=5,
                             refer_level=4, refer_scale=224):
    """distribute_fpn_proposals_op.cc:24: route each roi to an FPN level
    by sqrt(area). rois [R,4] + count. Returns (per-level list of
    ([R,4] zero-padded rois, mask [R]), restore_index [R] int32): level
    buffers keep the ORIGINAL row order compacted to the front, and
    restore_index maps concat(level outputs) rows back to input order."""
    jnp = _jnp()
    R = rois.shape[0]
    valid = jnp.arange(R) < roi_count
    w = rois[:, 2] - rois[:, 0]
    h = rois[:, 3] - rois[:, 1]
    scale = jnp.sqrt(jnp.clip(w, 0, None) * jnp.clip(h, 0, None))
    lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-6)) + refer_level
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    lvl = jnp.where(valid, lvl, max_level + 1)  # pads route nowhere
    outs = []
    offsets = jnp.zeros((), jnp.int32)
    # sentinel R = "routed nowhere": out-of-bounds scatters get dropped,
    # so padded rows can never clobber concat position 0
    pos_in_out = jnp.full((R,), R, jnp.int32)
    for level in range(min_level, max_level + 1):
        m = lvl == level
        # compact this level's rois to the buffer front, original order
        rank = jnp.cumsum(m.astype(jnp.int32)) - 1
        cnt = m.sum().astype(jnp.int32)
        buf = jnp.zeros((R, 4), rois.dtype)
        buf = buf.at[jnp.where(m, rank, R)].set(
            jnp.where(m[:, None], rois, 0.0), mode="drop")
        # rows that routed here sit at concat offset + rank
        pos_in_out = jnp.where(m, offsets + rank, pos_in_out)
        offsets = offsets + cnt
        outs.append((buf, m, cnt))
    restore = jnp.zeros((R,), jnp.int32).at[pos_in_out].set(
        jnp.arange(R, dtype=jnp.int32), mode="drop")
    # restore_index[j] = original row of concat-row j (reference contract)
    nvalid = jnp.asarray(roi_count, jnp.int32)
    return outs, jnp.where(jnp.arange(R) < nvalid, restore, -1)


def collect_fpn_proposals(multi_rois, multi_scores, counts,
                          post_nms_top_n=1000):
    """collect_fpn_proposals_op.cc:29: concat per-level (rois, scores),
    keep global top post_nms_top_n by score. Each entry [Ri,4]/[Ri] with
    counts[i] valid. Returns (rois [K,4], scores [K], num_valid)."""
    jnp = _jnp()
    rois = jnp.concatenate(multi_rois, axis=0)
    scores = jnp.concatenate(multi_scores, axis=0)
    valids = jnp.concatenate([
        jnp.arange(r.shape[0]) < c
        for r, c in zip(multi_rois, counts)], axis=0)
    K = int(post_nms_top_n)
    sc = jnp.where(valids, scores, -jnp.inf)
    top = jnp.argsort(-sc)[:K]
    ok = sc[top] > -jnp.inf
    return (jnp.where(ok[:, None], rois[top], 0.0),
            jnp.where(ok, scores[top], 0.0),
            ok.sum().astype(jnp.int32))


def target_assign(x, match_indices, mismatch_value=0.0, x_count=None):
    """target_assign_op.cc:24 (batched, static): x [B, M, K] candidate
    rows (gt boxes / labels), match_indices [B, P] int (-1 = no match).
    out[b,p] = x[b, match[b,p]] when matched else mismatch_value;
    weight 1/0 alike. x_count [B] masks padded gt rows to mismatch."""
    jnp = _jnp()
    x = jnp.asarray(x)
    mi = jnp.asarray(match_indices)
    B, M = x.shape[0], x.shape[1]
    matched = mi >= 0
    if x_count is not None:
        matched = matched & (mi < jnp.asarray(x_count)[:, None])
    sel = jnp.clip(mi, 0, M - 1)
    out = x[jnp.arange(B)[:, None], sel]
    out = jnp.where(matched[..., None] if out.ndim == 3 else matched,
                    out, mismatch_value)
    wt = matched.astype(jnp.float32)
    return out, wt


def mine_hard_examples(cls_loss, match_indices, match_dist, loc_loss=None,
                       neg_pos_ratio=3.0, neg_dist_threshold=0.5,
                       sample_size=0, mining_type="max_negative"):
    """mine_hard_examples_op.cc:268 (max_negative mining, static masks).

    cls_loss [B,P], match_indices [B,P] (-1 = unmatched), match_dist
    [B,P]. Negative candidates are unmatched priors with dist <
    neg_dist_threshold; keep top (neg_pos_ratio * num_pos) (or
    sample_size for hard_example mining) by loss. Returns
    (neg_mask [B,P] bool, updated_match_indices [B,P]) where non-selected
    negatives stay -1 and positives keep their match."""
    jnp = _jnp()
    cl = jnp.asarray(cls_loss)
    if loc_loss is not None and mining_type == "hard_example":
        cl = cl + jnp.asarray(loc_loss)
    mi = jnp.asarray(match_indices)
    md = jnp.asarray(match_dist)
    B, P = cl.shape
    pos = mi >= 0
    neg_cand = (~pos) & (md < neg_dist_threshold)
    if mining_type == "hard_example" and sample_size > 0:
        quota = jnp.full((B,), int(sample_size), jnp.int32)
    else:
        quota = jnp.ceil(
            pos.sum(axis=1).astype(jnp.float32) * neg_pos_ratio
        ).astype(jnp.int32)
    quota = jnp.minimum(quota, neg_cand.sum(axis=1).astype(jnp.int32))
    loss_k = jnp.where(neg_cand, cl, -jnp.inf)
    order = jnp.argsort(-loss_k, axis=1)
    rank = jnp.zeros((B, P), jnp.int32)
    rank = rank.at[jnp.arange(B)[:, None], order].set(
        jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32), (B, P)))
    neg_mask = neg_cand & (rank < quota[:, None])
    return neg_mask, jnp.where(pos, mi, -1)


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction"):
    """SSD multibox loss, fully fused (reference
    python/paddle/fluid/layers/detection.py ssd_loss composition over
    target_assign/mine_hard_examples; one jittable op here).

    location [B,P,4] predicted deltas, confidence [B,P,C] logits,
    gt_box [B,G,4] zero-padded, gt_label [B,G] int (rows beyond the real
    gt count must be zero-area boxes), prior_box [P,4]. Differentiable
    wrt location/confidence; matching is stop-gradient.
    """
    import jax

    from .detection import bipartite_match

    jnp = _jnp()
    B, P, C = confidence.shape
    loc = location
    conf = confidence
    gt_box = jax.lax.stop_gradient(jnp.asarray(gt_box))
    prior = jnp.asarray(prior_box)
    gvalid = ((gt_box[..., 2] > gt_box[..., 0])
              & (gt_box[..., 3] > gt_box[..., 1]))  # [B,G]

    if prior_box_var is None:
        var = jnp.ones((4,), jnp.float32)
    else:
        # a 4-vector broadcasts to all priors; a [P,4] tensor stays
        # per-prior (the reference PriorBoxVar input form)
        var = jnp.asarray(prior_box_var, jnp.float32).reshape(-1, 4)
        if var.shape[0] == 1:
            var = var[0]

    def one(loc_b, conf_b, gtb, gtl, gv):
        iou = iou_matrix(gtb, prior, normalized=True)       # [G,P]
        iou = jnp.where(gv[:, None], iou, -1.0)
        match, mdist = bipartite_match(iou)
        if match_type == "per_prediction":
            best = iou.max(axis=0)
            arg = iou.argmax(axis=0)
            extra = (match < 0) & (best >= overlap_threshold)
            match = jnp.where(extra, arg.astype(jnp.int32), match)
            mdist = jnp.where(extra, best, mdist)
        pos = match >= 0
        sel = jnp.clip(match, 0, gtb.shape[0] - 1)
        tgt = _encode_rowwise(gtb[sel], prior, weights=var)
        lbl = jnp.where(pos, jnp.asarray(gtl, jnp.int32)[sel],
                        background_label)
        lp = jax.nn.log_softmax(conf_b.astype(jnp.float32), -1)
        conf_loss = -jnp.take_along_axis(lp, lbl[:, None], 1)[:, 0]
        # hard negative mining on the conf loss
        neg_cand = (~pos) & (iou.max(axis=0) < neg_overlap)
        quota = jnp.minimum(
            jnp.ceil(pos.sum() * neg_pos_ratio).astype(jnp.int32),
            neg_cand.sum().astype(jnp.int32))
        lk = jnp.where(neg_cand, jax.lax.stop_gradient(conf_loss),
                       -jnp.inf)
        order = jnp.argsort(-lk)
        rank = jnp.zeros((P,), jnp.int32).at[order].set(
            jnp.arange(P, dtype=jnp.int32))
        neg = neg_cand & (rank < quota)
        diff = (loc_b - tgt).astype(jnp.float32)
        ad = jnp.abs(diff)
        sl1 = jnp.where(ad < 1.0, 0.5 * diff * diff, ad - 0.5).sum(-1)
        denom = jnp.maximum(pos.sum().astype(jnp.float32), 1.0)
        return (loc_loss_weight * (sl1 * pos).sum()
                + conf_loss_weight * (conf_loss * (pos | neg)).sum()
                ) / denom

    losses = jax.vmap(one)(loc, conf, gt_box,
                           jnp.asarray(gt_label), gvalid)
    return losses.mean().reshape((1,)).astype(location.dtype)


def matrix_nms(bboxes, scores, score_threshold=0.05, post_threshold=0.0,
               nms_top_k=400, keep_top_k=100, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True):
    """matrix_nms_op.cc:87: parallel soft suppression — no sequential
    greedy loop, the decay of box j is min_i f(iou_ij)/f(iou_max_i) over
    higher-scored same-class boxes i. O(k^2) matrix math, MXU/VPU
    friendly, zero lax.fori_loop. bboxes [N,4], scores [C,N].
    Returns (out [keep_top_k,6] rows [label,score,x1,y1,x2,y2] padded
    -1, index [keep_top_k] into N, num_valid)."""
    jnp = _jnp()
    C, N = scores.shape
    k = min(int(nms_top_k), N)
    rows = []
    idxs = []
    for c in range(C):
        if c == background_label:
            continue
        sc = scores[c]
        ok = sc > score_threshold
        sck = jnp.where(ok, sc, -jnp.inf)
        top = jnp.argsort(-sck)[:k]
        svalid = jnp.isfinite(sck[top])
        b = bboxes[top]
        iou = iou_matrix(b, b, normalized)
        upper = jnp.tril(jnp.ones((k, k), bool), -1).T  # i < j pairs
        iou_u = jnp.where(upper & svalid[:, None] & svalid[None, :],
                          iou, 0.0)
        # compensate_iou[i]: how much suppressor i is itself suppressed
        # by anything scored above it (SOLOv2 matrix-NMS: decay of j is
        # min_i f(iou_ij)/f(compensate_i) over higher-scored i)
        comp = iou_u.max(axis=0)[:, None]
        if use_gaussian:
            decay = jnp.exp(-(iou_u ** 2 - comp ** 2) / gaussian_sigma)
        else:
            decay = (1.0 - iou_u) / jnp.maximum(1.0 - comp, 1e-10)
        decay = jnp.where(upper, decay, jnp.inf).min(axis=0)
        decay = jnp.where(jnp.isinf(decay), 1.0, decay)
        newsc = jnp.where(svalid, sc[top] * decay, -1.0)
        if post_threshold > 0:
            newsc = jnp.where(newsc >= post_threshold, newsc, -1.0)
        rows.append(jnp.concatenate([
            jnp.full((k, 1), c, jnp.float32),
            newsc[:, None].astype(jnp.float32),
            b.astype(jnp.float32)], axis=1))
        idxs.append(top.astype(jnp.int32))
    if not rows:
        return (jnp.full((keep_top_k, 6), -1.0, jnp.float32),
                jnp.full((keep_top_k,), -1, jnp.int32),
                jnp.zeros((), jnp.int32))
    allrows = jnp.concatenate(rows, axis=0)
    allidx = jnp.concatenate(idxs, axis=0)
    keyv = jnp.where(allrows[:, 1] > 0, allrows[:, 1], -jnp.inf)
    K = int(keep_top_k)
    top = jnp.argsort(-keyv)[:K]          # length T = min(total, K)
    ok_t = jnp.isfinite(keyv[top])
    out_t = jnp.where(ok_t[:, None], allrows[top], -1.0)
    idx_t = jnp.where(ok_t, allidx[top], -1)
    pad = K - out_t.shape[0]              # total rows may be < K
    if pad > 0:
        out_t = jnp.concatenate(
            [out_t, jnp.full((pad, 6), -1.0, jnp.float32)], axis=0)
        idx_t = jnp.concatenate(
            [idx_t, jnp.full((pad,), -1, idx_t.dtype)], axis=0)
        ok_t = jnp.concatenate([ok_t, jnp.zeros((pad,), bool)], axis=0)
    return out_t, idx_t.astype(jnp.int32), ok_t.sum().astype(jnp.int32)
