"""Sequence (LoD) kernels over the padded+lengths canonical form.

Reference parity: paddle/fluid/operators/sequence_ops/ (~30 ops, 6.1k LoC
over packed LoD storage). TPU-native design: every kernel here takes a
dense padded array x[B, T, ...] plus lengths[B] (int32) and computes with
masks — static shapes throughout, so the whole family jits onto the MXU/VPU
with no host round-trips (SURVEY.md §7 hard part 1: LoD at the edges,
segment/mask ops inside).
"""
from __future__ import annotations

import functools


def _jnp():
    import jax.numpy as jnp

    return jnp


def seq_mask(lengths, maxlen, dtype=None):
    """[B] lengths -> [B, maxlen] validity mask (sequence_mask_op.cc)."""
    jnp = _jnp()
    m = jnp.arange(maxlen)[None, :] < jnp.reshape(lengths, (-1, 1))
    return m.astype(dtype) if dtype is not None else m


def _expand_mask(mask, x):
    """[B,T] mask broadcast over x[B,T,...] trailing dims."""
    return mask.reshape(mask.shape + (1,) * (x.ndim - 2))


def sequence_pool(x, lengths, pool_type="sum", pad_value=0.0):
    """sequence_pool_op.cc: SUM/AVERAGE/SQRT/MAX/MIN/LAST/FIRST over each
    row's valid prefix. Empty rows produce pad_value."""
    jnp = _jnp()
    T = x.shape[1]
    mask = seq_mask(lengths, T)
    fmask = _expand_mask(mask, x).astype(x.dtype)
    pt = pool_type.lower()
    is_float = jnp.issubdtype(x.dtype, jnp.floating)
    div_dtype = x.dtype if is_float else jnp.float32
    lens = jnp.maximum(jnp.reshape(lengths, (-1,)), 1)
    lens = lens.reshape((-1,) + (1,) * (x.ndim - 2)).astype(div_dtype)
    if pt == "sum":
        out = (x * fmask).sum(axis=1)
    elif pt == "average":
        out = ((x * fmask).sum(axis=1).astype(div_dtype) /
               lens).astype(x.dtype)
    elif pt == "sqrt":
        out = ((x * fmask).sum(axis=1).astype(div_dtype) /
               jnp.sqrt(lens)).astype(x.dtype)
    elif pt in ("max", "min"):
        info = jnp.finfo(x.dtype) if is_float else jnp.iinfo(x.dtype)
        fill = jnp.asarray(info.min if pt == "max" else info.max, x.dtype)
        masked = jnp.where(_expand_mask(mask, x), x, fill)
        out = masked.max(axis=1) if pt == "max" else masked.min(axis=1)
    elif pt == "last":
        idx = jnp.maximum(jnp.reshape(lengths, (-1,)) - 1, 0)
        out = jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)).astype(jnp.int32),
            axis=1)[:, 0]
    elif pt == "first":
        out = x[:, 0]
    else:
        raise ValueError(f"unknown pool_type {pool_type!r}")
    empty = (jnp.reshape(lengths, (-1,)) == 0)
    empty = empty.reshape((-1,) + (1,) * (out.ndim - 1))
    return jnp.where(empty, jnp.asarray(pad_value, out.dtype), out)


def sequence_softmax(x, lengths):
    """softmax within each row's valid prefix; padding -> 0
    (sequence_softmax_op.cc)."""
    jnp = _jnp()
    mask = seq_mask(lengths, x.shape[1])
    mask = _expand_mask(mask, x)
    neg = jnp.asarray(jnp.finfo(x.dtype).min, x.dtype)
    z = jnp.where(mask, x, neg)
    z = z - z.max(axis=1, keepdims=True)
    e = jnp.exp(z) * mask.astype(x.dtype)
    return e / jnp.maximum(e.sum(axis=1, keepdims=True), 1e-12)


def sequence_expand_as(x, y, y_lengths):
    """sequence_expand(_as)_op.h, static-shape case: x [B, D] (or
    [B, 1, D] — one step per sequence) broadcast to y's padded time axis,
    masked by y's lengths. The general ragged repeat (multi-step x rows)
    is rejected at the lowering (fluid/lowering_seq.py sequence_expand)."""
    jnp = _jnp()
    if x.ndim >= 3 and x.shape[1] == 1:
        x = x[:, 0]
    T = y.shape[1]
    out = jnp.broadcast_to(x[:, None], (x.shape[0], T) + x.shape[1:])
    m = _expand_mask(seq_mask(y_lengths, T), out).astype(out.dtype)
    return out * m


def sequence_conv(x, lengths, filt, context_length, context_start=None,
                  bias=None):
    """sequence_conv_op: per-step context window [t+start, t+start+len)
    gathered with zeros outside the row's valid range, then matmul with
    filt [context_length*D, M] (im2col+gemm in the reference,
    math/context_project.h)."""
    jnp = _jnp()
    if context_start is None:
        context_start = -(context_length - 1) // 2 if context_length % 2 else \
            -(context_length // 2)
    B, T, D = x.shape
    mask = seq_mask(lengths, T)
    cols = []
    for k in range(context_length):
        off = context_start + k
        shifted = jnp.roll(x, -off, axis=1)
        pos = jnp.arange(T) + off
        valid = (pos >= 0)[None, :] & (pos[None, :] <
                                       jnp.reshape(lengths, (-1, 1)))
        cols.append(shifted * valid[:, :, None].astype(x.dtype))
    ctx = jnp.concatenate(cols, axis=-1)  # [B, T, ctx_len*D]
    out = ctx @ filt
    if bias is not None:
        out = out + bias
    return out * mask[:, :, None].astype(out.dtype)


def sequence_reverse(x, lengths):
    """reverse each row's valid prefix in place; padding stays put
    (sequence_reverse_op.h)."""
    jnp = _jnp()
    T = x.shape[1]
    t = jnp.arange(T)[None, :]
    lens = jnp.reshape(lengths, (-1, 1))
    idx = jnp.where(t < lens, lens - 1 - t, t).astype(jnp.int32)
    return jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)


def sequence_slice(x, lengths, offset, length):
    """sequence_slice_op.h: per-row subsequence [offset, offset+length).
    The reference enforce-fails when offset+length exceeds the row length;
    inside jit that is not expressible, so the request is clamped to the
    valid range instead (never reads padding as data)."""
    jnp = _jnp()
    T = x.shape[1]
    rows = jnp.reshape(lengths, (-1, 1)).astype(jnp.int32)
    off = jnp.clip(jnp.reshape(offset, (-1, 1)).astype(jnp.int32), 0, rows)
    ln = jnp.clip(jnp.reshape(length, (-1, 1)).astype(jnp.int32),
                  0, rows - off)
    t = jnp.arange(T, dtype=jnp.int32)[None, :]
    src = jnp.clip(off + t, 0, T - 1)
    out = jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=1)
    m = (t < ln)
    return out * _expand_mask(m, out).astype(out.dtype), ln[:, 0]


def sequence_concat(xs, lens_list):
    """sequence_concat_op.h: concatenate along time per row, re-packing so
    row b is x1[b,:l1] ++ x2[b,:l2] ++ ... Output T = sum of input Ts."""
    jnp = _jnp()
    B = xs[0].shape[0]
    T_out = sum(x.shape[1] for x in xs)
    tail = xs[0].shape[2:]
    out = jnp.zeros((B, T_out) + tail, xs[0].dtype)
    out_lens = jnp.zeros((B,), jnp.int32)
    batch = jnp.arange(B, dtype=jnp.int32)[:, None]
    for x, lens in zip(xs, lens_list):
        T = x.shape[1]
        lens = jnp.reshape(lens, (-1,)).astype(jnp.int32)
        t = jnp.arange(T, dtype=jnp.int32)[None, :]
        valid = t < lens[:, None]
        dst = jnp.where(valid, out_lens[:, None] + t, T_out - 1)
        contrib = x * _expand_mask(valid, x).astype(x.dtype)
        out = out.at[batch, dst].add(
            jnp.where(_expand_mask(valid, x), contrib, 0))
        out_lens = out_lens + lens
    return out, out_lens


def sequence_reshape(x, lengths, new_dim):
    """sequence_reshape_op.h: refold the feature dim; row lengths scale by
    D/new_dim. Works on the padded form because each row's valid data is a
    contiguous prefix."""
    B, T, D = x.shape
    if (T * D) % new_dim:
        raise ValueError(f"cannot reshape T*D={T * D} to new_dim={new_dim}")
    jnp = _jnp()
    out = x.reshape(B, T * D // new_dim, new_dim)
    new_lens = (jnp.reshape(lengths, (-1,)) * D) // new_dim
    return out, new_lens.astype(jnp.int32)


def sequence_enumerate(ids, lengths, win_size, pad_value=0):
    """sequence_enumerate_op.h: sliding windows of ids; positions past the
    row end filled with pad_value. ids [B, T] -> [B, T, win_size]."""
    jnp = _jnp()
    B, T = ids.shape[:2]
    base = ids.reshape(B, T)
    t = jnp.arange(T)[None, :, None]
    k = jnp.arange(win_size)[None, None, :]
    pos = t + k
    lens = jnp.reshape(lengths, (-1, 1, 1))
    src = jnp.clip(pos, 0, T - 1).astype(jnp.int32)
    win = jnp.take_along_axis(base[:, :, None], src, axis=1)
    win = jnp.where(pos < lens, win, jnp.asarray(pad_value, base.dtype))
    mask = (t < lens)[..., 0]
    return win * mask[:, :, None].astype(win.dtype)


def sequence_pad(x, lengths, pad_value=0.0, padded_length=None):
    """sequence_pad_op: canonical form is already padded — normalize the
    padding region to pad_value and emit Length (the reference's outputs)."""
    jnp = _jnp()
    T = x.shape[1]
    if padded_length is not None and padded_length != T:
        if padded_length < T:
            x = x[:, :padded_length]
            T = padded_length
        else:
            pad = [(0, 0), (0, padded_length - T)] + \
                [(0, 0)] * (x.ndim - 2)
            x = jnp.pad(x, pad)
            T = padded_length
    m = _expand_mask(seq_mask(lengths, T), x)
    return jnp.where(m, x, jnp.asarray(pad_value, x.dtype))


def sequence_unpad(x, lengths):
    """sequence_unpad_op: dense padded -> sequence form. In the canonical
    representation this zeroes the pad region and attaches lengths."""
    jnp = _jnp()
    m = _expand_mask(seq_mask(lengths, x.shape[1]), x)
    return x * m.astype(x.dtype), jnp.reshape(lengths, (-1,)).astype(jnp.int32)


def sequence_scatter(x, ids, updates, upd_lengths):
    """sequence_scatter_op.h: per row b, x[b, ids[b, j]] += updates[b, j]
    for j < upd_lengths[b]."""
    jnp = _jnp()
    B = x.shape[0]
    J = ids.shape[1]
    valid = seq_mask(upd_lengths, J)
    upd = updates * _expand_mask(valid, updates).astype(updates.dtype)
    idx = jnp.where(valid, ids.reshape(B, J), 0).astype(jnp.int32)
    batch = jnp.arange(B, dtype=jnp.int32)[:, None]
    safe_upd = jnp.where(_expand_mask(valid, upd), upd, 0)
    return x.at[batch, idx].add(safe_upd)


# ---------------- recurrent sequence kernels ----------------

def _act(name):
    import jax

    jnp = _jnp()
    return {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
            "relu": jax.nn.relu, "identity": lambda v: v}[name]


def dynamic_lstm(x, lengths, weight, bias, h0=None, c0=None,
                 use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh"):
    """dynamic_lstm over padded input x[B, T, 4D] (already projected, the
    reference's op contract: user runs fc(size=4D) first — lstm_op.cc).

    Gate memory layout matches math/detail/lstm_kernel.h:25 (order c~, i,
    f, o): state = act(c~)*i + prev*f, with peephole terms checkI/F on the
    prev state and checkO on the new state. Bias is [1, 4D] or [1, 7D] with
    peepholes. State carries are frozen past each row's length (LoD batch
    semantics: shorter rows simply stop updating)."""
    import jax

    jnp = _jnp()
    B, T, D4 = x.shape
    D = D4 // 4
    act_g = _act(gate_activation)
    act_c = _act(cell_activation)
    act_cand = _act(candidate_activation)
    h = h0 if h0 is not None else jnp.zeros((B, D), x.dtype)
    c = c0 if c0 is not None else jnp.zeros((B, D), x.dtype)
    b_gate = bias[:, :D4] if bias is not None else 0.0
    if use_peepholes:
        checkI = bias[:, D4:D4 + D]
        checkF = bias[:, D4 + D:D4 + 2 * D]
        checkO = bias[:, D4 + 2 * D:D4 + 3 * D]
    lens = jnp.reshape(lengths, (-1, 1))

    xs = jnp.moveaxis(x, 1, 0)  # [T, B, 4D]
    ts = jnp.arange(T)
    if is_reverse:
        # process each row's valid prefix reversed: index len-1-t (held at
        # t for padding); equivalent to reversing valid data, scanning,
        # reversing back
        xs = jnp.moveaxis(sequence_reverse(x, lengths), 1, 0)

    def step(carry, inp):
        h_prev, c_prev = carry
        xt, t = inp
        g = xt + h_prev @ weight + b_gate
        cand, ig, fg, og = (g[:, :D], g[:, D:2 * D], g[:, 2 * D:3 * D],
                            g[:, 3 * D:])
        if use_peepholes:
            ig = ig + c_prev * checkI
            fg = fg + c_prev * checkF
        i = act_g(ig)
        f = act_g(fg)
        c_new = act_cand(cand) * i + c_prev * f
        if use_peepholes:
            og = og + c_new * checkO
        o = act_g(og)
        h_new = o * act_c(c_new)
        alive = (t < lens).astype(x.dtype)
        h_out = h_new * alive + h_prev * (1 - alive)
        c_out = c_new * alive + c_prev * (1 - alive)
        return (h_out, c_out), (h_new * alive, c_new * alive)

    (_, _), (hs, cs) = jax.lax.scan(step, (h, c), (xs, ts))
    hs = jnp.moveaxis(hs, 0, 1)
    cs = jnp.moveaxis(cs, 0, 1)
    if is_reverse:
        hs = sequence_reverse(hs, lengths)
        cs = sequence_reverse(cs, lengths)
    return hs, cs


def dynamic_gru(x, lengths, weight, bias=None, h0=None, is_reverse=False,
                gate_activation="sigmoid", candidate_activation="tanh",
                origin_mode=False):
    """dynamic_gru over padded x[B, T, 3D] (projected by fc(size=3D)).

    Matches math/detail/gru_kernel.h: gates [u, r] from weight[:, :2D],
    candidate from (r * h_prev) @ weight[:, 2D:]; output
    h = (1-u)*prev + u*cand (origin_mode=False, gru_kernel.h:66)."""
    import jax

    jnp = _jnp()
    B, T, D3 = x.shape
    D = D3 // 3
    act_g = _act(gate_activation)
    act_c = _act(candidate_activation)
    h = h0 if h0 is not None else jnp.zeros((B, D), x.dtype)
    w_gate = weight[:, :2 * D]
    w_cand = weight[:, 2 * D:]
    b = bias if bias is not None else jnp.zeros((1, D3), x.dtype)
    lens = jnp.reshape(lengths, (-1, 1))
    xs = jnp.moveaxis(sequence_reverse(x, lengths) if is_reverse else x,
                      1, 0)
    ts = jnp.arange(T)

    def step(h_prev, inp):
        xt, t = inp
        gates = xt[:, :2 * D] + b[:, :2 * D] + h_prev @ w_gate
        u = act_g(gates[:, :D])
        r = act_g(gates[:, D:])
        cand = act_c(xt[:, 2 * D:] + b[:, 2 * D:] + (r * h_prev) @ w_cand)
        if origin_mode:
            h_new = u * h_prev + cand - u * cand
        else:
            h_new = h_prev - u * h_prev + u * cand
        alive = (t < lens).astype(x.dtype)
        h_out = h_new * alive + h_prev * (1 - alive)
        return h_out, h_new * alive

    _, hs = jax.lax.scan(step, h, (xs, ts))
    hs = jnp.moveaxis(hs, 0, 1)
    if is_reverse:
        hs = sequence_reverse(hs, lengths)
    return hs
