"""Quantized and gathered matmul primitives for multi-tenant serving.

Two kernel families, both shaped by TPP's low-precision-primitive
argument (PAPERS.md) and dispatched through the same TuningTable
discipline as the attention kernels:

  * **int8 weight matmul** — the large dense weights (QKV / out-proj /
    FFN / embedding-vocab) stored as symmetric per-output-channel int8
    with fp32 scales, dequantized on the way into the MXU:
    ``y = (x @ q) * scale``. The compute dtype is preserved (the
    accumulate runs fp32), so quantization error is the weight-rounding
    error only. On TPU a blocked pallas kernel (block_m x block_n
    tiles, tuned) reads the int8 tiles straight from HBM — 4x less
    weight traffic per step, which is the whole win on a
    bandwidth-bound decode; elsewhere the XLA reference computes the
    identical math.
  * **gathered LoRA matmul** — the per-slot low-rank adapter delta of
    the multi-tenant serving pool: stacked ``A [n_adapters, d_in, r]``
    / ``B [n_adapters, r, d_out]`` banks, per-row adapter ids as a
    traced int32 input (adapter switches never retrace — the page-table
    trick), and the delta for every row computed as ONE batched
    ``(x @ A[ids]) @ B[ids]`` gather-matmul. Row id 0 is the base
    model: its bank rows stay zero, so opted-out requests ride the
    same program with an exactly-zero delta. On TPU the pallas kernel
    scalar-prefetches the ids and dereferences them in the A/B
    BlockSpec index maps (each grid row DMAs only its own adapter's
    bank rows); elsewhere the gathered einsum reference runs — and its
    batch-leading layout is row-invariant on XLA CPU, which is what
    makes pooled adapter decode token-identical to a solo batch-1 run.

The adapter ids + banks reach the Linear layers through a trace-scoped
context (`lora_scope`) rather than threaded signatures: the serving
step bodies receive them as ordinary traced arguments and open the
scope around the functionalized net apply, so the layers below need no
plumbing and the hook costs one dict read when disarmed.
"""
from __future__ import annotations

import contextlib
import threading

import numpy as np

__all__ = ["quantize_int8_weight", "int8_matmul", "int8_matmul_reference",
           "int8_gather", "lora_delta", "lora_delta_reference",
           "lora_scope", "current_lora", "merge_lora_weight"]

_QMAX = 127.0

#: block ladders the int8 matmul kernel tiles from (the TuningTable's
#: candidate sets draw from these; see tuning.autotune)
INT8_BLOCK_M = (256, 128, 64, 32, 16, 8)
INT8_BLOCK_N = (512, 384, 256, 128)


def _jnp():
    import jax.numpy as jnp

    return jnp


# --------------------------------------------------------------------------
# weight quantization (pure jnp; host-side one-shot at engine build)
# --------------------------------------------------------------------------

def quantize_int8_weight(w):
    """Symmetric per-output-channel int8: ``w [..., d_out]`` ->
    ``(q int8, scale f32 [d_out])`` with ``scale = amax(|col|) / 127``
    (1.0 for all-zero columns so dequant never divides by zero) — the
    same amax/127 policy as the paged KV int8 pages, per weight column
    instead of per page."""
    jnp = _jnp()

    w32 = jnp.asarray(w).astype(jnp.float32)
    red = tuple(range(w32.ndim - 1))
    amax = jnp.max(jnp.abs(w32), axis=red)
    scale = jnp.where(amax > 0, amax / _QMAX,
                      jnp.float32(1.0)).astype(jnp.float32)
    q = jnp.clip(jnp.round(w32 / scale), -_QMAX, _QMAX).astype(jnp.int8)
    return q, scale


# --------------------------------------------------------------------------
# int8 matmul: reference, pallas kernel, dispatcher
# --------------------------------------------------------------------------

def int8_matmul_reference(x, q, scale, bias=None):
    """``(x @ q) * scale [+ bias]`` with an fp32 accumulate, cast back
    to x's dtype. Scaling AFTER the matmul keeps the contraction in
    int8-feedable form (the MXU shape TPP argues for); per-output-
    channel scales make the two orders algebraically identical."""
    import jax.numpy as jnp

    acc = jnp.matmul(x.astype(jnp.float32), q.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    acc = acc * scale
    out = acc.astype(x.dtype)
    if bias is not None:
        out = out + bias.astype(out.dtype)
    return out


def _pick_int8_blocks_heuristic(m, n):
    """Hand-picked (block_m, block_n) for the int8 matmul kernel: the
    largest ladder entries that tile the operand — the committed-
    fallback source of truth for the int8_matmul tuning-table entries
    (tuning.autotune.fallback_config mirrors this function)."""
    def _one(s, ladder):
        for b in ladder:
            if s % b == 0:
                return min(b, s)
        return s
    return _one(int(m), INT8_BLOCK_M), _one(int(n), INT8_BLOCK_N)


def _int8_matmul_call(m, d, n, bm, bn, interpret):
    """The blocked int8 matmul kernel: grid (m/bm, n/bn), each step an
    (bm, d) x (d, bn) MXU tile with the int8 weight tile upcast in
    VMEM and the per-column scale applied to the fp32 accumulator."""
    import jax

    from .attention import _import_pallas, _z

    pl = _import_pallas()
    import jax.numpy as jnp

    def kernel(x_ref, q_ref, s_ref, o_ref):
        acc = jnp.dot(x_ref[...].astype(jnp.float32),
                      q_ref[...].astype(jnp.float32),
                      preferred_element_type=jnp.float32)
        o_ref[...] = acc * s_ref[...]

    return pl.pallas_call(
        kernel,
        grid=(m // bm, n // bn),
        in_specs=[
            pl.BlockSpec((bm, d), lambda i, j: (i, _z())),
            pl.BlockSpec((d, bn), lambda i, j: (_z(), j)),
            pl.BlockSpec((1, bn), lambda i, j: (_z(), j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        interpret=interpret)


def _tuned_int8_blocks(m, d, n, dtype, block_m=None, block_n=None):
    """Tuned (block_m, block_n) — explicit overrides win, then the
    table keyed (d bucket, n bucket, dtype), then the heuristic; a
    tuned entry that does not tile THESE dims falls back too (same
    discipline as _pick_blocks)."""
    from .attention import _seq_bucket, _tuned

    if block_m is not None or block_n is not None:
        hb_m, hb_n = _pick_int8_blocks_heuristic(m, n)
        return (min(int(block_m), m) if block_m else hb_m,
                min(int(block_n), n) if block_n else hb_n)
    cfg = _tuned("int8_matmul", (_seq_bucket(d), _seq_bucket(n),
                                 str(dtype)))
    if cfg is not None:
        try:
            bm = min(int(cfg["block_m"]), m)
            bn = min(int(cfg["block_n"]), n)
        except (KeyError, TypeError, ValueError):
            bm = bn = 0
        if bm > 0 and bn > 0 and m % bm == 0 and n % bn == 0:
            return bm, bn
    return _pick_int8_blocks_heuristic(m, n)


def int8_matmul(x, q, scale, bias=None, interpret=False, block_m=None,
                block_n=None):
    """Scaled int8 matmul dispatch: ``x [..., d_in] @ q int8 [d_in,
    d_out] * scale [d_out]``. The blocked pallas kernel on TPU (or
    under interpret=True for CPU parity tests); the XLA reference —
    bit-identical math, fp32 accumulate — elsewhere."""
    import jax.numpy as jnp

    from .attention import _flash_usable, _on_tpu

    d, n = q.shape
    lead = x.shape[:-1]
    m = 1
    for s in lead:
        m *= int(s)
    use_kernel = interpret or (_on_tpu() and _flash_usable()
                               and m >= 8 and n % 128 == 0)
    if use_kernel:
        try:
            bm, bn = _tuned_int8_blocks(m, d, n, x.dtype, block_m,
                                        block_n)
            call = _int8_matmul_call(m, d, n, bm, bn, interpret)
            acc = call(x.reshape(m, d).astype(jnp.float32), q,
                       scale.reshape(1, n))
            out = acc.astype(x.dtype).reshape(lead + (n,))
            if bias is not None:
                out = out + bias.astype(out.dtype)
            return out
        except Exception:
            if interpret:
                raise
    return int8_matmul_reference(x, q, scale, bias)


def int8_gather(ids, q, scale, dtype):
    """Embedding-vocab lookup over an int8 table: gather the id rows
    and apply the per-output-channel scale — the embedding is the
    one-hot matmul special case of `int8_matmul`, and a gather IS its
    int8 kernel (no dequantized [V, D] copy ever materializes)."""
    import jax.numpy as jnp

    rows = jnp.take(q, ids, axis=0).astype(jnp.float32)
    return (rows * scale).astype(dtype)


# --------------------------------------------------------------------------
# gathered LoRA matmul: reference, pallas kernel, dispatcher
# --------------------------------------------------------------------------

def lora_delta_reference(x, A, B, ids):
    """The batched per-row adapter delta: ``(x @ A[ids]) @ B[ids]``,
    fp32 accumulate, cast back to x's dtype. ``x [b, s, d_in]``,
    ``A [n, d_in, r]``, ``B [n, r, d_out]``, ``ids [b] int32``. Row 0
    of the banks is all-zero (the base model), so id 0 contributes an
    exactly-zero delta through the same program."""
    import jax.numpy as jnp

    Ag = jnp.take(A, ids, axis=0)                    # [b, d_in, r]
    Bg = jnp.take(B, ids, axis=0)                    # [b, r, d_out]
    x32 = x.astype(jnp.float32)
    xa = jnp.einsum("bsd,bdr->bsr", x32, Ag.astype(jnp.float32))
    out = jnp.einsum("bsr,bro->bso", xa, Bg.astype(jnp.float32))
    return out.astype(x.dtype)


def _lora_dispatch_heuristic():
    """Hand-picked dispatch config for the gathered LoRA matmul: the
    scalar-prefetch kernel on (TPU only; the CPU fallback is the
    gathered einsum either way). The committed-fallback source of
    truth for the lora_matmul tuning-table entries."""
    return {"kernel": True}


def _lora_gather_call(b, s, d, r, n_out, interpret):
    """The gathered LoRA kernel: grid (b,) with the per-row adapter
    ids scalar-prefetched — each grid row's A/B BlockSpec index maps
    dereference ids[i] to DMA only that adapter's bank rows (the
    paged-decode table trick applied to weight banks)."""
    import jax

    from .attention import _import_pallas, _z

    pl = _import_pallas()
    from jax.experimental.pallas import tpu as pltpu
    import jax.numpy as jnp

    def kernel(ids_ref, x_ref, a_ref, b_ref, o_ref):
        xa = jnp.dot(x_ref[...].astype(jnp.float32),
                     a_ref[...].astype(jnp.float32),
                     preferred_element_type=jnp.float32)
        o_ref[...] = jnp.dot(xa, b_ref[...].astype(jnp.float32),
                             preferred_element_type=jnp.float32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(b,),
        in_specs=[
            pl.BlockSpec((None, s, d), lambda i, ids: (i, _z(), _z())),
            pl.BlockSpec((None, d, r),
                         lambda i, ids: (ids[i], _z(), _z())),
            pl.BlockSpec((None, r, n_out),
                         lambda i, ids: (ids[i], _z(), _z())),
        ],
        out_specs=pl.BlockSpec((None, s, n_out),
                               lambda i, ids: (i, _z(), _z())))
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, s, n_out), jnp.float32),
        interpret=interpret)


def lora_delta(x, A, B, ids, interpret=False):
    """Gathered-LoRA dispatch: the scalar-prefetch pallas kernel on
    TPU (tuned on/off per (d, r, dtype) — or under interpret=True for
    CPU parity tests); the gathered einsum reference elsewhere."""
    import jax.numpy as jnp

    from .attention import (_flash_usable, _on_tpu, _seq_bucket,
                            _tuned)

    b, s, d = x.shape
    _, _, r = A.shape
    n_out = B.shape[-1]
    cfg = _tuned("lora_matmul", (_seq_bucket(d), int(r), str(x.dtype)))
    if cfg is None:
        cfg = _lora_dispatch_heuristic()
    use_kernel = interpret or (
        _on_tpu() and _flash_usable() and r % 8 == 0
        and bool(cfg.get("kernel", True)))
    if use_kernel:
        try:
            out = _lora_gather_call(b, s, d, r, n_out, interpret)(
                jnp.asarray(ids, jnp.int32), x, A, B)
            return out.astype(x.dtype)
        except Exception:
            if interpret:
                raise
    return lora_delta_reference(x, A, B, ids)


def merge_lora_weight(w, wA, wB):
    """``W + A @ B`` — the merged-weight equivalent of the factored
    delta (B pre-scaled by alpha/r, the AdapterPool storage
    convention). The multi-tenant acceptance tests serve the factored
    path and compare against a solo engine running this merge."""
    import jax.numpy as jnp

    w = jnp.asarray(w)
    return (w.astype(jnp.float32) +
            jnp.asarray(wA, jnp.float32) @ jnp.asarray(wB, jnp.float32)
            ).astype(w.dtype)


# --------------------------------------------------------------------------
# the trace-scoped adapter context the serving step bodies open
# --------------------------------------------------------------------------

_LORA_STATE = threading.local()


@contextlib.contextmanager
def lora_scope(ids, banks):
    """Make (per-row adapter ids, [(A, B), ...] banks) visible to the
    Linear layers under this trace scope. `ids` is a traced int32 [b]
    aligned with the batch rows of every Linear input; `banks` is
    indexed by each target layer's installed `_lora_idx`. Re-entrant
    (the previous scope is restored on exit); reading the scope when
    none is open returns None — the zero-cost disarmed path."""
    prev = getattr(_LORA_STATE, "ctx", None)
    _LORA_STATE.ctx = (ids, banks)
    try:
        yield
    finally:
        _LORA_STATE.ctx = prev


def current_lora():
    """The active (ids, banks) pair, or None outside any lora_scope."""
    return getattr(_LORA_STATE, "ctx", None)
