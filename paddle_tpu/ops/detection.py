"""Detection op library (operators/detection/ parity, 17.1k LoC of CUDA
re-designed TPU-first).

Every kernel keeps STATIC shapes — XLA's contract: NMS returns a
fixed-size index buffer padded with -1 plus a valid count (the reference
returns a variable-length LoDTensor; the -1-padded form is the
TPU-native equivalent, like TF's combined_non_max_suppression). Greedy
loops (nms, bipartite match) are lax.fori_loop over masks, not data-
dependent Python.

Key reference files: multiclass_nms_op.cc, roi_align_op.cu, yolo_box_op.h,
prior_box_op.h, box_coder_op.h, iou_similarity_op.h, bipartite_match_op.cc.
"""
from __future__ import annotations

import numpy as np


def _jnp():
    import jax.numpy as jnp

    return jnp


def box_area(boxes):
    return ((boxes[..., 2] - boxes[..., 0]) *
            (boxes[..., 3] - boxes[..., 1]))


def iou_matrix(a, b, normalized=True):
    """Pairwise IoU: a [N,4], b [M,4] -> [N,M] (iou_similarity_op.h)."""
    jnp = _jnp()
    off = 0.0 if normalized else 1.0
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt + off, 0.0, None)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[:, 2] - a[:, 0] + off) * (a[:, 3] - a[:, 1] + off)
    area_b = (b[:, 2] - b[:, 0] + off) * (b[:, 3] - b[:, 1] + off)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def nms(boxes, scores, iou_threshold=0.3, score_threshold=None,
        max_out=None, normalized=True):
    """Greedy hard NMS. Returns (keep_idx [max_out] int32 padded -1,
    num_valid). boxes [N,4], scores [N]."""
    import jax

    jnp = _jnp()
    boxes = jnp.asarray(boxes)
    scores = jnp.asarray(scores)
    n = boxes.shape[0]
    max_out = int(max_out or n)
    if score_threshold is not None:
        valid = scores > score_threshold
    else:
        valid = jnp.ones((n,), bool)
    order = jnp.argsort(-scores)
    ious = iou_matrix(boxes, boxes, normalized)

    def body(i, carry):
        keep_mask, out, count = carry
        cand = order[i]
        ok = keep_mask[cand] & valid[cand] & (count < max_out)
        out = out.at[jnp.clip(count, 0, max_out - 1)].set(
            jnp.where(ok, cand.astype(jnp.int32),
                      out[jnp.clip(count, 0, max_out - 1)]))
        count = count + ok.astype(jnp.int32)
        # suppress every box with IoU > thr against the kept candidate
        sup = ious[cand] > iou_threshold
        keep_mask = jnp.where(ok, keep_mask & ~sup, keep_mask)
        return keep_mask, out, count

    out0 = jnp.full((max_out,), -1, jnp.int32)
    _, out, count = jax.lax.fori_loop(
        0, n, body, (jnp.ones((n,), bool), out0, jnp.zeros((), jnp.int32)))
    return out, count


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=64,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   background_label=0):
    """multiclass_nms_op.cc capability with static output:
    bboxes [N, 4], scores [C, N] -> (out [keep_top_k, 6] rows
    [label, score, x1, y1, x2, y2] padded with -1 labels, num_valid)."""
    import jax

    jnp = _jnp()
    C, N = scores.shape
    per_class = []
    for c in range(C):
        if c == background_label:
            continue
        keep, cnt = nms(bboxes, scores[c], nms_threshold, score_threshold,
                        min(nms_top_k, N), normalized)
        k = keep.shape[0]
        sel = jnp.clip(keep, 0, N - 1)
        valid = (jnp.arange(k) < cnt) & (keep >= 0)
        rows = jnp.concatenate([
            jnp.full((k, 1), c, jnp.float32),
            scores[c][sel][:, None].astype(jnp.float32),
            bboxes[sel].astype(jnp.float32),
        ], axis=1)
        rows = jnp.where(valid[:, None], rows, -1.0)
        per_class.append(rows)
    if not per_class:  # every class was the background label
        return (jnp.full((keep_top_k, 6), -1.0, jnp.float32),
                jnp.zeros((), jnp.int32))
    allrows = jnp.concatenate(per_class, axis=0)
    # keep_top_k by score over all classes
    key = jnp.where(allrows[:, 0] >= 0, allrows[:, 1], -jnp.inf)
    top = jnp.argsort(-key)[:keep_top_k]
    out = allrows[top]
    pad = keep_top_k - out.shape[0]
    if pad > 0:
        out = jnp.concatenate(
            [out, jnp.full((pad, 6), -1.0, jnp.float32)], axis=0)
    num = (out[:, 0] >= 0).sum().astype(jnp.int32)
    return out, num


def box_clip(boxes, im_shape):
    """box_clip_op.h: clip to [0, w-1] x [0, h-1]."""
    jnp = _jnp()
    h, w = im_shape[0], im_shape[1]
    x1 = jnp.clip(boxes[..., 0], 0, w - 1)
    y1 = jnp.clip(boxes[..., 1], 0, h - 1)
    x2 = jnp.clip(boxes[..., 2], 0, w - 1)
    y2 = jnp.clip(boxes[..., 3], 0, h - 1)
    return jnp.stack([x1, y1, x2, y2], axis=-1)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True):
    """box_coder_op.h: encode targets against priors, or decode deltas."""
    jnp = _jnp()
    off = 0.0 if box_normalized else 1.0
    pw = prior_box[:, 2] - prior_box[:, 0] + off
    ph = prior_box[:, 3] - prior_box[:, 1] + off
    pcx = prior_box[:, 0] + pw * 0.5
    pcy = prior_box[:, 1] + ph * 0.5
    if prior_box_var is None:
        var = jnp.ones((1, 4), jnp.float32)
    else:
        var = jnp.asarray(prior_box_var, jnp.float32).reshape(-1, 4) \
            if np.ndim(prior_box_var) == 1 else prior_box_var
    if code_type.lower() in ("encode_center_size", "encode"):
        tw = target_box[:, 2] - target_box[:, 0] + off
        th = target_box[:, 3] - target_box[:, 1] + off
        tcx = target_box[:, 0] + tw * 0.5
        tcy = target_box[:, 1] + th * 0.5
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        dw = jnp.log(jnp.clip(tw[:, None] / pw[None, :], 1e-10, None))
        dh = jnp.log(jnp.clip(th[:, None] / ph[None, :], 1e-10, None))
        out = jnp.stack([dx, dy, dw, dh], axis=-1)
        return out / var[None, :, :]
    # decode: target_box [N, 4] deltas against priors [N, 4]
    d = target_box * var if var.shape[0] != 1 else target_box * var[0]
    cx = d[:, 0] * pw + pcx
    cy = d[:, 1] * ph + pcy
    w = jnp.exp(d[:, 2]) * pw
    h = jnp.exp(d[:, 3]) * ph
    return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                      cx + w * 0.5 - off, cy + h * 0.5 - off], axis=-1)


def prior_box(input_hw, image_hw, min_sizes, max_sizes=None,
              aspect_ratios=(1.0,), variances=(0.1, 0.1, 0.2, 0.2),
              flip=False, clip=False, steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False):
    """prior_box_op.h: SSD prior boxes for one feature map. Returns
    (boxes [H, W, P, 4], variances [H, W, P, 4])."""
    jnp = _jnp()
    H, W = input_hw
    img_h, img_w = image_hw
    step_h = steps[0] or img_h / H
    step_w = steps[1] or img_w / W
    ars = [1.0]
    for ar in aspect_ratios:
        if all(abs(ar - a) > 1e-6 for a in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    whs = []
    for ms in min_sizes:
        whs.append((ms, ms))
        if min_max_aspect_ratios_order and max_sizes:
            mx = max_sizes[min_sizes.index(ms)]
            whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
        for ar in ars:
            if abs(ar - 1.0) < 1e-6:
                continue
            whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        if max_sizes and not min_max_aspect_ratios_order:
            mx = max_sizes[min_sizes.index(ms)]
            whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
    whs = np.asarray(whs, np.float32)  # [P, 2]
    P = len(whs)
    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)  # [H, W]
    cxg = cxg[..., None]
    cyg = cyg[..., None]
    w_half = jnp.asarray(whs[:, 0])[None, None, :] * 0.5
    h_half = jnp.asarray(whs[:, 1])[None, None, :] * 0.5
    boxes = jnp.stack([(cxg - w_half) / img_w, (cyg - h_half) / img_h,
                       (cxg + w_half) / img_w, (cyg + h_half) / img_h],
                      axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           (H, W, P, 4))
    return boxes, var


def anchor_generator(input_hw, anchor_sizes, aspect_ratios, stride,
                     variances=(0.1, 0.1, 0.2, 0.2), offset=0.5):
    """anchor_generator_op.h: RPN anchors. Returns (anchors [H,W,A,4],
    variances [H,W,A,4]); coordinates in input-image pixels."""
    jnp = _jnp()
    H, W = input_hw
    whs = []
    for ar in aspect_ratios:
        for sz in anchor_sizes:
            area = sz * sz
            w = np.sqrt(area / ar)
            whs.append((w, w * ar))
    whs = np.asarray(whs, np.float32)
    A = len(whs)
    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * stride[0]
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * stride[1]
    cxg, cyg = jnp.meshgrid(cx, cy)
    cxg, cyg = cxg[..., None], cyg[..., None]
    wh = jnp.asarray(whs) * 0.5
    anchors = jnp.stack([cxg - wh[None, None, :, 0],
                         cyg - wh[None, None, :, 1],
                         cxg + wh[None, None, :, 0],
                         cyg + wh[None, None, :, 1]], axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           (H, W, A, 4))
    return anchors, var


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0):
    """yolo_box_op.h: decode one YOLOv3 head. x [B, A*(5+C), H, W],
    img_size [B, 2] (h, w). Returns (boxes [B, H*W*A, 4],
    scores [B, H*W*A, C])."""
    import jax

    jnp = _jnp()
    B, ch, H, W = x.shape
    A = len(anchors) // 2
    C = class_num
    x = x.reshape(B, A, 5 + C, H, W)
    grid_x = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    grid_y = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    an_w = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
    an_h = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]
    input_h = downsample_ratio * H
    input_w = downsample_ratio * W
    sig = jax.nn.sigmoid
    bx = (grid_x + sig(x[:, :, 0]) * scale_x_y -
          (scale_x_y - 1) * 0.5) / W
    by = (grid_y + sig(x[:, :, 1]) * scale_x_y -
          (scale_x_y - 1) * 0.5) / H
    bw = jnp.exp(x[:, :, 2]) * an_w / input_w
    bh = jnp.exp(x[:, :, 3]) * an_h / input_h
    conf = sig(x[:, :, 4])
    probs = sig(x[:, :, 5:]) * conf[:, :, None]
    img_h = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    img_w = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    x1 = (bx - bw * 0.5) * img_w
    y1 = (by - bh * 0.5) * img_h
    x2 = (bx + bw * 0.5) * img_w
    y2 = (by + bh * 0.5) * img_h
    if clip_bbox:
        x1 = jnp.clip(x1, 0.0, img_w - 1)
        y1 = jnp.clip(y1, 0.0, img_h - 1)
        x2 = jnp.clip(x2, 0.0, img_w - 1)
        y2 = jnp.clip(y2, 0.0, img_h - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)  # [B, A, H, W, 4]
    mask = (conf > conf_thresh)[..., None]
    boxes = jnp.where(mask, boxes, 0.0)
    probs = jnp.where(mask, jnp.moveaxis(probs, 2, -1), 0.0)
    boxes = boxes.transpose(0, 2, 3, 1, 4).reshape(B, H * W * A, 4)
    scores = probs.transpose(0, 2, 3, 1, 4).reshape(B, H * W * A, C)
    return boxes, scores


def roi_align(x, rois, roi_batch_id, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=False):
    """roi_align_op: bilinear-sampled average pooling per RoI.
    x [B, C, H, W], rois [R, 4] (x1, y1, x2, y2 in input coords),
    roi_batch_id [R] int -> [R, C, ph, pw]."""
    import jax

    jnp = _jnp()
    x = jnp.asarray(x)
    B, C, H, W = x.shape
    ph, pw = (output_size, output_size) if isinstance(output_size, int) \
        else output_size
    offset = 0.5 if aligned else 0.0
    sr = sampling_ratio if sampling_ratio > 0 else 2

    def one_roi(roi, bidx):
        x1 = roi[0] * spatial_scale - offset
        y1 = roi[1] * spatial_scale - offset
        x2 = roi[2] * spatial_scale - offset
        y2 = roi[3] * spatial_scale - offset
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        bin_w = rw / pw
        bin_h = rh / ph
        # sample grid: [ph, sr] x [pw, sr]
        iy = (jnp.arange(ph)[:, None] +
              (jnp.arange(sr)[None, :] + 0.5) / sr)  # [ph, sr]
        ix = (jnp.arange(pw)[:, None] +
              (jnp.arange(sr)[None, :] + 0.5) / sr)
        sy = y1 + iy * bin_h   # [ph, sr]
        sx = x1 + ix * bin_w   # [pw, sr]
        img = x[bidx]  # [C, H, W]

        def bilinear(yy, xx):
            y0 = jnp.clip(jnp.floor(yy), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, W - 1)
            y1_ = jnp.clip(y0 + 1, 0, H - 1)
            x1_ = jnp.clip(x0 + 1, 0, W - 1)
            ly = jnp.clip(yy - y0, 0.0, 1.0)
            lx = jnp.clip(xx - x0, 0.0, 1.0)
            y0i, x0i = y0.astype(jnp.int32), x0.astype(jnp.int32)
            y1i, x1i = y1_.astype(jnp.int32), x1_.astype(jnp.int32)
            v00 = img[:, y0i, x0i]
            v01 = img[:, y0i, x1i]
            v10 = img[:, y1i, x0i]
            v11 = img[:, y1i, x1i]
            return (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx +
                    v10 * ly * (1 - lx) + v11 * ly * lx)

        yy = sy[:, None, :, None]          # [ph, 1, sr, 1]
        xx = sx[None, :, None, :]          # [1, pw, 1, sr]
        yy = jnp.broadcast_to(yy, (ph, pw, sr, sr))
        xx = jnp.broadcast_to(xx, (ph, pw, sr, sr))
        vals = bilinear(yy.reshape(-1), xx.reshape(-1))  # [C, ph*pw*sr*sr]
        vals = vals.reshape(C, ph, pw, sr, sr)
        return vals.mean(axis=(3, 4))

    return jax.vmap(one_roi)(rois.astype(jnp.float32),
                             roi_batch_id.astype(jnp.int32))


def roi_pool(x, rois, roi_batch_id, output_size, spatial_scale=1.0):
    """roi_pool_op: max pooling per RoI bin (quantized boundaries)."""
    import jax

    jnp = _jnp()
    x = jnp.asarray(x)
    B, C, H, W = x.shape
    ph, pw = (output_size, output_size) if isinstance(output_size, int) \
        else output_size

    def one_roi(roi, bidx):
        x1 = jnp.round(roi[0] * spatial_scale)
        y1 = jnp.round(roi[1] * spatial_scale)
        x2 = jnp.round(roi[2] * spatial_scale)
        y2 = jnp.round(roi[3] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        img = x[bidx]
        ys = jnp.arange(H, dtype=jnp.float32)
        xs = jnp.arange(W, dtype=jnp.float32)
        # bin index of each pixel relative to the roi, or -1 outside
        by = jnp.floor((ys - y1) / (rh / ph))
        bx = jnp.floor((xs - x1) / (rw / pw))
        by = jnp.where((ys >= y1) & (ys <= y2), jnp.clip(by, 0, ph - 1),
                       -1)
        bx = jnp.where((xs >= x1) & (xs <= x2), jnp.clip(bx, 0, pw - 1),
                       -1)
        out = jnp.full((C, ph, pw), -jnp.inf, x.dtype)
        onehot_y = (by[None, :] ==
                    jnp.arange(ph, dtype=jnp.float32)[:, None])
        onehot_x = (bx[None, :] ==
                    jnp.arange(pw, dtype=jnp.float32)[:, None])
        # [ph, H] x [pw, W]: max over the masked pixels per bin
        masked = jnp.where(
            onehot_y[None, :, None, :, None] &
            onehot_x[None, None, :, None, :],
            img[:, None, None, :, :], -jnp.inf)
        out = masked.max(axis=(3, 4))
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return jax.vmap(one_roi)(rois.astype(jnp.float32),
                             roi_batch_id.astype(jnp.int32))


def bipartite_match(dist):
    """bipartite_match_op.cc greedy max matching: dist [N, M] ->
    (match_indices [M] int32 with -1 for unmatched, match_dist [M])."""
    import jax

    jnp = _jnp()
    N, M = dist.shape

    def body(_, carry):
        d, match_idx, match_d = carry
        flat = jnp.argmax(d)
        i, j = flat // M, flat % M
        v = d[i, j]
        ok = v > 0
        match_idx = match_idx.at[j].set(
            jnp.where(ok, i.astype(jnp.int32), match_idx[j]))
        match_d = match_d.at[j].set(jnp.where(ok, v, match_d[j]))
        d = jnp.where(ok, d.at[i, :].set(-1.0).at[:, j].set(-1.0), d)
        return d, match_idx, match_d

    init = (dist.astype(jnp.float32),
            jnp.full((M,), -1, jnp.int32), jnp.zeros((M,), jnp.float32))
    _, match_idx, match_d = jax.lax.fori_loop(
        0, min(N, M), body, init)
    return match_idx, match_d


def density_prior_box(input_hw, image_hw, fixed_sizes, fixed_ratios,
                      densities, variances=(0.1, 0.1, 0.2, 0.2),
                      steps=(0.0, 0.0), offset=0.5, clip=False):
    """density_prior_box_op.h: dense-grid SSD priors."""
    jnp = _jnp()
    H, W = input_hw
    img_h, img_w = image_hw
    step_h = steps[0] or img_h / H
    step_w = steps[1] or img_w / W
    whs = []
    shifts = []
    for size, density in zip(fixed_sizes, densities):
        for ratio in fixed_ratios:
            w = size * np.sqrt(ratio)
            h = size / np.sqrt(ratio)
            step = 1.0 / density
            for di in range(density):
                for dj in range(density):
                    whs.append((w, h))
                    shifts.append((
                        (dj + 0.5) * step - 0.5,
                        (di + 0.5) * step - 0.5))
    whs = np.asarray(whs, np.float32)
    shifts = np.asarray(shifts, np.float32)
    P = len(whs)
    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)
    cxg = cxg[..., None] + jnp.asarray(shifts[:, 0]) * step_w
    cyg = cyg[..., None] + jnp.asarray(shifts[:, 1]) * step_h
    wh = jnp.asarray(whs) * 0.5
    boxes = jnp.stack([(cxg - wh[None, None, :, 0]) / img_w,
                       (cyg - wh[None, None, :, 1]) / img_h,
                       (cxg + wh[None, None, :, 0]) / img_w,
                       (cyg + wh[None, None, :, 1]) / img_h], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           (H, W, P, 4))
    return boxes, var
