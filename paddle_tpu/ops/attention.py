"""Attention kernels: XLA composition + (on TPU) a Pallas flash-attention
kernel. Reference parity: the fused multihead attention of
operators/fused/multihead_matmul_op.* and math/bert_encoder_functor.cu —
re-designed TPU-first as a blockwise online-softmax kernel (flash attention)
instead of a translated CUDA kernel.

Layout: (batch, heads, seq, head_dim) throughout.
"""
from __future__ import annotations

import functools
import math


def _jnp():
    import jax.numpy as jnp

    return jnp


def sdpa_reference(q, k, v, mask=None, is_causal=False, scale=None):
    """Plain XLA attention: always correct, runs anywhere, XLA fuses it."""
    import jax
    import jax.numpy as jnp

    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("...qd,...kd->...qk", q, k) * s
    if is_causal:
        qlen, klen = logits.shape[-2], logits.shape[-1]
        cmask = jnp.tril(jnp.ones((qlen, klen), bool), klen - qlen)
        logits = jnp.where(cmask, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("...qk,...kd->...qd", probs.astype(q.dtype), v)


def _on_tpu() -> bool:
    import jax

    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


def flash_attention_tpu(q, k, v, is_causal=False, scale=None,
                        block_q=256, block_k=256):
    """Pallas blockwise flash attention (forward) for TPU.

    Grid over (batch*heads, q blocks); the k loop runs inside the kernel with
    online softmax in fp32 accumulators (VMEM-resident blocks, MXU matmuls).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    b, h, sq, d = q.shape
    sk = k.shape[2]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        return sdpa_reference(q, k, v, None, is_causal, scale)

    qr = q.reshape(b * h, sq, d)
    kr = k.reshape(b * h, sk, d)
    vr = v.reshape(b * h, sk, d)
    nq = sq // block_q
    nk = sk // block_k

    def kernel(q_ref, k_ref, v_ref, o_ref):
        qi = pl.program_id(1)
        qb = q_ref[...].astype(jnp.float32) * s

        def body(ki, carry):
            acc, m_prev, l_prev = carry
            kb = pl.load(k_ref, (pl.ds(ki * block_k, block_k),
                                 slice(None))).astype(jnp.float32)
            vb = pl.load(v_ref, (pl.ds(ki * block_k, block_k),
                                 slice(None))).astype(jnp.float32)
            logits = jnp.dot(qb, kb.T,
                             preferred_element_type=jnp.float32)
            if is_causal:
                rows = qi * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                cols = ki * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                logits = jnp.where(rows >= cols, logits, -1e30)
            m_cur = jnp.maximum(m_prev, logits.max(axis=-1, keepdims=True))
            alpha = jnp.exp(m_prev - m_cur)
            p = jnp.exp(logits - m_cur)
            l_cur = l_prev * alpha + p.sum(axis=-1, keepdims=True)
            acc = acc * alpha + jnp.dot(p, vb,
                                        preferred_element_type=jnp.float32)
            return acc, m_cur, l_cur

        acc0 = jnp.zeros((block_q, d), jnp.float32)
        m0 = jnp.full((block_q, 1), -1e30, jnp.float32)
        l0 = jnp.zeros((block_q, 1), jnp.float32)
        if is_causal:
            # only blocks up to and including the diagonal contribute
            k_hi = (qi + 1) * block_q
            nk_eff = (k_hi + block_k - 1) // block_k
        else:
            nk_eff = nk
        acc, m_f, l_f = jax.lax.fori_loop(0, nk_eff, body, (acc0, m0, l0))
        o_ref[...] = (acc / jnp.maximum(l_f, 1e-30)).astype(o_ref.dtype)

    out = pl.pallas_call(
        kernel,
        grid=(b * h, nq),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, sk, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, sk, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
    )(qr, kr, vr)
    return out.reshape(b, h, sq, d)


def sdpa(q, k, v, mask=None, is_causal=False, scale=None):
    """Dispatch: pallas flash kernel on TPU for mask-free/causal attention,
    XLA reference otherwise. Differentiable (flash path uses custom VJP via
    recompute through the reference — cheap under remat)."""
    if mask is None and _on_tpu() and q.ndim == 4 and q.shape[-1] <= 256:
        try:
            return _flash_diff(q, k, v, is_causal, scale)
        except Exception:
            pass
    return sdpa_reference(q, k, v, mask, is_causal, scale)


def _flash_diff(q, k, v, is_causal, scale):
    import jax

    @jax.custom_vjp
    def f(q, k, v):
        return flash_attention_tpu(q, k, v, is_causal, scale)

    def fwd(q, k, v):
        return f(q, k, v), (q, k, v)

    def bwd(res, g):
        q, k, v = res
        _, vjp = jax.vjp(
            lambda a, b, c: sdpa_reference(a, b, c, None, is_causal, scale),
            q, k, v)
        return vjp(g)

    f.defvjp(fwd, bwd)
    return f(q, k, v)
